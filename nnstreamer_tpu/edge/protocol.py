"""Length-prefixed tensor message framing over TCP.

≙ nnstreamer-edge's nns_edge_data_* wire format (serialize per-frame
tensor payloads + metadata, SURVEY.md §5 distributed backend). A message
is::

    magic   u32  0x4E4E5445 ("NNTE")
    kind    u8   MsgKind
    meta    u32 len + utf-8 JSON (caps/client_id/pts/shapes/dtypes)
    n       u32  payload count
    n x (u64 len + bytes)

Tensor payloads ride as raw bytes; dtypes/shapes live in the JSON meta so
flexible streams need no renegotiation.
"""
from __future__ import annotations

import enum
import json
import socket
import struct
from typing import Dict, List, Sequence, Tuple

import numpy as np

MAGIC = 0x4E4E5445
_HDR = struct.Struct("<IBI")
_PLEN = struct.Struct("<Q")


class MsgKind(enum.IntEnum):
    CAPS = 1        # caps string exchange at connect
    CAPS_ACK = 2
    DATA = 3        # client -> server frame
    RESULT = 4      # server -> client frame
    EOS = 5
    ERROR = 6
    SUBSCRIBE = 7   # edgesrc -> edgesink hello
    REGISTER = 8    # server -> broker: advertise topic at host:port
    QUERY = 9       # client -> broker: who serves this topic?
    QUERY_ACK = 10  # broker -> client: endpoint list
    PUBLISH = 11    # publisher -> message broker: topic payload
    SHED = 12       # server -> client: request dropped (admission or
                    # deadline); meta carries retry_after_ms + seq


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ConnectionError("peer closed")
        buf.extend(part)
    return bytes(buf)


def send_msg(sock: socket.socket, kind: MsgKind, meta: Dict,
             payloads: Sequence[bytes] = ()) -> None:
    mb = json.dumps(meta).encode()
    parts = [_HDR.pack(MAGIC, int(kind), len(mb)), mb,
             struct.pack("<I", len(payloads))]
    for p in payloads:
        parts.append(_PLEN.pack(len(p)))
        parts.append(p)
    sock.sendall(b"".join(parts))


def recv_msg(sock: socket.socket) -> Tuple[MsgKind, Dict, List[bytes]]:
    magic, kind, mlen = _HDR.unpack(_read_exact(sock, _HDR.size))
    if magic != MAGIC:
        raise ValueError(f"bad magic {magic:#x}")
    meta = json.loads(_read_exact(sock, mlen)) if mlen else {}
    (n,) = struct.unpack("<I", _read_exact(sock, 4))
    payloads = []
    for _ in range(n):
        (plen,) = _PLEN.unpack(_read_exact(sock, _PLEN.size))
        payloads.append(_read_exact(sock, plen))
    return MsgKind(kind), meta, payloads


def buffer_to_wire(buf) -> Tuple[Dict, List[bytes]]:
    """Buffer -> (meta, payloads); dtype/shape per chunk in meta."""
    tensors = []
    payloads = []
    for c in buf.chunks:
        arr = c.host()
        tensors.append({"dtype": str(arr.dtype), "shape": list(arr.shape)})
        payloads.append(arr.tobytes())
    meta = {"pts": buf.pts, "duration": buf.duration, "tensors": tensors}
    return meta, payloads


def wire_to_buffer(meta: Dict, payloads: List[bytes]):
    from ..tensors.buffer import Buffer, Chunk
    chunks = []
    for t, p in zip(meta.get("tensors", []), payloads):
        arr = np.frombuffer(p, np.dtype(t["dtype"])).reshape(t["shape"])
        chunks.append(Chunk(arr))
    return Buffer(chunks, pts=meta.get("pts"), duration=meta.get("duration"))
