"""Length-prefixed tensor message framing over TCP.

≙ nnstreamer-edge's nns_edge_data_* wire format (serialize per-frame
tensor payloads + metadata, SURVEY.md §5 distributed backend). A message
is::

    magic   u32  0x4E4E5445 ("NNTE")
    kind    u8   MsgKind
    meta    u32 len + utf-8 JSON (caps/client_id/pts/shapes/dtypes)
    n       u32  payload count
    n x (u64 len + bytes)

Tensor payloads ride as raw bytes; dtypes/shapes live in the JSON meta so
flexible streams need no renegotiation.

The framing above is wire v1 and is what every message still looks like
on the outside. What changed underneath (wire v2, see ``wire.py`` and
Documentation/edge.md):

* **send** is vectored: ``send_msg`` accepts ndarrays / memoryviews and
  hands the header + payload views to ``socket.sendmsg`` scatter-gather,
  so tensor bytes go from the array to the kernel without ``tobytes()``
  or a ``b"".join`` staging copy.
* **recv** is zero-copy: ``recv_msg`` preallocates the destination —
  the exact ndarray described by ``meta["tensors"]`` when the payload is
  raw, a ``bytearray`` otherwise — and fills it with ``recv_into``.
  Either way the payload memory is writable and lands once.
* Negotiated extras (codecs, dtype downcast, DATA_BATCH coalescing) are
  layered on top by ``wire.py`` and only ever used on links where both
  peers advertised them; a v1 peer sees byte-identical traffic.
"""
from __future__ import annotations

import enum
import json
import socket
import struct
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

MAGIC = 0x4E4E5445
_HDR = struct.Struct("<IBI")
_PLEN = struct.Struct("<Q")

# Guards on attacker/corruption-controlled lengths: reject before
# allocating. 4 GB per tensor payload (the u64 length path must not let
# a flipped bit demand an exabyte), 64 MB of JSON meta.
MAX_PAYLOAD = 1 << 32
MAX_META = 1 << 26

# sendmsg scatter-gather is POSIX; cap the iovec count per call well
# under any realistic IOV_MAX (Linux: 1024).
_HAS_SENDMSG = hasattr(socket.socket, "sendmsg")
_IOV_BATCH = 64

Payload = Union[bytes, bytearray, memoryview, np.ndarray]


class MsgKind(enum.IntEnum):
    CAPS = 1        # caps string exchange at connect
    CAPS_ACK = 2
    DATA = 3        # client -> server frame
    RESULT = 4      # server -> client frame
    EOS = 5
    ERROR = 6
    SUBSCRIBE = 7   # edgesrc -> edgesink hello
    REGISTER = 8    # server -> broker: advertise topic at host:port
    QUERY = 9       # client -> broker: who serves this topic?
    QUERY_ACK = 10  # broker -> client: endpoint list
    PUBLISH = 11    # publisher -> message broker: topic payload
    SHED = 12       # server -> client: request dropped (admission or
                    # deadline); meta carries retry_after_ms + seq
    DATA_BATCH = 13  # N coalesced DATA frames in one message (wire v2
                     # only: meta template + per-frame binary header)
    # session layer (edge/session.py) — only ever sent on links that
    # negotiated a session at CAPS/SUBSCRIBE; a v1 peer never sees them
    ACK = 14        # receiver -> sender: cumulative delivery watermark
    RESUME = 15     # reconnecting receiver: {sid, last delivered seq}
    RESUME_ACK = 16  # sender's answer: {resumed, frames_lost, base}
    PING = 17       # liveness probe across an idle link
    PONG = 18       # echo of the PING's timestamp
    DRAIN = 19      # graceful teardown: admission is closing; in-flight
                    # frames flush + settle before the peer goes away
    KV_XFER = 20    # prefill -> decode replica: a stream's prompt KV
                    # blocks + last logits (edge/kv.py; wire-v2
                    # precision negotiated at CAPS like any tensor link)
    KV_ACK = 21     # decode replica's admission receipt ({sid, adopted})


def resolve_dtype(name: str) -> np.dtype:
    """dtype-by-name, including the ml_dtypes extras (bfloat16) that
    ``np.dtype`` alone does not know."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # ships with jax; never an extra dependency
        return np.dtype(getattr(ml_dtypes, name))


def byte_view(arr: np.ndarray) -> Optional[memoryview]:
    """A flat writable-agnostic byte view of ``arr``, or None when the
    dtype defeats the buffer protocol (e.g. bfloat16 on some numpy
    versions) and the caller must fall back to a copy."""
    try:
        return memoryview(arr).cast("B")
    except (TypeError, ValueError, NotImplementedError):
        try:
            return memoryview(arr.view(np.uint8).reshape(-1))
        except (TypeError, ValueError):
            return None


def as_payload_view(p: Payload) -> Union[bytes, memoryview]:
    """Normalize one payload to something len()-able and sendable."""
    if isinstance(p, np.ndarray):
        if p.size and not p.flags.c_contiguous:
            p = np.ascontiguousarray(p)
        v = byte_view(p)
        return v if v is not None else p.tobytes()
    if isinstance(p, (bytearray, memoryview)):
        return memoryview(p).cast("B")
    return p


def sever_socket(sock: Optional[socket.socket]) -> None:
    """Force-close a live socket so BOTH ends notice immediately.
    shutdown() must precede close(): a thread blocked in recv() on this
    socket holds a kernel reference, so a bare close() would neither
    wake it nor send FIN — the peer's select() would wait forever on a
    connection that is dead only in name."""
    if sock is None:
        return
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def _recv_exact_into(sock: socket.socket, view: memoryview) -> None:
    got, n = 0, len(view)
    while got < n:
        r = sock.recv_into(view[got:])
        if not r:
            raise ConnectionError("peer closed")
        got += r


def _read_exact(sock: socket.socket, n: int) -> bytearray:
    # one allocation, filled in place (the old version grew a bytearray
    # through repeated recv()+extend copies)
    buf = bytearray(n)
    if n:
        _recv_exact_into(sock, memoryview(buf))
    return buf


def _sendmsg_all(sock: socket.socket, parts: List[Union[bytes, memoryview]]
                 ) -> None:
    """sendall() semantics over a scatter-gather list, resuming cleanly
    after partial sends; falls back to join+sendall without sendmsg."""
    if not _HAS_SENDMSG:
        sock.sendall(b"".join(parts))
        return
    pending = [memoryview(p) for p in parts if len(p)]
    while pending:
        sent = sock.sendmsg(pending[:_IOV_BATCH])
        while sent:
            if sent >= len(pending[0]):
                sent -= len(pending.pop(0))
            else:
                pending[0] = pending[0][sent:]
                sent = 0


def send_msg(sock: socket.socket, kind: MsgKind, meta: Dict,
             payloads: Sequence[Payload] = (), stats=None) -> int:
    """Frame + send one message; returns bytes put on the wire.

    Payloads may be bytes, bytearray, memoryview, or ndarray — ndarrays
    are sent straight from their backing memory (made contiguous only
    when they are not).
    """
    mb = json.dumps(meta).encode()
    parts: List[Union[bytes, memoryview]] = [
        _HDR.pack(MAGIC, int(kind), len(mb)), mb,
        struct.pack("<I", len(payloads))]
    total = _HDR.size + len(mb) + 4
    for p in payloads:
        v = as_payload_view(p)
        parts.append(_PLEN.pack(len(v)))
        total += _PLEN.size + len(v)
        if len(v):
            parts.append(v)
    _sendmsg_all(sock, parts)
    if stats is not None:
        stats.add(wire_bytes_out=total, wire_msgs_out=1)
    return total


def _preallocate(meta: Dict, n: int) -> Optional[List[Optional[np.ndarray]]]:
    """Per-payload destination ndarrays when meta fully describes raw
    tensors, else None (caller falls back to bytearray — still writable,
    still filled by recv_into)."""
    tensors = meta.get("tensors")
    if not isinstance(tensors, list) or len(tensors) != n:
        return None
    out: List[Optional[np.ndarray]] = []
    for t in tensors:
        if not isinstance(t, dict) or "codec" in t or "wire_dtype" in t:
            out.append(None)
            continue
        try:
            out.append(np.empty(tuple(t["shape"]), resolve_dtype(t["dtype"])))
        except Exception:
            out.append(None)
    return out


def recv_msg(sock: socket.socket, stats=None
             ) -> Tuple[MsgKind, Dict, List[Payload]]:
    """Receive one message. Raw tensor payloads land directly in freshly
    allocated ndarrays (writable, zero extra copies); anything else
    (control frames, encoded payloads) comes back as a bytearray."""
    magic, kind, mlen = _HDR.unpack(_read_exact(sock, _HDR.size))
    if magic != MAGIC:
        raise ValueError(f"bad magic {magic:#x}")
    if mlen > MAX_META:
        raise ValueError(f"meta length {mlen} exceeds {MAX_META} guard")
    meta = json.loads(bytes(_read_exact(sock, mlen))) if mlen else {}
    (n,) = struct.unpack("<I", _read_exact(sock, 4))
    dests = _preallocate(meta, n) if n else None
    total = _HDR.size + mlen + 4
    payloads: List[Payload] = []
    for i in range(n):
        (plen,) = _PLEN.unpack(_read_exact(sock, _PLEN.size))
        if plen > MAX_PAYLOAD:
            raise ValueError(
                f"payload {i} length {plen} exceeds {MAX_PAYLOAD} guard")
        total += _PLEN.size + plen
        arr = dests[i] if dests is not None else None
        view = byte_view(arr) if arr is not None else None
        if view is not None and len(view) == plen:
            _recv_exact_into(sock, view)
            payloads.append(arr)
        else:
            payloads.append(_read_exact(sock, plen))
    if stats is not None:
        stats.add(wire_bytes_in=total, wire_msgs_in=1)
    return MsgKind(kind), meta, payloads


def buffer_to_wire(buf) -> Tuple[Dict, List[Payload]]:
    """Buffer -> (meta, payloads); dtype/shape per chunk in meta.

    Payloads are memoryviews over the chunk arrays (no copy) whenever
    the buffer protocol allows; ``send_msg`` consumes them as-is. This
    is the plain/v1 path — negotiated codecs live in ``wire.py``.
    """
    tensors = []
    payloads: List[Payload] = []
    for c in buf.chunks:
        arr = np.asarray(c.host())
        if arr.size and not arr.flags.c_contiguous:
            arr = np.ascontiguousarray(arr)
        tensors.append({"dtype": str(arr.dtype), "shape": list(arr.shape)})
        payloads.append(arr)
    meta = {"pts": buf.pts, "duration": buf.duration, "tensors": tensors}
    return meta, payloads


def wire_to_buffer(meta: Dict, payloads: Sequence[Payload]):
    """(meta, payloads) -> Buffer with WRITABLE chunk arrays.

    ``recv_msg`` already delivers shaped ndarrays for raw tensors (zero
    copy); bytearray payloads wrap writably in place; a read-only
    ``bytes`` payload (v1 peers, tests) is copied once — downstream
    in-place transforms must never trip on a read-only chunk.
    """
    from ..tensors.buffer import Buffer, Chunk
    chunks = []
    for t, p in zip(meta.get("tensors", []), payloads):
        dtype = resolve_dtype(t["dtype"])
        shape = tuple(t["shape"])
        if isinstance(p, np.ndarray) and p.dtype == dtype and \
                p.shape == shape and p.flags.writeable:
            arr = p
        else:
            raw = p.tobytes() if isinstance(p, np.ndarray) else p
            arr = np.frombuffer(raw, dtype).reshape(shape)
            if not arr.flags.writeable:
                arr = arr.copy()
        chunks.append(Chunk(arr))
    return Buffer(chunks, pts=meta.get("pts"), duration=meta.get("duration"))
