"""SNTP client: universal-time source for cross-device base-time sync.

≙ gst/mqtt/ntputil.c — the reference queries configured NTP servers
(default pool.ntp.org:123) so that every device stamps its pipeline
base-time against the same clock before embedding it in MQTT headers
(mqttsink.c:89, Documentation/synchronization-in-mqtt-elements.md).

Implements a plain SNTPv4 exchange over UDP: 48-byte request with the
client transmit timestamp, server reply carrying its receive/transmit
timestamps; the offset estimate is the standard
((t1 - t0) + (t2 - t3)) / 2.
"""
from __future__ import annotations

import socket
import struct
import time
from typing import List, Optional, Tuple

from ..utils.log import logger

# seconds between the NTP epoch (1900) and the Unix epoch (1970)
_NTP_DELTA = 2208988800


def _to_ntp(unix_s: float) -> Tuple[int, int]:
    secs = int(unix_s) + _NTP_DELTA
    frac = int((unix_s % 1.0) * (1 << 32))
    return secs, frac


def _from_ntp(secs: int, frac: int) -> float:
    return secs - _NTP_DELTA + frac / (1 << 32)


def query_offset(host: str, port: int = 123,
                 timeout: float = 2.0) -> float:
    """One SNTP exchange; returns the estimated clock offset in seconds
    (add to local unix time to get server time)."""
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        s.settimeout(timeout)
        t0 = time.time()
        pkt = bytearray(48)
        pkt[0] = (0 << 6) | (4 << 3) | 3   # LI=0, VN=4, mode=3 (client)
        pkt[40:48] = struct.pack("!II", *_to_ntp(t0))
        s.sendto(bytes(pkt), (host, port))
        data, _ = s.recvfrom(512)
        t3 = time.time()
    if len(data) < 48:
        raise ValueError("short NTP reply")
    t1 = _from_ntp(*struct.unpack("!II", data[32:40]))  # server receive
    t2 = _from_ntp(*struct.unpack("!II", data[40:48]))  # server transmit
    return ((t1 - t0) + (t2 - t3)) / 2.0


def best_offset(servers: str, timeout: float = 2.0) -> float:
    """Try ``host[:port],host[:port],...`` in order; first success wins
    (≙ ntputil.c walking mqtt-ntp-srvs). Returns 0.0 when none answer —
    falling back to the local clock like the reference's non-sync mode."""
    for srv in (s.strip() for s in (servers or "").split(",")):
        if not srv:
            continue
        host, _, port = srv.partition(":")
        try:
            off = query_offset(host, int(port or 123), timeout)
            logger.info("ntp: offset %+.6fs from %s", off, srv)
            return off
        except (OSError, ValueError) as e:
            logger.warning("ntp: %s unreachable (%s)", srv, e)
    return 0.0


def synced_epoch_ns(servers: Optional[str], timeout: float = 2.0) -> int:
    """Universal 'now' in ns: local clock plus NTP offset when servers
    are configured, local clock otherwise."""
    off = best_offset(servers, timeout) if servers else 0.0
    return time.time_ns() + int(off * 1e9)
