"""Among-device streaming: the nnstreamer-edge slot.

≙ the external nnstreamer-edge library (TCP / MQTT-hybrid) that backs
tensor_query_* and edgesrc/edgesink in the reference (SURVEY.md §2.4).
Here the control+data plane is a length-prefixed TCP protocol (DCN-side);
in-pod scale-out instead uses jax.sharding over ICI (parallel/).
"""
from .broker import DiscoveryBroker, discover, discover_meta
from .mqtt import MqttBroker
from .protocol import MsgKind, recv_msg, send_msg
from .session import (Heartbeat, ReplayRing, SessionConfig, SessionReceiver,
                      new_session_id)
from .wire import WireConfig, accept, advertise, negotiate, tune_socket

__all__ = ["MsgKind", "send_msg", "recv_msg", "DiscoveryBroker", "discover",
           "discover_meta",
           "MqttBroker", "WireConfig", "advertise", "negotiate", "accept",
           "tune_socket", "SessionConfig", "SessionReceiver", "ReplayRing",
           "Heartbeat", "new_session_id"]
