"""KV-block handoff between disaggregated LLM replicas (KV_XFER/KV_ACK).

A prefill-role replica runs only the prompt pass; the resulting KV
prefix ([L, T, H, Dh] per tensor, plus the last-position logits the
decode loop samples first) must land inside a decode-role replica's
continuous-batching pool. This module is that link:

* **framing** — one KV_XFER message per stream: JSON meta (stream id =
  the prompt's ``token_sha`` digest, the prompt itself for
  prefix-cache commit and snapshot re-adoption, remaining budget,
  sampling seed, any already-emitted tokens when re-shipping after a
  crash) + the K/V/logits payloads encoded through the SAME
  ``_encode_tensor`` path as DATA frames, so the wire-v2 precision
  downcast (bf16/fp16) and adaptive compression apply unchanged;
* **negotiation** — the sender opens with CAPS carrying a standard
  ``wire.advertise`` block and adopts the receiver's CAPS_ACK echo,
  exactly like the trace field: an old peer that never learned
  KV_XFER simply never negotiates one of these links, and nothing on
  existing links changes byte-wise;
* **tracing** — when both ends advertised tracing, meta carries the
  frame-trace context and the receiver records a ``kv-handoff`` span
  parented on the sender's prefill span, so ``top`` shows
  prefill -> handoff -> decode as one connected tree per conversation.

The transport is deliberately dumb (one request, one ack, blocking):
handoffs are per-conversation control traffic, not the per-frame hot
path, and the ack doubles as admission backpressure — a decode
replica that cannot allocate pool blocks answers ``adopted=False``
and the prefill side can retry elsewhere.
"""
from __future__ import annotations

import logging
import socket
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..obs import context as _obs_ctx
from ..obs import spans as _obs_spans
from . import wire
from .listener import TcpListener
from .protocol import MsgKind, recv_msg, send_msg

logger = logging.getLogger(__name__)


def pack_kv(sid: str, prompt, k, v, logits, *, remaining: int, seed: int,
            emitted=(), cfg: Optional[wire.WireConfig] = None,
            ctx=None):
    """-> (meta, payloads) for one KV_XFER message."""
    metas: List[Dict] = []
    payloads: List = []
    codes: List[int] = []
    for arr in (k, v, logits):
        p, t, _, code = wire._encode_tensor(np.asarray(arr), cfg)
        metas.append(t)
        payloads.append(p)
        codes.append(code)
    meta = {"sid": str(sid),
            "prompt": [int(t) for t in np.asarray(prompt).ravel()],
            "emitted": [int(t) for t in emitted],
            "remaining": int(remaining), "seed": int(seed),
            "tensors": metas, "enc": codes}
    if cfg is not None and cfg.trace and ctx is not None:
        meta["trace"] = _obs_ctx.to_wire(ctx)
    return meta, payloads


def unpack_kv(meta: Dict, payloads) -> Dict:
    """KV_XFER meta+payloads -> handoff dict (k/v/logits as host
    ndarrays, upcast back to their declared dtype when the link
    downcast them). The receiver records the wire-hop span here so the
    trace tree connects across the replica hop."""
    codes = meta.get("enc") or [None] * len(payloads)
    arrs = [wire._decode_tensor(t, p, c) for t, p, c in
            zip(meta["tensors"], payloads, codes)]
    out = {"sid": str(meta.get("sid", "")),
           "prompt": np.asarray(meta.get("prompt", ()), np.int32),
           "emitted": [int(t) for t in meta.get("emitted", ())],
           "remaining": int(meta.get("remaining", 0)),
           "seed": int(meta.get("seed", 0)),
           "k": arrs[0], "v": arrs[1], "logits": arrs[2], "ctx": None}
    got = _obs_ctx.from_wire(meta.get("trace"))
    if got is not None:
        ctx, t_send = got
        now = time.time_ns()
        dur = max(0, now - t_send)
        _obs_spans.record_span("kv-handoff", "wire", t_send, dur, ctx)
        ctx.w_ns += dur
        out["ctx"] = ctx
    return out


class KvSender:
    """Prefill side: one persistent negotiated link to a decode
    replica's KvReceiver. ``send`` blocks for the KV_ACK (handoffs are
    control-plane, and the ack is the admission signal)."""

    def __init__(self, host: str, port: int, *, codec: str = "raw",
                 precision: str = "none", timeout: float = 10.0,
                 stats=None):
        self.host, self.port = host, int(port)
        self.codec, self.precision = codec, precision
        self.timeout = timeout
        self.stats = stats
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self.cfg: Optional[wire.WireConfig] = None

    def _connect_locked(self) -> None:
        if self._sock is not None:
            return
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.timeout)
        wire.tune_socket(sock)
        send_msg(sock, MsgKind.CAPS,
                 {"kv": 1,
                  "wire": wire.advertise(self.codec, self.precision)},
                 stats=self.stats)
        kind, meta, _ = recv_msg(sock, stats=self.stats)
        if kind != MsgKind.CAPS_ACK:
            sock.close()
            raise ConnectionError(f"kv handshake got {kind!r}")
        self.cfg = wire.accept(meta.get("wire"))
        self._sock = sock

    def send(self, sid: str, prompt, k, v, logits, *, remaining: int,
             seed: int, emitted=(), ctx=None) -> Dict:
        """Ship one stream; returns the KV_ACK meta ({"sid", "adopted"}).
        A transport error tears the link down (next send reconnects)
        and re-raises for the caller's failover accounting."""
        with self._lock:
            self._connect_locked()
            try:
                meta, payloads = pack_kv(
                    sid, prompt, k, v, logits, remaining=remaining,
                    seed=seed, emitted=emitted, cfg=self.cfg, ctx=ctx)
                send_msg(self._sock, MsgKind.KV_XFER, meta, payloads,
                         stats=self.stats)
                # racecheck: ok(deliberate: _lock is a LEAF serializing the one link; the blocking ack IS the admission signal, bounded by the socket timeout)
                kind, ack, _ = recv_msg(self._sock, stats=self.stats)
            except (ConnectionError, OSError, ValueError):
                self.close_locked()
                raise
            if kind != MsgKind.KV_ACK:
                self.close_locked()
                raise ConnectionError(f"kv xfer got {kind!r}")
            return ack

    def close_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
            self.cfg = None

    def close(self) -> None:
        with self._lock:
            self.close_locked()


class KvReceiver:
    """Decode side: accept KV_XFER streams and hand each decoded
    handoff dict to ``on_kv`` (called on the per-connection listener
    thread; it returns truthy iff the stream was admitted, which
    becomes the ack's ``adopted`` flag)."""

    def __init__(self, host: str, port: int,
                 on_kv: Callable[[Dict], bool], *, codec: str = "raw",
                 precision: str = "none", name: str = "kv-rx",
                 stats=None):
        self._on_kv = on_kv
        self.codec, self.precision = codec, precision
        self.stats = stats
        self._listener = TcpListener(host, port, self._conn_loop,
                                     name=name)

    @property
    def bound_port(self) -> int:
        return self._listener.bound_port

    def start(self) -> "KvReceiver":
        self._listener.start()
        return self

    def stop(self) -> None:
        self._listener.stop()

    def _conn_loop(self, conn: socket.socket) -> None:
        wire.tune_socket(conn)
        try:
            while not self._listener.stop_evt.is_set():
                kind, meta, payloads = recv_msg(conn, stats=self.stats)
                if kind == MsgKind.CAPS:
                    cfg = wire.negotiate(meta.get("wire"), self.codec,
                                         self.precision)
                    ack: Dict = {"kv": 1}
                    if cfg is not None:
                        ack["wire"] = cfg.to_meta()
                    send_msg(conn, MsgKind.CAPS_ACK, ack,
                             stats=self.stats)
                elif kind == MsgKind.KV_XFER:
                    d = unpack_kv(meta, payloads)
                    try:
                        adopted = bool(self._on_kv(d))
                    except Exception:  # noqa: BLE001 — a bad stream must not kill the link
                        logger.exception("kv-rx: on_kv failed for %s",
                                         d.get("sid"))
                        adopted = False
                    send_msg(conn, MsgKind.KV_ACK,
                             {"sid": d["sid"], "adopted": adopted},
                             stats=self.stats)
                elif kind == MsgKind.EOS:
                    break
        except (ConnectionError, OSError, ValueError) as exc:
            logger.info("kv-rx: connection ended: %r", exc)
        finally:
            try:
                conn.close()
            except OSError:
                pass
