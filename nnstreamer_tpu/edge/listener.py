"""Shared TCP listener scaffolding for the edge-layer servers.

One implementation of the bind/SO_REUSEADDR/listen/accept-thread/close
pattern used by the discovery broker, the MQTT-style message broker,
and the gRPC bridge endpoints — so fixes to the accept/shutdown
behavior land everywhere at once.
"""
from __future__ import annotations

import socket
import threading
from typing import Callable, Optional


class TcpListener:
    """Owns a listening socket and an accept thread; calls ``on_conn``
    (from a fresh daemon thread per connection) for every client."""

    def __init__(self, host: str, port: int,
                 on_conn: Callable[[socket.socket], None],
                 name: str = "tcp-listener", backlog: int = 32,
                 spawn_thread: bool = True):
        self.host, self.port = host, int(port)
        self._on_conn = on_conn
        self._name = name
        self._backlog = backlog
        self._spawn = spawn_thread
        self._sock: Optional[socket.socket] = None
        self.stop_evt = threading.Event()

    @property
    def bound_port(self) -> int:
        return self._sock.getsockname()[1] if self._sock else self.port

    @property
    def active(self) -> bool:
        return self._sock is not None

    def start(self) -> "TcpListener":
        self.stop_evt.clear()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self._sock.listen(self._backlog)
        threading.Thread(target=self._accept_loop, name=self._name,
                         daemon=True).start()
        return self

    def stop(self) -> None:
        self.stop_evt.set()
        if self._sock is not None:
            try:
                # shutdown BEFORE close: closing an fd does NOT wake a
                # thread blocked in accept() on Linux — the thread would
                # zombie on the stale fd number, and when the kernel
                # recycles that fd for a new CLIENT socket the old
                # accept loop starts stealing from it (observed as
                # phantom half-open connections after a broker restart).
                # shutdown(SHUT_RDWR) wakes the blocked accept with an
                # error so the loop exits before the fd is reused.
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _accept_loop(self) -> None:
        while not self.stop_evt.is_set():
            sock = self._sock  # stop() may null the attribute concurrently
            if sock is None:
                return
            try:
                conn, _ = sock.accept()
            except OSError:
                return
            from .wire import tune_socket
            try:
                tune_socket(conn)
            except OSError:
                # peer died between accept and setsockopt: close the
                # fd instead of leaking it
                conn.close()
                continue
            if self._spawn:
                threading.Thread(target=self._on_conn, args=(conn,),
                                 name=f"{self._name}-conn",
                                 daemon=True).start()
            else:
                self._on_conn(conn)
