"""Wire v2: negotiated codecs, dtype downcast, and frame coalescing.

This module layers optional compaction on top of the v1 framing in
``protocol.py``; the outer message format never changes, so a v1 peer
sees byte-identical traffic. The extras are negotiated per link at the
CAPS/SUBSCRIBE handshake:

* the connecting side sends ``{"wire": advertise(...)}`` inside its
  handshake meta;
* the accepting side folds that into its own requested config with
  :func:`negotiate` and echoes the chosen block in the CAPS_ACK meta;
* the connecting side adopts the echoed choice with :func:`accept`.

A peer that never mentions ``wire`` (any pre-v2 build) gets ``None`` out
of both :func:`negotiate` and :func:`accept`, which every call below
treats as "plain v1": no codec, no downcast, no DATA_BATCH.

Codecs (all lossless):

* ``raw`` — payloads as-is (the zero-copy vectored path).
* ``zlib`` — per-tensor zlib at a throughput-oriented level.
* ``shuffle-zlib`` — byte-shuffle (group same-significance bytes across
  elements, a ``blosc``-style filter) before zlib; float tensors whose
  exponents dominate compress far better shuffled.
* ``delta`` — temporal keyframe+diff transport: the sender keeps the
  last frame shipped on the link as the reference and sends sparse
  bitwise diffs (the ``elements/sparse.py`` (index, value) format,
  zlib'd when that pays) between keyframes. Keyframes go out on a fresh
  link, every ``delta_k`` frames, on any layout change, and whenever a
  diff would not beat the dense frame (promotion). Each frame carries
  the reference epoch it was encoded against, so a receiver can never
  silently patch the wrong baseline — a mismatch raises, the link
  reconnects, and the fresh link starts with a keyframe. Lossless and
  deterministic: decode output is byte-identical to the delta-off path.

Per-tensor, a codec is only kept when it actually shrinks the payload
(otherwise the tensor ships raw with no marker), and a link that keeps
failing to compress stops trying for a while (adaptive skip) so
incompressible streams pay ~zero codec overhead.

Delta is the one codec with per-link *state* on both ends, so it is
only ever chosen by the accepting side's own request (an edgesink's
``wire-codec=delta``), never adopted from a peer's wish — paths that
do not thread their negotiated :class:`WireConfig` into the unpack
calls can therefore never receive a delta frame. Old peers advertise a
codec list without ``delta`` and fall back to raw/zlib cleanly in both
directions.

``wire-precision`` (opt-in, lossy): float32 tensors are downcast to
bfloat16/float16 on the wire and upcast back to float32 on receive; the
original dtype always rides in meta.
"""
from __future__ import annotations

import struct
import time
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs import context as _obs_ctx
from ..obs import spans as _obs_spans
from ..tensors.buffer import Buffer, BufferFlags, Chunk
from . import protocol
from .protocol import Payload, as_payload_view, resolve_dtype

WIRE_VERSION = 2

CODEC_RAW = "raw"
CODEC_ZLIB = "zlib"
CODEC_SHUFFLE = "shuffle-zlib"
CODEC_DELTA = "delta"
CODECS = (CODEC_RAW, CODEC_ZLIB, CODEC_SHUFFLE, CODEC_DELTA)

# default keyframe cadence for wire-codec=delta: a keyframe every K
# frames bounds both the blast radius of a corrupted reference and the
# time a joining observer waits for a decodable frame. 0 = never rekey
# on schedule (pipelint flags that as delta-no-keyframe-interval).
DELTA_KEYFRAME_INTERVAL = 32

PREC_NONE = "none"
PREC_BF16 = "bf16"
PREC_FP16 = "fp16"
PRECISIONS = (PREC_NONE, PREC_BF16, PREC_FP16)
_PREC_DTYPE = {PREC_BF16: "bfloat16", PREC_FP16: "float16"}

# numeric codec codes for the compact per-payload ``enc`` list on
# DATA_BATCH messages (single DATA frames use the per-tensor "codec"
# meta key instead); _CODE_DELTA(_Z) mark sparse-diff payloads (plain /
# zlib'd) and only ever appear on links that negotiated delta
_CODE_RAW, _CODE_ZLIB, _CODE_SHUFFLE = 0, 1, 2
_CODE_DELTA, _CODE_DELTA_Z = 3, 4
_CODE_NAME = {_CODE_ZLIB: CODEC_ZLIB, _CODE_SHUFFLE: CODEC_SHUFFLE}

# don't bother compressing tiny tensors; keep zlib at a
# throughput-oriented level — the wire win must not cost more pack time
# than it saves in send time
MIN_COMPRESS = 512
COMPRESS_LEVEL = 1
# a codec result must beat raw by at least this factor to be kept
KEEP_RATIO = 0.9
# adaptive skip: after this many consecutive "compression didn't help"
# tensors, send raw without trying for SKIP_FRAMES tensors, then reprobe
POOR_LIMIT = 3
SKIP_FRAMES = 256
# early abort (the ZFS-compress trick): before compressing a large
# tensor, deflate just this prefix — if even the sample won't shrink,
# the tensor ships raw for ~1/10 the cost of a full failed attempt
PROBE_BYTES = 16384

# per-frame binary header inside a DATA_BATCH payload[0]:
# seq i64 (-1 = none), pts f64 (NaN = none), duration f64 (NaN = none),
# flags u32 — replaces per-frame JSON meta
_FHDR = struct.Struct("<qddI")
# the trace-extended header (negotiated: both peers advertised
# ``trace``; marked ``fhdr=2`` in the batch meta so the receiver is
# self-describing): the v1 fields + trace_id u64, span_id u64 (0/0 =
# untraced frame), then the context's birth stamp and queue/compute/
# wire attribution accumulators (i64 ns each) so end-to-end latency
# attribution survives the hop. A link that did not negotiate trace
# ships the v1 header byte-identically.
_FHDR_T = struct.Struct("<qddIQQqqqq")


class WireConfig:
    """The negotiated per-link wire feature set (+ adaptive codec
    state). One instance per connection; the skip counters are touched
    from whatever thread packs for that link, under a leaf lock."""

    __slots__ = ("version", "codec", "precision", "trace", "delta_k",
                 "_lock", "_poor", "_skip", "_dlock", "_dtx", "_drx")

    def __init__(self, codec: str = CODEC_RAW, precision: str = PREC_NONE,
                 version: int = WIRE_VERSION, trace: bool = False,
                 delta_k: int = DELTA_KEYFRAME_INTERVAL):
        import threading
        self.version = version
        self.codec = codec if codec in CODECS else CODEC_RAW
        self.precision = precision if precision in PRECISIONS else PREC_NONE
        # negotiated frame-trace propagation (obs/): DATA meta gains a
        # "trace" field and DATA_BATCH the fhdr=2 extended header —
        # only when BOTH peers advertised it (old peers: byte-identical)
        self.trace = bool(trace)
        self._lock = threading.Lock()
        self._poor = 0
        self._skip = 0
        # delta codec: keyframe cadence + per-direction reference state.
        # A WireConfig is minted fresh per connection (negotiate/accept),
        # so a reconnect or session RESUME always restarts from a
        # keyframe — replayed frames can never diff against a reference
        # the peer no longer holds. _dtx/_drx are guarded by _dlock
        # (never _lock: the keyframe zlib attempt must not re-enter the
        # adaptive-skip lock).
        self.delta_k = int(delta_k)
        self._dlock = threading.Lock()
        self._dtx: Optional[Dict] = None
        self._drx: Optional[Dict] = None

    def to_meta(self) -> Dict:
        out = {"v": self.version, "codec": self.codec,
               "precision": self.precision, "codecs": list(CODECS),
               "precisions": list(PRECISIONS)}
        if self.codec == CODEC_DELTA:
            out["delta_k"] = self.delta_k
        if self.trace:
            out["trace"] = True
        return out

    # -- adaptive skip (incompressible streams stop paying for zlib) ---
    def _try_compress(self) -> bool:
        with self._lock:
            if self._skip > 0:
                self._skip -= 1
                return False
            return True

    def _note(self, helped: bool) -> None:
        with self._lock:
            if helped:
                self._poor = 0
            else:
                self._poor += 1
                if self._poor >= POOR_LIMIT:
                    self._poor = 0
                    self._skip = SKIP_FRAMES

    def __repr__(self) -> str:
        return (f"WireConfig(v{self.version}, codec={self.codec}, "
                f"precision={self.precision})")


# -- negotiation -------------------------------------------------------


def advertise(codec: str = CODEC_RAW, precision: str = PREC_NONE) -> Dict:
    """The ``wire`` block a connecting peer puts in its handshake meta:
    what it supports, plus what it would like for this link."""
    out = {"v": WIRE_VERSION, "codec": codec, "precision": precision,
           "codecs": list(CODECS), "precisions": list(PRECISIONS)}
    if _obs_spans.ENABLED:
        # frame-trace propagation support (an old peer just ignores the
        # key; it only takes effect when both ends advertise it)
        out["trace"] = True
    return out


def negotiate(peer: Optional[Dict], codec: str = CODEC_RAW,
              precision: str = PREC_NONE,
              delta_k: Optional[int] = None) -> Optional[WireConfig]:
    """Accepting side: fold the peer's advertisement into our own
    request. Returns None — meaning "speak plain v1" — when the peer
    did not advertise v2. A non-default local request wins over the
    peer's wish; either way the result is clamped to what both ends
    support, falling back to raw/none rather than erroring. Delta is
    the exception to wish-adoption: it requires per-link reference
    state on the accepting side, so it is only chosen when *our own*
    request asks for it (and the peer's codec list shows it can decode
    deltas) — a peer wishing for delta against a non-delta acceptor
    falls back to raw."""
    if not isinstance(peer, dict):
        return None
    try:
        if int(peer.get("v", 1)) < WIRE_VERSION:
            return None
    except (TypeError, ValueError):
        return None
    peer_codecs = set(peer.get("codecs") or (CODEC_RAW,))
    want = codec if codec != CODEC_RAW else str(peer.get("codec") or CODEC_RAW)
    if want == CODEC_DELTA and codec != CODEC_DELTA:
        want = CODEC_RAW
    chosen = want if want in CODECS and want in peer_codecs else CODEC_RAW
    peer_precs = set(peer.get("precisions") or (PREC_NONE,))
    wantp = precision if precision != PREC_NONE \
        else str(peer.get("precision") or PREC_NONE)
    chosenp = wantp if wantp in PRECISIONS and wantp in peer_precs \
        else PREC_NONE
    dk = DELTA_KEYFRAME_INTERVAL if delta_k is None else int(delta_k)
    return WireConfig(chosen, chosenp, delta_k=dk,
                      trace=bool(peer.get("trace")) and _obs_spans.ENABLED)


def accept(reply: Optional[Dict]) -> Optional[WireConfig]:
    """Connecting side: adopt the config the accepting side chose (the
    ``wire`` block echoed in CAPS_ACK). None — plain v1 — when the
    peer didn't echo one (any pre-v2 build)."""
    if not isinstance(reply, dict):
        return None
    try:
        if int(reply.get("v", 1)) < WIRE_VERSION:
            return None
    except (TypeError, ValueError):
        return None
    try:
        dk = int(reply.get("delta_k", DELTA_KEYFRAME_INTERVAL))
    except (TypeError, ValueError):
        dk = DELTA_KEYFRAME_INTERVAL
    return WireConfig(str(reply.get("codec") or CODEC_RAW),
                      str(reply.get("precision") or PREC_NONE),
                      delta_k=dk,
                      trace=bool(reply.get("trace")) and _obs_spans.ENABLED)


def tune_socket(sock, bufsize: int = 1 << 20) -> None:
    """Latency/throughput socket defaults for tensor links: NODELAY
    (frames are whole messages; never wait on Nagle) and roomy kernel
    buffers so a burst of coalesced frames doesn't stall the sender."""
    import socket as _socket
    try:
        sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
    except OSError:
        pass  # AF_UNIX etc.
    for opt in (_socket.SO_SNDBUF, _socket.SO_RCVBUF):
        try:
            sock.setsockopt(_socket.SOL_SOCKET, opt, bufsize)
        except OSError:
            pass


# -- per-tensor encode/decode ------------------------------------------


def _byte_shuffle(view, itemsize: int) -> bytes:
    """blosc-style shuffle: byte k of every element becomes contiguous."""
    u8 = np.frombuffer(view, np.uint8)
    return u8.reshape(-1, itemsize).T.tobytes()


def _byte_unshuffle(data: bytes, itemsize: int) -> np.ndarray:
    u8 = np.frombuffer(data, np.uint8)
    # transpose().copy() restores element order AND yields writable memory
    return u8.reshape(itemsize, -1).transpose().copy().reshape(-1)


def _encode_tensor(arr: np.ndarray, cfg: Optional[WireConfig]
                   ) -> Tuple[Payload, Dict, int, int]:
    """One tensor -> (payload, tensor-meta, raw_nbytes, codec_code)."""
    arr = np.asarray(arr)
    if arr.size and not arr.flags.c_contiguous:
        arr = np.ascontiguousarray(arr)
    t = {"dtype": str(arr.dtype), "shape": list(arr.shape)}
    if cfg is not None and cfg.precision != PREC_NONE and \
            arr.dtype == np.float32:
        wname = _PREC_DTYPE[cfg.precision]
        arr = np.ascontiguousarray(arr.astype(resolve_dtype(wname)))
        t["wire_dtype"] = wname
    raw = as_payload_view(arr)
    nraw = len(raw)
    if cfg is None or cfg.codec == CODEC_RAW or nraw < MIN_COMPRESS or \
            not cfg._try_compress():
        return raw, t, nraw, _CODE_RAW
    itemsize = arr.dtype.itemsize
    if cfg.codec == CODEC_SHUFFLE and itemsize > 1:
        data = _byte_shuffle(raw, itemsize)
        code = _CODE_SHUFFLE
    else:
        data = raw
        code = _CODE_ZLIB
    if nraw > 4 * PROBE_BYTES and \
            len(zlib.compress(data[:PROBE_BYTES], COMPRESS_LEVEL)) >= \
            KEEP_RATIO * PROBE_BYTES:
        # even the sample won't shrink: incompressible, don't pay for
        # the full attempt (counts toward the adaptive skip like one)
        cfg._note(False)
        return raw, t, nraw, _CODE_RAW
    comp = zlib.compress(data, COMPRESS_LEVEL)
    if len(comp) < KEEP_RATIO * nraw:
        cfg._note(True)
        return comp, t, nraw, code
    cfg._note(False)
    return raw, t, nraw, _CODE_RAW


def _decode_tensor(t: Dict, p: Payload, code: Optional[int] = None,
                   upcast: bool = True) -> np.ndarray:
    """One payload -> writable ndarray per its tensor-meta (+ optional
    numeric codec code from a batch's ``enc`` list). ``upcast=False``
    keeps the wire dtype (the delta decoder stores references in wire
    precision, exactly like the sender's)."""
    codec = _CODE_NAME.get(code) if code is not None else t.get("codec")
    wname = t.get("wire_dtype")
    dtype = resolve_dtype(wname or t["dtype"])
    shape = tuple(t["shape"])
    if codec == CODEC_SHUFFLE:
        arr = _byte_unshuffle(zlib.decompress(p), dtype.itemsize) \
            .view(dtype).reshape(shape)
    elif codec == CODEC_ZLIB:
        arr = np.frombuffer(bytearray(zlib.decompress(p)), dtype) \
            .reshape(shape)
    elif isinstance(p, np.ndarray) and p.dtype == dtype and \
            p.shape == shape and p.flags.writeable:
        arr = p  # recv_msg preallocated it: already in place, writable
    else:
        raw = p.tobytes() if isinstance(p, np.ndarray) else p
        arr = np.frombuffer(raw, dtype).reshape(shape)
        if not arr.flags.writeable:
            arr = arr.copy()
    if wname and upcast:
        arr = arr.astype(resolve_dtype(t["dtype"]))
    return arr


# -- delta codec (temporal keyframe + sparse diff) ---------------------


def _delta_wire_arr(arr: np.ndarray, cfg: WireConfig
                    ) -> Tuple[np.ndarray, Dict]:
    """One chunk -> (contiguous wire-dtype array, base tensor meta).
    Precision downcast composes *under* delta: references live in wire
    precision on both ends, so diffs are exact in the wire domain."""
    arr = np.asarray(arr)
    if arr.size and not arr.flags.c_contiguous:
        arr = np.ascontiguousarray(arr)
    t = {"dtype": str(arr.dtype), "shape": list(arr.shape)}
    if cfg.precision != PREC_NONE and arr.dtype == np.float32:
        wname = _PREC_DTYPE[cfg.precision]
        arr = np.ascontiguousarray(arr.astype(resolve_dtype(wname)))
        t["wire_dtype"] = wname
    return arr, t


def _delta_layout_ok(refs: List[np.ndarray],
                     arrs: List[np.ndarray]) -> bool:
    return len(refs) == len(arrs) and all(
        r.shape == a.shape and r.dtype == a.dtype
        for r, a in zip(refs, arrs))


def _zlib_maybe(data: bytes) -> Tuple[bytes, bool]:
    """zlib when it pays (same MIN/KEEP thresholds as the codec path,
    no adaptive skip: delta decisions must be deterministic so the
    delta-on/off parity gate is exact)."""
    if len(data) < MIN_COMPRESS:
        return data, False
    comp = zlib.compress(data, COMPRESS_LEVEL)
    if len(comp) < KEEP_RATIO * len(data):
        return comp, True
    return data, False


def _delta_encode(buf: Buffer, cfg: WireConfig
                  ) -> Tuple[bool, int, List[Dict], List[Payload],
                             List[int], int, int, bool]:
    """One frame under the link's sender delta state (caller holds
    cfg._dlock) -> (keyframe?, epoch, tensor metas, payloads, numeric
    codes, raw bytes, enc bytes, promoted?). Keyframe triggers: fresh
    link, layout change, K-th frame, or a diff that would not beat the
    dense frame."""
    from ..elements.sparse import sparse_encode
    pairs = [_delta_wire_arr(np.asarray(c.host()), cfg) for c in buf.chunks]
    arrs = [a for a, _t in pairs]
    nraw = sum(a.nbytes for a in arrs)
    st = cfg._dtx
    promoted = False
    key = False
    if st is None or not _delta_layout_ok(st["refs"], arrs):
        key = True
        promoted = st is not None  # caps/layout change mid-stream
    elif cfg.delta_k > 0 and st["n"] + 1 >= cfg.delta_k:
        key = True
    diffs: List[Tuple[bytes, bool]] = []
    if not key:
        total = 0
        for a, ref in zip(arrs, st["refs"]):
            payload, z = _zlib_maybe(sparse_encode(a, ref))
            diffs.append((payload, z))
            total += len(payload)
        if total >= KEEP_RATIO * max(nraw, 1):
            key = True       # diff does not pay: promote to keyframe
            promoted = True
    tensors: List[Dict] = []
    payloads: List[Payload] = []
    codes: List[int] = []
    nenc = 0
    if key:
        epoch = 1 if st is None else st["e"] + 1
        for a, t in pairs:
            raw = as_payload_view(a)
            payload, z = _zlib_maybe(raw)
            codes.append(_CODE_ZLIB if z else _CODE_RAW)
            payloads.append(payload)
            tensors.append(dict(t))
            nenc += len(payload)
        cfg._dtx = {"refs": [a.copy() for a in arrs], "e": epoch, "n": 0}
        return True, epoch, tensors, payloads, codes, nraw, nenc, promoted
    epoch = st["e"]
    for (a, t), (payload, z) in zip(pairs, diffs):
        tensors.append(dict(t))
        codes.append(_CODE_DELTA_Z if z else _CODE_DELTA)
        payloads.append(payload)
        nenc += len(payload)
    st["refs"] = [a.copy() for a in arrs]
    st["n"] += 1
    return False, epoch, tensors, payloads, codes, nraw, nenc, False


def _delta_deliver(arr: np.ndarray, t: Dict, aliased: bool) -> np.ndarray:
    """Wire-dtype array -> what the app sees. Never aliases the
    receiver reference (downstream transforms mutate in place)."""
    wname = t.get("wire_dtype")
    if wname:
        return arr.astype(resolve_dtype(t["dtype"]))
    return arr.copy() if aliased else arr


def _delta_decode(tensors: Sequence[Dict], payloads: Sequence[Payload],
                  key: bool, epoch: int, cfg: WireConfig,
                  codes: Optional[Sequence[int]] = None) -> List[np.ndarray]:
    """One frame's payloads -> delivered arrays, advancing the receiver
    reference state (caller holds cfg._dlock). A diff whose epoch does
    not match the held reference raises — the link layer treats that as
    a dead link and reconnects, which restarts from a keyframe."""
    st = cfg._drx
    out: List[np.ndarray] = []
    if key:
        refs = []
        for j, (t, p) in enumerate(zip(tensors, payloads)):
            code = codes[j] if codes is not None else None
            arr = _decode_tensor(t, p, code, upcast=False)
            refs.append(arr.copy())
            out.append(_delta_deliver(arr, t, aliased=False))
        cfg._drx = {"refs": refs, "e": epoch}
        return out
    if st is None or st.get("e") != epoch:
        raise ValueError(
            "delta diff against a missing/stale reference (held epoch "
            f"{None if st is None else st['e']}, frame wants {epoch})")
    from ..elements.sparse import sparse_decode
    refs = st["refs"]
    if len(refs) != len(tensors):
        raise ValueError("delta diff tensor count mismatch")
    for j, (t, p) in enumerate(zip(tensors, payloads)):
        code = codes[j] if codes is not None else None
        z = (code == _CODE_DELTA_Z) if code is not None \
            else bool(t.get("dz"))
        data = p.tobytes() if isinstance(p, np.ndarray) else bytes(p)
        if z:
            data = zlib.decompress(data)
        arr = sparse_decode(data, ref=refs[j])
        refs[j] = arr
        out.append(_delta_deliver(arr, t, aliased=True))
    return out


def _delta_out_stats(stats, key: bool, promoted: bool,
                     nraw: int, nenc: int) -> None:
    stats.add(wire_delta_keyframes=int(key), wire_delta_diffs=int(not key),
              wire_delta_promotions=int(promoted),
              wire_delta_bytes_saved=max(0, nraw - nenc))


# -- frame pack/unpack -------------------------------------------------


def pack_buffer(buf: Buffer, cfg: Optional[WireConfig] = None, stats=None
                ) -> Tuple[Dict, List[Payload]]:
    """Buffer -> one DATA/RESULT message body under the link config.
    With ``cfg=None`` the meta is exactly v1 ``buffer_to_wire`` output
    (no codec/wire_dtype keys ever appear), so it is always safe for a
    v1 peer."""
    if cfg is not None and cfg.codec == CODEC_DELTA:
        return _pack_buffer_delta(buf, cfg, stats)
    t0 = time.perf_counter_ns()
    tensors: List[Dict] = []
    payloads: List[Payload] = []
    nraw = nenc = 0
    for c in buf.chunks:
        payload, t, raw_b, code = _encode_tensor(np.asarray(c.host()), cfg)
        if code != _CODE_RAW:
            t["codec"] = _CODE_NAME[code]
        tensors.append(t)
        payloads.append(payload)
        nraw += raw_b
        nenc += len(payload)
    meta = {"pts": buf.pts, "duration": buf.duration, "tensors": tensors}
    if cfg is not None and cfg.trace:
        ctx = buf.extras.get(_obs_ctx.CTX_KEY)
        if ctx is not None:
            meta["trace"] = _obs_ctx.to_wire(ctx)
    if stats is not None:
        stats.add(wire_frames_out=1, wire_raw_bytes_out=nraw,
                  wire_enc_bytes_out=nenc,
                  wire_pack_ns=time.perf_counter_ns() - t0)
    return meta, payloads


def _pack_buffer_delta(buf: Buffer, cfg: WireConfig, stats=None
                       ) -> Tuple[Dict, List[Payload]]:
    """pack_buffer for a delta link: frame-level meta carries the
    reference epoch (+ ``k`` on keyframes); diff tensors are marked
    ``codec=delta`` (``dz=1`` when the sparse bytes are zlib'd)."""
    t0 = time.perf_counter_ns()
    with cfg._dlock:
        key, epoch, tensors, payloads, codes, nraw, nenc, promoted = \
            _delta_encode(buf, cfg)
    for t, code in zip(tensors, codes):
        if code == _CODE_ZLIB:
            t["codec"] = CODEC_ZLIB
        elif code in (_CODE_DELTA, _CODE_DELTA_Z):
            t["codec"] = CODEC_DELTA
            if code == _CODE_DELTA_Z:
                t["dz"] = 1
    meta = {"pts": buf.pts, "duration": buf.duration, "tensors": tensors,
            "delta": {"e": epoch, "k": 1} if key else {"e": epoch}}
    if cfg.trace:
        ctx = buf.extras.get(_obs_ctx.CTX_KEY)
        if ctx is not None:
            meta["trace"] = _obs_ctx.to_wire(ctx)
    if stats is not None:
        stats.add(wire_frames_out=1, wire_raw_bytes_out=nraw,
                  wire_enc_bytes_out=nenc,
                  wire_pack_ns=time.perf_counter_ns() - t0)
        _delta_out_stats(stats, key, promoted, nraw, nenc)
    return meta, payloads


def unpack_buffer(meta: Dict, payloads: Sequence[Payload], stats=None,
                  cfg: Optional[WireConfig] = None) -> Buffer:
    """Inverse of :func:`pack_buffer`; handles plain-v1 and every v2
    codec/precision marker. Chunk arrays are always writable. ``cfg``
    is only needed on links that negotiated the delta codec (the
    receiver keeps reference state in it)."""
    if meta.get("delta") is not None:
        return _unpack_buffer_delta(meta, payloads, stats, cfg)
    if stats is not None:
        stats.inc("wire_frames_in")
    tensors = meta.get("tensors", [])
    if not any("codec" in t or "wire_dtype" in t for t in tensors):
        buf = protocol.wire_to_buffer(meta, payloads)
    else:
        chunks = [Chunk(_decode_tensor(t, p))
                  for t, p in zip(tensors, payloads)]
        buf = Buffer(chunks, pts=meta.get("pts"),
                     duration=meta.get("duration"))
    trace = meta.get("trace")
    if trace is not None and _obs_spans.ENABLED:
        _adopt_trace(buf, trace)
    return buf


def _unpack_buffer_delta(meta: Dict, payloads: Sequence[Payload],
                         stats=None, cfg: Optional[WireConfig] = None
                         ) -> Buffer:
    if cfg is None or cfg.codec != CODEC_DELTA:
        raise ValueError(
            "delta frame on a link that did not negotiate wire-codec="
            "delta (no receiver reference state)")
    d = meta["delta"]
    key = bool(d.get("k"))
    with cfg._dlock:
        arrs = _delta_decode(meta.get("tensors", []), payloads, key,
                             int(d.get("e", 0)), cfg)
    buf = Buffer([Chunk(a) for a in arrs], pts=meta.get("pts"),
                 duration=meta.get("duration"))
    if stats is not None:
        stats.add(wire_frames_in=1, wire_delta_keyframes_in=int(key),
                  wire_delta_diffs_in=int(not key))
    trace = meta.get("trace")
    if trace is not None and _obs_spans.ENABLED:
        _adopt_trace(buf, trace)
    return buf


def _adopt_trace(buf: Buffer, field) -> None:
    """Receiver side of a traced DATA frame: rebuild the context, record
    the wire-hop span (parented on the sender's last span — the ids are
    fleet-unique, so the merged dump re-links across processes), and
    attribute the transit time."""
    got = _obs_ctx.from_wire(field)
    if got is None:
        return
    ctx, t_send = got
    now = time.time_ns()
    dur = max(0, now - t_send)
    _obs_spans.record_span("wire", "wire", t_send, dur, ctx)
    ctx.w_ns += dur
    _obs_ctx.attach(buf, ctx)


def batch_compatible(a: Buffer, b: Buffer) -> bool:
    """Frames can share one DATA_BATCH template iff chunk layouts match."""
    if len(a.chunks) != len(b.chunks):
        return False
    for ca, cb in zip(a.chunks, b.chunks):
        xa, xb = np.asarray(ca.host()), np.asarray(cb.host())
        if xa.dtype != xb.dtype or xa.shape != xb.shape:
            return False
    return True


def _stamp_fhdr(hdr: bytearray, i: int, buf: Buffer, seq: int,
                trace: bool) -> None:
    """Stamp frame i's binary header record (v1 or trace-extended)."""
    pts = float("nan") if buf.pts is None else float(buf.pts)
    dur = float("nan") if buf.duration is None else float(buf.duration)
    if trace:
        ctx = buf.extras.get(_obs_ctx.CTX_KEY)
        if ctx is None:
            _FHDR_T.pack_into(hdr, i * _FHDR_T.size, int(seq), pts,
                              dur, int(buf.flags), 0, 0, 0, 0, 0, 0)
        else:
            _FHDR_T.pack_into(hdr, i * _FHDR_T.size, int(seq), pts,
                              dur, int(buf.flags), ctx.trace_id,
                              ctx.span_id, ctx.t0_ns, ctx.q_ns,
                              ctx.c_ns, ctx.w_ns)
    else:
        _FHDR.pack_into(hdr, i * _FHDR.size, int(seq), pts, dur,
                        int(buf.flags))


def pack_batch(bufs: Sequence[Buffer], cfg: Optional[WireConfig] = None,
               stats=None, seqs: Optional[Sequence[int]] = None
               ) -> Tuple[Dict, List[Payload]]:
    """N layout-identical frames -> one DATA_BATCH message body: a meta
    template (shapes/dtypes once), payload[0] a compact binary per-frame
    header (seq/pts/duration/flags), then frames×tensors payloads with a
    numeric ``enc`` codec list. Only ever sent on links that negotiated
    v2 (a v1 peer cannot parse DATA_BATCH)."""
    if cfg is not None and cfg.codec == CODEC_DELTA:
        return _pack_batch_delta(bufs, cfg, stats, seqs)
    t0 = time.perf_counter_ns()
    trace = cfg is not None and cfg.trace and _obs_spans.ENABLED
    fhdr = _FHDR_T if trace else _FHDR
    hdr = bytearray(fhdr.size * len(bufs))
    template: List[Dict] = []
    enc: List[int] = []
    payloads: List[Payload] = [hdr]
    nraw = nenc = 0
    for i, buf in enumerate(bufs):
        seq = seqs[i] if seqs is not None and seqs[i] is not None else -1
        _stamp_fhdr(hdr, i, buf, seq, trace)
        for c in buf.chunks:
            payload, t, raw_b, code = _encode_tensor(np.asarray(c.host()),
                                                     cfg)
            if i == 0:
                template.append(t)
            enc.append(code)
            payloads.append(payload)
            nraw += raw_b
            nenc += len(payload)
    meta = {"wire_batch": 1, "frames": len(bufs), "tensors": template,
            "enc": enc}
    if trace:
        meta["fhdr"] = 2
        meta["ts"] = time.time_ns()   # one send stamp for the batch
    if stats is not None:
        stats.add(wire_frames_out=len(bufs), wire_raw_bytes_out=nraw,
                  wire_enc_bytes_out=nenc,
                  wire_pack_ns=time.perf_counter_ns() - t0)
    return meta, payloads


def _pack_batch_delta(bufs: Sequence[Buffer], cfg: WireConfig,
                      stats=None, seqs: Optional[Sequence[int]] = None
                      ) -> Tuple[Dict, List[Payload]]:
    """pack_batch for a delta link: frames are delta-encoded in order
    against the evolving link reference (a coalesced batch can contain
    a mid-batch keyframe — K rollover or promotion); per-frame epochs
    and keyframe flags ride in the ``delta`` meta block, per-payload
    codecs in the numeric ``enc`` list."""
    t0 = time.perf_counter_ns()
    trace = cfg.trace and _obs_spans.ENABLED
    fhdr = _FHDR_T if trace else _FHDR
    hdr = bytearray(fhdr.size * len(bufs))
    template: List[Dict] = []
    enc: List[int] = []
    es: List[int] = []
    ks: List[int] = []
    payloads: List[Payload] = [hdr]
    nraw = nenc = 0
    with cfg._dlock:
        for i, buf in enumerate(bufs):
            seq = seqs[i] if seqs is not None and seqs[i] is not None else -1
            _stamp_fhdr(hdr, i, buf, seq, trace)
            key, epoch, tensors, pls, codes, r, e, promoted = \
                _delta_encode(buf, cfg)
            if i == 0:
                template = tensors
            es.append(epoch)
            ks.append(int(key))
            enc.extend(codes)
            payloads.extend(pls)
            nraw += r
            nenc += e
            if stats is not None:
                _delta_out_stats(stats, key, promoted, r, e)
    meta = {"wire_batch": 1, "frames": len(bufs), "tensors": template,
            "enc": enc, "delta": {"es": es, "ks": ks}}
    if trace:
        meta["fhdr"] = 2
        meta["ts"] = time.time_ns()
    if stats is not None:
        stats.add(wire_frames_out=len(bufs), wire_raw_bytes_out=nraw,
                  wire_enc_bytes_out=nenc,
                  wire_pack_ns=time.perf_counter_ns() - t0)
    return meta, payloads


def unpack_batch(meta: Dict, payloads: Sequence[Payload], stats=None,
                 cfg: Optional[WireConfig] = None) -> List[Buffer]:
    """Inverse of :func:`pack_batch` -> the original frames, in order,
    with pts/duration/flags restored and seq (when present) in
    ``extras["seq"]``. ``cfg`` is only needed on delta links (receiver
    reference state)."""
    if meta.get("delta") is not None:
        return _unpack_batch_delta(meta, payloads, stats, cfg)
    frames = int(meta.get("frames", 0))
    template = meta.get("tensors", [])
    enc = meta.get("enc")
    ntens = len(template)
    hdr = payloads[0]
    traced = int(meta.get("fhdr", 1)) >= 2
    fhdr = _FHDR_T if traced else _FHDR
    t_send = int(meta.get("ts", 0))
    if stats is not None:
        stats.add(wire_frames_in=frames)
    out: List[Buffer] = []
    idx = 1
    for i in range(frames):
        rec = fhdr.unpack_from(hdr, i * fhdr.size)
        seq, pts, dur, flags = rec[:4]
        chunks = []
        for j, t in enumerate(template):
            code = enc[i * ntens + j] if enc else _CODE_RAW
            chunks.append(Chunk(_decode_tensor(t, payloads[idx], code)))
            idx += 1
        buf = Buffer(chunks,
                     pts=None if pts != pts else pts,
                     duration=None if dur != dur else dur,
                     flags=BufferFlags(flags))
        if seq >= 0:
            buf.extras["seq"] = seq
        if traced and _obs_spans.ENABLED and rec[4]:
            _adopt_trace(buf, (rec[4], rec[5], t_send,
                               rec[6], rec[7], rec[8], rec[9]))
        out.append(buf)
    return out


def _unpack_batch_delta(meta: Dict, payloads: Sequence[Payload],
                        stats=None, cfg: Optional[WireConfig] = None
                        ) -> List[Buffer]:
    if cfg is None or cfg.codec != CODEC_DELTA:
        raise ValueError(
            "delta batch on a link that did not negotiate wire-codec="
            "delta (no receiver reference state)")
    frames = int(meta.get("frames", 0))
    template = meta.get("tensors", [])
    enc = meta.get("enc") or []
    d = meta["delta"]
    es, ks = d.get("es") or [], d.get("ks") or []
    ntens = len(template)
    hdr = payloads[0]
    traced = int(meta.get("fhdr", 1)) >= 2
    fhdr = _FHDR_T if traced else _FHDR
    t_send = int(meta.get("ts", 0))
    out: List[Buffer] = []
    idx = 1
    with cfg._dlock:
        for i in range(frames):
            rec = fhdr.unpack_from(hdr, i * fhdr.size)
            seq, pts, dur, flags = rec[:4]
            key = bool(ks[i]) if i < len(ks) else False
            epoch = int(es[i]) if i < len(es) else 0
            codes = enc[i * ntens:(i + 1) * ntens]
            arrs = _delta_decode(template, payloads[idx:idx + ntens],
                                 key, epoch, cfg, codes)
            idx += ntens
            if stats is not None:
                stats.add(wire_frames_in=1,
                          wire_delta_keyframes_in=int(key),
                          wire_delta_diffs_in=int(not key))
            buf = Buffer([Chunk(a) for a in arrs],
                         pts=None if pts != pts else pts,
                         duration=None if dur != dur else dur,
                         flags=BufferFlags(flags))
            if seq >= 0:
                buf.extras["seq"] = seq
            if traced and _obs_spans.ENABLED and rec[4]:
                _adopt_trace(buf, (rec[4], rec[5], t_send,
                                   rec[6], rec[7], rec[8], rec[9]))
            out.append(buf)
    return out
