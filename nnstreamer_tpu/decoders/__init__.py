"""Decoder subplugins: tensors -> media.

≙ ext/nnstreamer/tensor_decoder/* (direct_video, image_labeling,
bounding_boxes with pluggable box-properties classes, pose_estimation,
image_segment, tensor_region, ...).
"""
from . import registry
from .registry import DecoderPlugin, find_decoder, register_decoder
from . import (bounding_box, codecs, direct_video, image_label,  # noqa: F401
               pose, python3, segment, tensor_region)

__all__ = ["registry", "DecoderPlugin", "find_decoder", "register_decoder"]
