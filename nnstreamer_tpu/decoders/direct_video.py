"""direct_video decoder: uint8 tensors -> video/x-raw frames.

≙ ext/nnstreamer/tensor_decoder/tensordec-directvideo.c. Channel count
picks the video format (1->GRAY8, 3->RGB, 4->RGBA; option1 may force BGR).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorsConfig
from .registry import DecoderPlugin, register_decoder

_FMT_BY_CHANNELS = {1: "GRAY8", 3: "RGB", 4: "RGBA"}


@register_decoder
class DirectVideo(DecoderPlugin):
    NAME = "direct_video"

    def get_out_caps(self, config: TensorsConfig) -> Caps:
        info = config.info[0]
        if len(info.shape) != 3:
            raise ValueError(
                f"direct_video needs HWC uint8 tensors, got {info!r}")
        h, w, c = info.shape
        fmt = self.option(1) or _FMT_BY_CHANNELS.get(c)
        if fmt is None:
            raise ValueError(f"direct_video: no video format for {c} channels")
        self._fmt = fmt
        rate = f"{config.rate_n}/{config.rate_d}"
        return Caps(f"video/x-raw,format={fmt},width={w},height={h},"
                    f"framerate=(fraction){rate}")

    def decode(self, buf: Buffer) -> Optional[Buffer]:
        arr = buf.chunks[0].host()
        if arr.dtype != np.uint8:
            arr = np.clip(arr, 0, 255).astype(np.uint8)
        if self._fmt == "BGR":
            arr = arr[..., ::-1]
        return Buffer([Chunk(np.ascontiguousarray(arr))])
