"""image_labeling decoder: classification logits -> text label.

≙ ext/nnstreamer/tensor_decoder/tensordec-imagelabel.c (+ label-file
loading in tensordecutil.c). option1 = labels file (one label per line).
Output is text/x-raw; the label string rides as a uint8 tensor chunk.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorsConfig
from .registry import DecoderPlugin, register_decoder


def load_labels(path: str) -> List[str]:
    with open(path) as f:
        return [line.strip() for line in f if line.strip()]


@register_decoder
class ImageLabeling(DecoderPlugin):
    NAME = "image_labeling"

    def set_options(self, options) -> None:
        super().set_options(options)
        self._labels = load_labels(self.option(1)) if self.option(1) else None

    def get_out_caps(self, config: TensorsConfig) -> Caps:
        return Caps("text/x-raw,format=utf8")

    def decode(self, buf: Buffer) -> Optional[Buffer]:
        scores = buf.chunks[0].host().reshape(-1)
        idx = int(np.argmax(scores))
        label = self._labels[idx] if self._labels and idx < len(self._labels) \
            else str(idx)
        out = Buffer([Chunk(np.frombuffer(label.encode(), np.uint8))])
        out.extras["label_index"] = idx
        out.extras["label"] = label
        return out
