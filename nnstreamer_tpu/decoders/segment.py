"""image_segment decoder: per-pixel class map -> RGBA color overlay.

≙ ext/nnstreamer/tensor_decoder/tensordec-imagesegment.c
(tflite-deeplab mode). Input [H, W, C] logits (argmax over C) or [H, W]
int class map. option1 = mode, option2 = alpha.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorsConfig
from .registry import DecoderPlugin, register_decoder

# 21-class PASCAL-VOC-ish palette, RGB
_COLORS = (np.array([
    [0, 0, 0], [128, 0, 0], [0, 128, 0], [128, 128, 0], [0, 0, 128],
    [128, 0, 128], [0, 128, 128], [128, 128, 128], [64, 0, 0], [192, 0, 0],
    [64, 128, 0], [192, 128, 0], [64, 0, 128], [192, 0, 128], [64, 128, 128],
    [192, 128, 128], [0, 64, 0], [128, 64, 0], [0, 192, 0], [128, 192, 0],
    [0, 64, 128]], np.uint8))


@register_decoder
class ImageSegment(DecoderPlugin):
    NAME = "image_segment"

    def set_options(self, options) -> None:
        super().set_options(options)
        self.alpha = int(float(self.option(2) or 0.6) * 255)

    def get_out_caps(self, config: TensorsConfig) -> Caps:
        shape = config.info[0].shape
        h, w = shape[0], shape[1]
        self._hw = (h, w)
        rate = f"{config.rate_n}/{config.rate_d}"
        return Caps(f"video/x-raw,format=RGBA,width={w},height={h},"
                    f"framerate=(fraction){rate}")

    def decode(self, buf: Buffer) -> Optional[Buffer]:
        arr = buf.chunks[0].host()
        if arr.ndim >= 3 and arr.shape[-1] > 1:
            classes = np.argmax(arr, axis=-1)
        else:
            classes = arr.reshape(arr.shape[0], arr.shape[1]).astype(np.int64)
        rgb = _COLORS[classes % len(_COLORS)]
        a = np.where(classes[..., None] > 0, self.alpha, 0).astype(np.uint8)
        out = np.concatenate([rgb, a], axis=-1)
        b = Buffer([Chunk(np.ascontiguousarray(out))])
        b.extras["class_map"] = classes
        return b

    def device_fn(self, config=None):
        """Fused decode: argmax + palette gather + alpha select are all
        integer/gather ops, exact under XLA and byte-identical to the
        numpy path (argmax ties resolve first-index on both). The
        ``class_map`` extras entry is host-side bookkeeping and is not
        materialized on the fused path (extras carry no caps; consumers
        needing it opt out with fuse=false)."""
        if config is None or not len(config.info):
            return None
        shape = tuple(config.info[0].shape)
        if len(shape) < 2:
            return None
        heatmap = len(shape) >= 3 and shape[-1] > 1
        alpha, ncolors = self.alpha, len(_COLORS)
        import jax.numpy as jnp
        colors = jnp.asarray(_COLORS)

        def fn(arrays):
            arr = arrays[0]
            if heatmap:
                classes = jnp.argmax(arr, axis=-1)
            else:
                classes = arr.reshape(arr.shape[0],
                                      arr.shape[1]).astype(jnp.int32)
            rgb = colors[classes % ncolors]
            a = jnp.where(classes[..., None] > 0,
                          alpha, 0).astype(jnp.uint8)
            return [jnp.concatenate([rgb, a], axis=-1)]

        return fn
