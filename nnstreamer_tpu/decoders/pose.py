"""pose_estimation decoder: heatmap keypoints -> RGBA skeleton overlay.

≙ ext/nnstreamer/tensor_decoder/tensordec-pose.c. Input is a PoseNet-style
heatmap tensor [H', W', K] (argmax per keypoint channel) or an explicit
keypoint tensor [K, 2|3]. option1 = output size "W:H", option2 = input
size, option3 = optional label/skeleton file ("key" mode vs "heatmap").
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorsConfig
from .registry import DecoderPlugin, register_decoder

# COCO-17 skeleton edges (the reference's default pose topology)
_EDGES = [(0, 1), (0, 2), (1, 3), (2, 4), (5, 6), (5, 7), (7, 9), (6, 8),
          (8, 10), (5, 11), (6, 12), (11, 12), (11, 13), (13, 15), (12, 14),
          (14, 16)]


def _draw_dot(canvas: np.ndarray, x: int, y: int, color, r: int = 3) -> None:
    h, w = canvas.shape[:2]
    canvas[max(0, y - r):min(h, y + r + 1),
           max(0, x - r):min(w, x + r + 1)] = color


def _draw_line(canvas: np.ndarray, p0, p1, color) -> None:
    n = int(max(abs(p1[0] - p0[0]), abs(p1[1] - p0[1]), 1))
    xs = np.linspace(p0[0], p1[0], n).astype(int)
    ys = np.linspace(p0[1], p1[1], n).astype(int)
    h, w = canvas.shape[:2]
    ok = (xs >= 0) & (xs < w) & (ys >= 0) & (ys < h)
    canvas[ys[ok], xs[ok]] = color


@register_decoder
class PoseEstimation(DecoderPlugin):
    NAME = "pose_estimation"

    def set_options(self, options) -> None:
        super().set_options(options)
        def wh(opt, dflt):
            if not opt:
                return dflt
            w, h = opt.split(":")
            return int(w), int(h)
        self.out_w, self.out_h = wh(self.option(1), (640, 480))
        self.in_w, self.in_h = wh(self.option(2), (257, 257))
        self.score_threshold = float(self.option(4) or 0.3)

    def get_out_caps(self, config: TensorsConfig) -> Caps:
        rate = f"{config.rate_n}/{config.rate_d}"
        return Caps(f"video/x-raw,format=RGBA,width={self.out_w},"
                    f"height={self.out_h},framerate=(fraction){rate}")

    def _keypoints(self, buf: Buffer) -> List[Tuple[float, float, float]]:
        arr = buf.chunks[0].host()
        if arr.ndim >= 3:  # heatmap [H', W', K]
            hm = arr.reshape(arr.shape[-3], arr.shape[-2], arr.shape[-1])
            hp, wp, k = hm.shape
            flat = hm.reshape(-1, k)
            idx = np.argmax(flat, axis=0)
            ys, xs = np.unravel_index(idx, (hp, wp))
            # the heatmap value is used AS the score, matching the
            # reference's plain-heatmap mode (tensordec-pose.c:782 only
            # sigmoids in HEATMAP_OFFSET mode; its doc header calls
            # Tensor[0] "label sigmoid probability"). zoo://posenet
            # already emits sigmoided maps, so this keeps the heatmap
            # and decode=device paths on ONE score scale — the model's
            # output scale, which is what score_threshold is defined on.
            scores = flat[idx, np.arange(k)]
            return [(x / max(wp - 1, 1), y / max(hp - 1, 1), float(s))
                    for x, y, s in zip(xs, ys, scores)]
        pts = arr.reshape(-1, arr.shape[-1])  # [K, 2|3] normalized
        return [(float(p[0]), float(p[1]),
                 float(p[2]) if len(p) > 2 else 1.0) for p in pts]

    def decode(self, buf: Buffer) -> Optional[Buffer]:
        kps = self._keypoints(buf)
        canvas = np.zeros((self.out_h, self.out_w, 4), np.uint8)
        pix = [(int(x * (self.out_w - 1)), int(y * (self.out_h - 1)), s)
               for x, y, s in kps]
        for a, b in _EDGES:
            if a < len(pix) and b < len(pix) and \
                    pix[a][2] >= self.score_threshold and \
                    pix[b][2] >= self.score_threshold:
                _draw_line(canvas, pix[a][:2], pix[b][:2],
                           (64, 255, 64, 255))
        for x, y, s in pix:
            if s >= self.score_threshold:
                _draw_dot(canvas, x, y, (255, 64, 64, 255))
        out = Buffer([Chunk(canvas)])
        out.extras["keypoints"] = kps
        return out
