"""Python-script decoder subplugin.

≙ ext/nnstreamer/tensor_decoder/tensordec-python3.cc: a user .py file
(option1) implements the decoder. The script defines::

    def get_out_caps(config) -> str | Caps    # config: TensorsConfig
    def decode(buf) -> Buffer                 # buf: tensors Buffer

mirroring the converter custom-script hook (converters/registry.py).
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from ..tensors.buffer import Buffer
from ..tensors.caps import Caps
from ..tensors.info import TensorsConfig
from .registry import DecoderPlugin, register_decoder


@register_decoder
class PythonDecoder(DecoderPlugin):
    NAME = "python3"

    def _load(self) -> Dict[str, Any]:
        path = self.option(1)
        if not path:
            raise ValueError("python3 decoder needs option1=<script.py>")
        ns: Dict[str, Any] = {}
        with open(path) as f:
            exec(compile(f.read(), path, "exec"), ns)  # noqa: S102 — user script
        if "decode" not in ns:
            raise ValueError(f"{path}: decoder script must define decode()")
        return ns

    def get_out_caps(self, config: TensorsConfig) -> Caps:
        self._ns = self._load()
        fn = self._ns.get("get_out_caps")
        if fn is None:
            return Caps.ANY()
        out = fn(config)
        return out if isinstance(out, Caps) else Caps(str(out))

    def decode(self, buf: Buffer) -> Optional[Buffer]:
        return self._ns["decode"](buf)
