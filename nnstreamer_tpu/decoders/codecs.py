"""Codec decoder subplugins: tensors -> serialized byte streams.

≙ ext/nnstreamer/tensor_decoder/tensordec-flatbuf.cc, -flexbuf.cc,
-protobuf.cc, -octetstream.c. Each mode wraps the wire codecs in
interop/tensor_codec.py and emits a single byte-payload buffer with the
reference's mimetype (other/flatbuf-tensor, other/flexbuf,
other/protobuf-tensor, application/octet-stream).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..interop import tensor_codec as tc
from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorsConfig
from .registry import DecoderPlugin, register_decoder


class _CodecDecoder(DecoderPlugin):
    MIMETYPE = ""
    PACK = None

    def get_out_caps(self, config: TensorsConfig) -> Caps:
        self._config = config
        return Caps(f"{self.MIMETYPE},framerate=(fraction)"
                    f"{config.rate_n}/{config.rate_d}")

    def _frame(self, buf: Buffer) -> tc.Frame:
        cfg = self._config
        names = [i.name or "" for i in cfg.info] if len(cfg.info) else None
        return tc.Frame([c.host() for c in buf.chunks], names,
                        cfg.rate_n, cfg.rate_d, int(cfg.format))

    def decode(self, buf: Buffer) -> Optional[Buffer]:
        data = type(self).PACK(self._frame(buf))
        return Buffer([Chunk(np.frombuffer(data, np.uint8))])


@register_decoder
class FlatbufDecoder(_CodecDecoder):
    NAME = "flatbuf"
    MIMETYPE = "other/flatbuf-tensor"
    PACK = staticmethod(tc.pack_flatbuf)


@register_decoder
class FlexbufDecoder(_CodecDecoder):
    NAME = "flexbuf"
    MIMETYPE = "other/flexbuf"
    PACK = staticmethod(tc.pack_flexbuf)


@register_decoder
class ProtobufDecoder(_CodecDecoder):
    NAME = "protobuf"
    MIMETYPE = "other/protobuf-tensor"
    PACK = staticmethod(tc.pack_protobuf)


@register_decoder
class OctetDecoder(_CodecDecoder):
    NAME = "octet_stream"
    MIMETYPE = "application/octet-stream"
    PACK = staticmethod(tc.pack_octet)
