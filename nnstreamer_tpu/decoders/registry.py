"""Decoder subplugin registry + base class.

≙ GstTensorDecoderDef registration (nnstreamer_plugin_api_decoder.h) and
nnstreamer_decoder_custom runtime registration.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Type

from ..tensors.buffer import Buffer
from ..tensors.caps import Caps
from ..tensors.info import TensorsConfig

_lock = threading.Lock()
_decoders: Dict[str, Type["DecoderPlugin"]] = {}


class DecoderPlugin:
    """set_options(opts 1..9) -> get_out_caps(config) -> decode(buffer)."""

    NAME = ""

    def set_options(self, options: List[str]) -> None:
        self.options = options

    def option(self, i: int) -> str:
        """1-indexed option accessor (option1..option9)."""
        return self.options[i - 1] if i - 1 < len(self.options) else ""

    def get_out_caps(self, config: TensorsConfig) -> Caps:
        raise NotImplementedError

    def decode(self, buf: Buffer) -> Optional[Buffer]:
        raise NotImplementedError

    def device_fn(self, config: Optional[TensorsConfig] = None):
        """Optional device-side decode: a pure jax-traceable
        ``fn(arrays) -> arrays`` equivalent of :meth:`decode` for the
        fusion compiler, specialized to the planned input *config*
        (shapes are static under jit, so branch on config here, not on
        array values). Default None: the decode stays on the host.
        Subplugins overriding this make ``tensor_decoder mode=<name>``
        device-fusible (tools/gen_element_docs.py marks them)."""
        return None


def register_decoder(cls: Type[DecoderPlugin]) -> Type[DecoderPlugin]:
    if not cls.NAME:
        raise ValueError("decoder subplugin needs a NAME")
    with _lock:
        _decoders[cls.NAME] = cls
    return cls


def register_custom_decoder(name: str,
                            fn: Callable[[Buffer], Buffer],
                            out_caps: "Caps | str" = None) -> None:
    """Runtime callback registration (≙ nnstreamer_decoder_custom_register)."""
    caps = Caps(out_caps) if isinstance(out_caps, str) else out_caps

    class _Custom(DecoderPlugin):
        NAME = name

        def get_out_caps(self, config: TensorsConfig) -> Caps:
            return caps if caps is not None else Caps.ANY()

        def decode(self, buf: Buffer) -> Optional[Buffer]:
            return fn(buf)

    with _lock:
        _decoders[name] = _Custom


def unregister_decoder(name: str) -> None:
    with _lock:
        _decoders.pop(name, None)


def find_decoder(name: str) -> Type[DecoderPlugin]:
    with _lock:
        if name not in _decoders:
            raise ValueError(
                f"unknown decoder mode {name!r}; known: {sorted(_decoders)}")
        return _decoders[name]


def decoder_names() -> List[str]:
    with _lock:
        return sorted(_decoders)
