"""tensor_region decoder: detection tensors -> crop-region tensor.

≙ ext/nnstreamer/tensor_decoder/tensordec-tensor_region.c: emits the
top-N detected regions as a uint32 [N, 4] (x, y, w, h pixel) tensor for
tensor_crop's info pad. option1 = N, option2 = labels, option3 = image
size "W:H".
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorsConfig
from .bounding_box import BoundingBoxes
from .registry import DecoderPlugin, register_decoder


@register_decoder
class TensorRegion(DecoderPlugin):
    NAME = "tensor_region"

    def set_options(self, options) -> None:
        super().set_options(options)
        self.num = int(self.option(1) or 1)
        # reuse the bounding-box tensor parsers; region mode defaults ssd-pp
        self._bb = BoundingBoxes()
        self._bb.set_options(["mobilenet-ssd-postprocess", self.option(2),
                              "", self.option(3), self.option(3),
                              "", "", "", ""])

    def get_out_caps(self, config: TensorsConfig) -> Caps:
        from ..tensors.info import TensorsConfig as TC, TensorsInfo
        info = TensorsInfo.make("uint32", f"4:{self.num}")
        return Caps.from_config(TC(info, rate_n=config.rate_n,
                                   rate_d=config.rate_d))

    def decode(self, buf: Buffer) -> Optional[Buffer]:
        boxes = self._bb._boxes_ssd_pp(buf)
        boxes = sorted(boxes, key=lambda b: -b.score)[:self.num]
        w, h = self._bb.out_w, self._bb.out_h
        out = np.zeros((self.num, 4), np.uint32)
        for i, b in enumerate(boxes):
            out[i] = [max(0, int(b.x * w)), max(0, int(b.y * h)),
                      int(b.w * w), int(b.h * h)]
        ob = Buffer([Chunk(out)])
        ob.extras["regions"] = out
        return ob
