"""bounding_boxes decoder: detection tensors -> RGBA overlay video.

≙ ext/nnstreamer/tensor_decoder/tensordec-boundingbox.cc with its
pluggable BoxProperties classes (tensordec-boundingbox.h:236-305):
yolov5/yolov8 (box_properties/yolo.cc), mobilenet-ssd (mobilenetssd.cc),
mobilenet-ssd-postprocess (mobilenetssdpp.cc).

Options (reference-compatible):
  option1 = mode: yolov5 | yolov8 | mobilenet-ssd-postprocess | custom
  option2 = labels file
  option3 = mode-specific (yolo: "scale:conf:iou"; ssd-pp: tensor order)
  option4 = output video size "W:H"
  option5 = model input size "W:H"
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorsConfig
from .image_label import load_labels
from .registry import DecoderPlugin, register_decoder

_PALETTE = np.array([
    [255, 64, 64, 255], [64, 255, 64, 255], [64, 64, 255, 255],
    [255, 255, 64, 255], [255, 64, 255, 255], [64, 255, 255, 255],
    [255, 160, 64, 255], [160, 64, 255, 255]], np.uint8)


@dataclasses.dataclass
class DetectedBox:
    x: float       # normalized [0,1] left
    y: float       # top
    w: float
    h: float
    cls: int
    score: float


def iou(a: DetectedBox, b: DetectedBox) -> float:
    x1, y1 = max(a.x, b.x), max(a.y, b.y)
    x2 = min(a.x + a.w, b.x + b.w)
    y2 = min(a.y + a.h, b.y + b.h)
    inter = max(0.0, x2 - x1) * max(0.0, y2 - y1)
    union = a.w * a.h + b.w * b.h - inter
    return inter / union if union > 0 else 0.0


def nms(boxes: List[DetectedBox], threshold: float = 0.5) -> List[DetectedBox]:
    """Greedy per-class non-max suppression (≙ reference nms in
    tensordec-boundingbox.cc)."""
    out: List[DetectedBox] = []
    for b in sorted(boxes, key=lambda b: -b.score):
        if all(o.cls != b.cls or iou(o, b) < threshold for o in out):
            out.append(b)
    return out


def draw_boxes(boxes: List[DetectedBox], width: int, height: int,
               thickness: int = 2) -> np.ndarray:
    """Rasterize box outlines onto a transparent RGBA canvas."""
    canvas = np.zeros((height, width, 4), np.uint8)
    for b in boxes:
        color = _PALETTE[b.cls % len(_PALETTE)]
        x0 = int(np.clip(b.x * width, 0, width - 1))
        y0 = int(np.clip(b.y * height, 0, height - 1))
        x1 = int(np.clip((b.x + b.w) * width, 0, width - 1))
        y1 = int(np.clip((b.y + b.h) * height, 0, height - 1))
        t = thickness
        canvas[y0:y0 + t, x0:x1 + 1] = color
        canvas[max(0, y1 - t + 1):y1 + 1, x0:x1 + 1] = color
        canvas[y0:y1 + 1, x0:x0 + t] = color
        canvas[y0:y1 + 1, max(0, x1 - t + 1):x1 + 1] = color
    return canvas


@register_decoder
class BoundingBoxes(DecoderPlugin):
    NAME = "bounding_boxes"

    def set_options(self, options) -> None:
        super().set_options(options)
        self.mode = self.option(1) or "yolov5"
        self._labels = load_labels(self.option(2)) if self.option(2) else None
        self.out_w, self.out_h = self._parse_wh(self.option(4), (640, 480))
        self.in_w, self.in_h = self._parse_wh(self.option(5),
                                              (self.out_w, self.out_h))
        opt3 = self.option(3)
        self.conf_threshold, self.iou_threshold, self.scaled = 0.25, 0.45, False
        if self.mode in ("yolov5", "yolov8") and opt3:
            parts = opt3.split(":")
            if parts and parts[0]:
                self.scaled = parts[0] not in ("0", "false")
            if len(parts) > 1 and parts[1]:
                self.conf_threshold = float(parts[1])
            if len(parts) > 2 and parts[2]:
                self.iou_threshold = float(parts[2])

    @staticmethod
    def _parse_wh(opt: str, default):
        if not opt:
            return default
        w, h = opt.split(":")
        return int(w), int(h)

    def get_out_caps(self, config: TensorsConfig) -> Caps:
        rate = f"{config.rate_n}/{config.rate_d}"
        return Caps(f"video/x-raw,format=RGBA,width={self.out_w},"
                    f"height={self.out_h},framerate=(fraction){rate}")

    # -- per-mode tensor parsing (the BoxProperties analog) ---------------
    def _boxes_yolov5(self, buf: Buffer) -> List[DetectedBox]:
        """pred [N, 5+nc]: cx,cy,w,h,obj,cls... (pixel scale when
        option3 scaled=1, else normalized)."""
        pred = buf.chunks[0].host()
        pred = pred.reshape(-1, pred.shape[-1])
        scale_w = self.in_w if self.scaled else 1.0
        scale_h = self.in_h if self.scaled else 1.0
        obj = pred[:, 4]
        cls_scores = pred[:, 5:] * obj[:, None]
        cls = np.argmax(cls_scores, axis=1)
        score = cls_scores[np.arange(len(cls)), cls]
        keep = score >= self.conf_threshold
        out = []
        for p, c, s in zip(pred[keep], cls[keep], score[keep]):
            cx, cy, w, h = (p[0] / scale_w, p[1] / scale_h,
                            p[2] / scale_w, p[3] / scale_h)
            out.append(DetectedBox(cx - w / 2, cy - h / 2, w, h,
                                   int(c), float(s)))
        return nms(out, self.iou_threshold)

    def _boxes_yolov8(self, buf: Buffer) -> List[DetectedBox]:
        """pred [4+nc, N] (or [N, 4+nc]): cx,cy,w,h,cls... (no objectness)."""
        pred = buf.chunks[0].host()
        pred = pred.reshape(pred.shape[-2], pred.shape[-1]) \
            if pred.ndim > 2 else pred
        if pred.shape[0] < pred.shape[1]:
            pred = pred.T  # -> [N, 4+nc]
        scale_w = self.in_w if self.scaled else 1.0
        scale_h = self.in_h if self.scaled else 1.0
        cls_scores = pred[:, 4:]
        cls = np.argmax(cls_scores, axis=1)
        score = cls_scores[np.arange(len(cls)), cls]
        keep = score >= self.conf_threshold
        out = []
        for p, c, s in zip(pred[keep], cls[keep], score[keep]):
            cx, cy, w, h = (p[0] / scale_w, p[1] / scale_h,
                            p[2] / scale_w, p[3] / scale_h)
            out.append(DetectedBox(cx - w / 2, cy - h / 2, w, h,
                                   int(c), float(s)))
        return nms(out, self.iou_threshold)

    def _boxes_ssd_pp(self, buf: Buffer) -> List[DetectedBox]:
        """TFLite detection-postprocess convention: boxes [N,4]
        (ymin,xmin,ymax,xmax normalized), classes [N], scores [N],
        count [1] (≙ mobilenetssdpp.cc tensor order, option3 reorders)."""
        order = [int(i) for i in self.option(3).split(":")] \
            if self.option(3) else [0, 1, 2, 3]
        chunks = [buf.chunks[i].host() for i in order]
        boxes, classes, scores, count = chunks
        n = int(count.reshape(-1)[0])
        boxes = boxes.reshape(-1, 4)
        out = []
        for i in range(min(n, len(boxes))):
            s = float(scores.reshape(-1)[i])
            if s < self.conf_threshold:
                continue
            ymin, xmin, ymax, xmax = boxes[i]
            out.append(DetectedBox(float(xmin), float(ymin),
                                   float(xmax - xmin), float(ymax - ymin),
                                   int(classes.reshape(-1)[i]), s))
        return out

    def decode(self, buf: Buffer) -> Optional[Buffer]:
        if self.mode == "yolov5":
            boxes = self._boxes_yolov5(buf)
        elif self.mode == "yolov8":
            boxes = self._boxes_yolov8(buf)
        elif self.mode in ("mobilenet-ssd-postprocess", "mobilenetssd-pp",
                           "tflite-ssd-postprocess"):
            boxes = self._boxes_ssd_pp(buf)
        else:
            raise ValueError(f"bounding_boxes: unknown mode {self.mode!r}")
        frame = draw_boxes(boxes, self.out_w, self.out_h)
        out = Buffer([Chunk(frame)])
        out.extras["boxes"] = [
            {"x": b.x, "y": b.y, "w": b.w, "h": b.h, "class": b.cls,
             "label": (self._labels[b.cls] if self._labels and
                       b.cls < len(self._labels) else str(b.cls)),
             "score": b.score}
            for b in boxes]
        return out
