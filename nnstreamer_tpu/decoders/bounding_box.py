"""bounding_boxes decoder: detection tensors -> RGBA overlay video.

≙ ext/nnstreamer/tensor_decoder/tensordec-boundingbox.cc with its
pluggable BoxProperties classes (tensordec-boundingbox.h:236-305):
yolov5/yolov8 (box_properties/yolo.cc), mobilenet-ssd (mobilenetssd.cc),
mobilenet-ssd-postprocess (mobilenetssdpp.cc).

Options (reference-compatible):
  option1 = mode: yolov5 | yolov8 | mobilenet-ssd-postprocess | custom
  option2 = labels file
  option3 = mode-specific (yolo: "scale:conf:iou"; ssd-pp: tensor order)
  option4 = output video size "W:H"
  option5 = model input size "W:H"
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorsConfig
from .image_label import load_labels
from .registry import DecoderPlugin, register_decoder

_PALETTE = np.array([
    [255, 64, 64, 255], [64, 255, 64, 255], [64, 64, 255, 255],
    [255, 255, 64, 255], [255, 64, 255, 255], [64, 255, 255, 255],
    [255, 160, 64, 255], [160, 64, 255, 255]], np.uint8)


@dataclasses.dataclass
class DetectedBox:
    x: float       # normalized [0,1] left
    y: float       # top
    w: float
    h: float
    cls: int
    score: float


def iou(a: DetectedBox, b: DetectedBox) -> float:
    x1, y1 = max(a.x, b.x), max(a.y, b.y)
    x2 = min(a.x + a.w, b.x + b.w)
    y2 = min(a.y + a.h, b.y + b.h)
    inter = max(0.0, x2 - x1) * max(0.0, y2 - y1)
    union = a.w * a.h + b.w * b.h - inter
    return inter / union if union > 0 else 0.0


def nms(boxes: List[DetectedBox], threshold: float = 0.5) -> List[DetectedBox]:
    """Greedy per-class non-max suppression (≙ reference nms in
    tensordec-boundingbox.cc)."""
    out: List[DetectedBox] = []
    for b in sorted(boxes, key=lambda b: -b.score):
        if all(o.cls != b.cls or iou(o, b) < threshold for o in out):
            out.append(b)
    return out


def draw_boxes(boxes: List[DetectedBox], width: int, height: int,
               thickness: int = 2,
               labels: Optional[List[str]] = None) -> np.ndarray:
    """Rasterize box outlines onto a transparent RGBA canvas; with
    ``labels``, print each box's class name above it (≙ the reference's
    bounding-box decoder + tensordec-font.c raster overlay)."""
    from .font import GLYPH_H, draw_text
    canvas = np.zeros((height, width, 4), np.uint8)
    for b in boxes:
        color = _PALETTE[b.cls % len(_PALETTE)]
        x0 = int(np.clip(b.x * width, 0, width - 1))
        y0 = int(np.clip(b.y * height, 0, height - 1))
        x1 = int(np.clip((b.x + b.w) * width, 0, width - 1))
        y1 = int(np.clip((b.y + b.h) * height, 0, height - 1))
        t = thickness
        canvas[y0:y0 + t, x0:x1 + 1] = color
        canvas[max(0, y1 - t + 1):y1 + 1, x0:x1 + 1] = color
        canvas[y0:y1 + 1, x0:x0 + t] = color
        canvas[y0:y1 + 1, max(0, x1 - t + 1):x1 + 1] = color
        if labels and 0 <= b.cls < len(labels):
            ty = y0 - GLYPH_H - 2
            draw_text(canvas, x0, ty if ty >= 0 else y0 + t + 1,
                      labels[b.cls], color)
    return canvas


@register_decoder
class BoundingBoxes(DecoderPlugin):
    NAME = "bounding_boxes"

    def set_options(self, options) -> None:
        super().set_options(options)
        self.mode = self.option(1) or "yolov5"
        self._labels = load_labels(self.option(2)) if self.option(2) else None
        self.out_w, self.out_h = self._parse_wh(self.option(4), (640, 480))
        self.in_w, self.in_h = self._parse_wh(self.option(5),
                                              (self.out_w, self.out_h))
        opt3 = self.option(3)
        self.conf_threshold, self.iou_threshold, self.scaled = 0.25, 0.45, False
        if self.mode in ("yolov5", "yolov8") and opt3:
            parts = opt3.split(":")
            if parts and parts[0]:
                self.scaled = parts[0] not in ("0", "false")
            if len(parts) > 1 and parts[1]:
                self.conf_threshold = float(parts[1])
            if len(parts) > 2 and parts[2]:
                self.iou_threshold = float(parts[2])
        elif self.mode in ("mobilenet-ssd", "mobilenetssd", "tflite-ssd"):
            self._parse_ssd_options(opt3)
        elif self.mode == "mp-palm-detection":
            self._parse_palm_options(opt3)

    def _parse_ssd_options(self, opt3: str) -> None:
        """option3 = <prior file>[:threshold:y_scale:x_scale:h_scale:
        w_scale:iou] (≙ mobilenetssd.cc setOptionInternal; defaults
        0.5/10/10/5/5/0.5)."""
        parts = (opt3 or "").split(":")
        if not parts or not parts[0]:
            raise ValueError(
                "mobilenet-ssd mode needs option3=<box-priors file>")
        self._priors = self._load_box_priors(parts[0])
        defaults = [0.5, 10.0, 10.0, 5.0, 5.0, 0.5]
        for i in range(6):
            if len(parts) > i + 1 and parts[i + 1]:
                defaults[i] = float(parts[i + 1])
        (self.conf_threshold, self._y_scale, self._x_scale,
         self._h_scale, self._w_scale, self.iou_threshold) = defaults

    @staticmethod
    def _load_box_priors(path: str) -> np.ndarray:
        """4 rows x N anchors (≙ mobilenet_ssd_loadBoxPrior)."""
        rows = []
        with open(path) as f:
            for line in f:
                vals = [float(v) for v in line.split()]
                if vals:
                    rows.append(vals)
        if len(rows) < 4:
            raise ValueError(
                f"{path}: box-priors file needs 4 rows, got {len(rows)}")
        return np.asarray(rows[:4], np.float32)

    def _parse_palm_options(self, opt3: str) -> None:
        """option3 = [min_score:num_layers:min_scale:max_scale:offset_x:
        offset_y:stride0:...] (≙ mppalmdetection.cc setOptionInternal)."""
        parts = [p for p in (opt3 or "").split(":")]
        def _get(i, cast, default):
            return cast(parts[i]) if len(parts) > i and parts[i] else default
        self.conf_threshold = _get(0, float, 0.5)
        num_layers = _get(1, int, 4)
        min_scale = _get(2, float, 1.0)
        max_scale = _get(3, float, 1.0)
        offset_x = _get(4, float, 0.5)
        offset_y = _get(5, float, 0.5)
        defaults = [8, 16, 16, 16]
        strides = [_get(6 + i, int,
                        defaults[i] if i < len(defaults) else defaults[-1])
                   for i in range(num_layers)]
        if not self.option(5):
            # anchors are generated for the 192x192 palm model; offsets
            # must be scaled by the same input size, not the 640x480
            # video default
            self.in_w = self.in_h = 192
        self._anchors = self._palm_anchors(num_layers, min_scale, max_scale,
                                           offset_x, offset_y, strides)
        self.iou_threshold = 0.05  # (≙ nms(results, 0.05f, ...) :367)

    @staticmethod
    def _palm_anchors(num_layers, min_scale, max_scale, offset_x, offset_y,
                      strides) -> np.ndarray:
        """SSD-style anchor grid for the 192x192 mediapipe palm model
        (≙ mp_palm_detection_generate_anchors). Rows: (x_c, y_c, w, h)."""
        def scale_for(idx):
            # NB: for the second anchor of the last layer this evaluates
            # at idx == num_layers, extrapolating past max_scale — that
            # mirrors the reference exactly (mppalmdetection.cc:173-175
            # calls _calculate_scale(last_same_stride_layer + 1, ...)),
            # which itself diverges from upstream mediapipe's
            # interpolated-scale variant. Parity wins here.
            if num_layers == 1:
                return (min_scale + max_scale) * 0.5
            return min_scale + (max_scale - min_scale) * idx / (num_layers - 1)

        anchors = []
        layer = 0
        while layer < num_layers:
            dims = []  # (w, h) per anchor at one cell
            last = layer
            while last < num_layers and strides[last] == strides[layer]:
                for s_idx in (last, last + 1):
                    sc = scale_for(s_idx)
                    dims.append((sc, sc))  # aspect ratio 1 -> w = h = scale
                last += 1
            stride = strides[layer]
            fm = int(np.ceil(192 / stride))
            for y in range(fm):
                for x in range(fm):
                    for w, h in dims:
                        anchors.append(((x + offset_x) / fm,
                                        (y + offset_y) / fm, w, h))
            layer = last
        return np.asarray(anchors, np.float32)

    @staticmethod
    def _parse_wh(opt: str, default):
        if not opt:
            return default
        w, h = opt.split(":")
        return int(w), int(h)

    def get_out_caps(self, config: TensorsConfig) -> Caps:
        rate = f"{config.rate_n}/{config.rate_d}"
        return Caps(f"video/x-raw,format=RGBA,width={self.out_w},"
                    f"height={self.out_h},framerate=(fraction){rate}")

    # -- per-mode tensor parsing (the BoxProperties analog) ---------------
    def _boxes_yolov5(self, buf: Buffer) -> List[DetectedBox]:
        """pred [N, 5+nc]: cx,cy,w,h,obj,cls... (pixel scale when
        option3 scaled=1, else normalized)."""
        pred = buf.chunks[0].host()
        pred = pred.reshape(-1, pred.shape[-1])
        scale_w = self.in_w if self.scaled else 1.0
        scale_h = self.in_h if self.scaled else 1.0
        obj = pred[:, 4]
        cls_scores = pred[:, 5:] * obj[:, None]
        cls = np.argmax(cls_scores, axis=1)
        score = cls_scores[np.arange(len(cls)), cls]
        keep = score >= self.conf_threshold
        out = []
        for p, c, s in zip(pred[keep], cls[keep], score[keep]):
            cx, cy, w, h = (p[0] / scale_w, p[1] / scale_h,
                            p[2] / scale_w, p[3] / scale_h)
            out.append(DetectedBox(cx - w / 2, cy - h / 2, w, h,
                                   int(c), float(s)))
        return nms(out, self.iou_threshold)

    def _boxes_yolov8(self, buf: Buffer) -> List[DetectedBox]:
        """pred [4+nc, N] (or [N, 4+nc]): cx,cy,w,h,cls... (no objectness)."""
        pred = buf.chunks[0].host()
        pred = pred.reshape(pred.shape[-2], pred.shape[-1]) \
            if pred.ndim > 2 else pred
        if pred.shape[0] < pred.shape[1]:
            pred = pred.T  # -> [N, 4+nc]
        scale_w = self.in_w if self.scaled else 1.0
        scale_h = self.in_h if self.scaled else 1.0
        cls_scores = pred[:, 4:]
        cls = np.argmax(cls_scores, axis=1)
        score = cls_scores[np.arange(len(cls)), cls]
        keep = score >= self.conf_threshold
        out = []
        for p, c, s in zip(pred[keep], cls[keep], score[keep]):
            cx, cy, w, h = (p[0] / scale_w, p[1] / scale_h,
                            p[2] / scale_w, p[3] / scale_h)
            out.append(DetectedBox(cx - w / 2, cy - h / 2, w, h,
                                   int(c), float(s)))
        return nms(out, self.iou_threshold)

    def _boxes_ssd_pp(self, buf: Buffer) -> List[DetectedBox]:
        """TFLite detection-postprocess convention: boxes [N,4]
        (ymin,xmin,ymax,xmax normalized), classes [N], scores [N],
        count [1] (≙ mobilenetssdpp.cc tensor order, option3 reorders).

        A SINGLE flat chunk of 6K+1 floats is the packed variant
        (zoo://ssd_mobilenet_v2?packed=1): [4K boxes][K classes]
        [K scores][1 count] — one D2H instead of four."""
        if len(buf.chunks) == 1:
            flat = buf.chunks[0].host().reshape(-1)
            if (flat.size - 1) % 6:
                raise ValueError(
                    "bounding_boxes: single-chunk ssd-postprocess input "
                    f"of {flat.size} floats is not the packed [6K+1] "
                    "layout (boxes/classes/scores/count)")
            k = (flat.size - 1) // 6
            boxes = flat[:4 * k]
            classes = flat[4 * k:5 * k]
            scores = flat[5 * k:6 * k]
            count = flat[6 * k:]
        else:
            order = [int(i) for i in self.option(3).split(":")] \
                if self.option(3) else [0, 1, 2, 3]
            chunks = [buf.chunks[i].host() for i in order]
            boxes, classes, scores, count = chunks
        n = int(count.reshape(-1)[0])
        boxes = boxes.reshape(-1, 4)
        scores = scores.reshape(-1)
        classes = classes.reshape(-1)
        n = min(n, len(boxes))
        keep = np.nonzero(scores[:n] >= self.conf_threshold)[0]
        return [DetectedBox(float(boxes[i, 1]), float(boxes[i, 0]),
                            float(boxes[i, 3] - boxes[i, 1]),
                            float(boxes[i, 2] - boxes[i, 0]),
                            int(classes[i]), float(scores[i]))
                for i in keep]

    def _boxes_mobilenet_ssd(self, buf: Buffer) -> List[DetectedBox]:
        """Raw SSD head + box-prior anchors: tensor0 = box deltas
        [N, 4], tensor1 = class logits [N, labels]
        (≙ mobilenetssd.cc _get_objects_mobilenet_ssd: per-anchor best
        class >= threshold, prior-decoded center/size, then NMS)."""
        deltas = buf.chunks[0].host().reshape(-1, 4).astype(np.float32)
        logits = buf.chunks[1].host()
        logits = logits.reshape(-1, logits.shape[-1]).astype(np.float32)
        n = min(len(deltas), len(logits), self._priors.shape[1])
        deltas, logits = deltas[:n], logits[:n]
        pr = self._priors[:, :n]  # rows: [0]=yc [1]=xc [2]=h [3]=w
        # best non-background class per anchor (class 0 is background)
        cls = np.argmax(logits[:, 1:], axis=1) + 1
        logit_best = logits[np.arange(n), cls]
        score = 1.0 / (1.0 + np.exp(-np.clip(logit_best, -100, 100)))
        keep = score >= self.conf_threshold
        yc = deltas[:, 0] / self._y_scale * pr[2] + pr[0]
        xc = deltas[:, 1] / self._x_scale * pr[3] + pr[1]
        h = np.exp(deltas[:, 2] / self._h_scale) * pr[2]
        w = np.exp(deltas[:, 3] / self._w_scale) * pr[3]
        out = [DetectedBox(float(xc[i] - w[i] / 2), float(yc[i] - h[i] / 2),
                           float(w[i]), float(h[i]), int(cls[i]),
                           float(score[i]))
               for i in np.nonzero(keep)[0]]
        return nms(out, self.iou_threshold)

    def _boxes_ov_person(self, buf: Buffer) -> List[DetectedBox]:
        """OpenVINO person-detection: one tensor of up to 200 rows of 7
        values [image_id, label, conf, x_min, y_min, x_max, y_max]
        (normalized corners). Scanning stops at the first image_id < 0;
        rows below the fixed 0.8 confidence are skipped, and kept boxes
        report class_id -1 / prob 1 — mirroring the reference exactly
        (≙ ovdetection.cc _get_persons_ov, conf threshold :19)."""
        rows = buf.chunks[0].host().reshape(-1, 7).astype(np.float32)
        out: List[DetectedBox] = []
        for r in rows[:200]:
            # int-truncating sentinel compare, like the reference's
            # `(int) desc.image_id < 0` (so -0.5 does NOT stop the scan)
            if int(r[0]) < 0:
                break
            if r[2] < 0.8:
                continue
            out.append(DetectedBox(float(r[3]), float(r[4]),
                                   float(r[5] - r[3]), float(r[6] - r[4]),
                                   -1, 1.0))
        return out

    def _boxes_mp_palm(self, buf: Buffer) -> List[DetectedBox]:
        """MediaPipe palm detection: tensor0 = boxes [N, >=4] (pixel
        offsets vs 192-input anchors), tensor1 = score logits [N]
        (≙ mppalmdetection.cc _get_objects_mp_palm_detection)."""
        boxes = buf.chunks[0].host()
        boxes = boxes.reshape(-1, boxes.shape[-1]).astype(np.float32)
        scores = buf.chunks[1].host().reshape(-1).astype(np.float32)
        n = min(len(boxes), len(scores), len(self._anchors))
        a = self._anchors[:n]  # columns: x_c, y_c, w, h
        score = 1.0 / (1.0 + np.exp(-np.clip(scores[:n], -100, 100)))
        keep = score >= self.conf_threshold
        yc = boxes[:n, 0] / self.in_h * a[:, 3] + a[:, 1]
        xc = boxes[:n, 1] / self.in_w * a[:, 2] + a[:, 0]
        h = boxes[:n, 2] / self.in_h * a[:, 3]
        w = boxes[:n, 3] / self.in_w * a[:, 2]
        out = [DetectedBox(float(xc[i] - w[i] / 2), float(yc[i] - h[i] / 2),
                           float(w[i]), float(h[i]), 0, float(score[i]))
               for i in np.nonzero(keep)[0]]
        return nms(out, self.iou_threshold)

    def decode(self, buf: Buffer) -> Optional[Buffer]:
        if self.mode == "yolov5":
            boxes = self._boxes_yolov5(buf)
        elif self.mode == "yolov8":
            boxes = self._boxes_yolov8(buf)
        elif self.mode in ("mobilenet-ssd-postprocess", "mobilenetssd-pp",
                           "tflite-ssd-postprocess"):
            boxes = self._boxes_ssd_pp(buf)
        elif self.mode in ("mobilenet-ssd", "mobilenetssd", "tflite-ssd"):
            boxes = self._boxes_mobilenet_ssd(buf)
        elif self.mode == "mp-palm-detection":
            boxes = self._boxes_mp_palm(buf)
        elif self.mode == "ov-person-detection":
            boxes = self._boxes_ov_person(buf)
        else:
            raise ValueError(f"bounding_boxes: unknown mode {self.mode!r}")
        frame = draw_boxes(boxes, self.out_w, self.out_h,
                           labels=self._labels)
        out = Buffer([Chunk(frame)])
        out.extras["boxes"] = [
            {"x": b.x, "y": b.y, "w": b.w, "h": b.h, "class": b.cls,
             "label": (self._labels[b.cls] if self._labels and
                       0 <= b.cls < len(self._labels) else str(b.cls)),
             "score": b.score}
            for b in boxes]
        return out
