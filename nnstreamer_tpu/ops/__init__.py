"""Custom TPU kernels (Pallas) for the pipeline's hot host-boundary ops.

≙ the role of the reference's Orc SIMD acceleration in tensor_transform
(gsttensor_transform.c:56-57 HAVE_ORC) — hand-tuned inner loops for the
per-element math that wraps every model invoke. Here the hand-tuning
targets the TPU's VPU via Pallas; every op carries a jnp reference
implementation used as fallback off-TPU and as the parity oracle in
tests.
"""
from .normalize import fused_normalize, normalize_reference

__all__ = ["fused_normalize", "normalize_reference"]
