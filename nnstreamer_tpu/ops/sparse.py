"""Device-side sparse pack/unpack (index+value coding of non-zeros).

SURVEY.md §7's design stance names sparse enc/dec as a custom-kernel
candidate. The kernel here is a jitted scatter, NOT Pallas — the pallas
guide's own rule: XLA's scatter/cumsum lowering is already optimal for
this access pattern, so a hand-written kernel would only add risk. What
makes it a *device* op is the contract: a device-resident activation is
packed to (indices, values, nnz) in HBM and only ``capacity`` pairs
cross the host link, instead of the dense tensor (reference analog:
gst/nnstreamer/elements/gsttensor_sparse_util.c packs on the host,
where memory is free).

Capacity is static (XLA needs static shapes): callers size it from an
expected density bound and fall back to the host path when nnz
overflows — detected from the returned nnz, never silently truncated.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def pack_reference(arr: np.ndarray):
    """Numpy oracle: (uint32 indices, values) of non-zeros, flat order."""
    flat = arr.reshape(-1)
    idx = np.flatnonzero(flat).astype(np.uint32)
    return idx, flat[idx]


@partial(jax.jit, static_argnums=(1,))
def pack(flat: jax.Array, capacity: int):
    """Pack non-zeros of ``flat`` [N] into fixed-size (idx, vals, nnz).

    Returns (idx uint32 [capacity], vals [capacity], nnz int32). Entries
    past nnz are zero; if nnz > capacity the overflow pairs are DROPPED
    (scatter mode=drop) — the caller must check nnz and fall back.
    """
    nz = flat != 0
    nnz = nz.sum().astype(jnp.int32)
    # each non-zero's output slot = its rank among non-zeros (stable)
    slot = jnp.cumsum(nz) - 1
    # zeros (and overflow ranks >= capacity) scatter out of bounds -> drop
    slot = jnp.where(nz, slot, capacity)
    idx = jnp.zeros((capacity,), jnp.uint32).at[slot].set(
        jnp.arange(flat.shape[0], dtype=jnp.uint32), mode="drop")
    vals = jnp.zeros((capacity,), flat.dtype).at[slot].set(
        flat, mode="drop")
    return idx, vals, nnz


@partial(jax.jit, static_argnums=(2,))
def unpack(idx: jax.Array, vals: jax.Array, size: int):
    """Scatter (idx, vals) back to a dense flat [size] on device.

    Padded entries (idx 0 with val 0 past nnz) are harmless: they write
    val 0 to index 0 after the real writes only if they FOLLOW them in
    scatter order — so mask them out of bounds instead, using the fact
    that a padded slot has val==0 AND would collide with slot 0.
    """
    n = idx.shape[0]
    # a pad slot is any slot whose value is zero: writing zero is a
    # no-op for correctness ONLY if index 0's real value isn't clobbered
    # -> route pad slots out of bounds (drop)
    target = jnp.where(vals != 0, idx.astype(jnp.int32), size)
    return jnp.zeros((size,), vals.dtype).at[target].set(vals, mode="drop")
