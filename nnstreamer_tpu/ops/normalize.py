"""uint8 -> scaled float Pallas kernel (the worked custom-kernel example).

Every vision pipeline runs ``(x - offset) * scale`` (typically
``x/127.5 - 1``) on each frame right after H2D; this implements it as a
VMEM-tiled Pallas kernel with a jnp oracle for parity.

Honest framing (the pallas guide's own rule: don't hand-schedule what
XLA already fuses): for THIS op, XLA's fusion into the consuming matmul
is at least as good — the zoo models fold the affine into the jitted
graph and need no kernel. ops/ exists as the extension point for ops
XLA handles poorly (custom quant codecs, windowed sparse packing), and
this file is the template: kernel + oracle + interpret-mode tests +
on-device parity.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# tile: 256 sublanes x 1024 lanes = 256 K elements per step (0.25 MB u8
# + 0.5 MB bf16) — small against the ~16 MB VMEM budget, wide enough to
# keep the VPU lanes full
_TILE_ROWS = 256
_LANES = 1024


def normalize_reference(x, scale: float, offset: float,
                        dtype=jnp.bfloat16):
    """The jnp oracle: (x - offset) * scale, cast to ``dtype``."""
    return ((x.astype(jnp.float32) - offset) * scale).astype(dtype)


def _kernel(scale: float, offset: float, out_dtype, x_ref, o_ref):
    # Mosaic has no direct u8->f32 cast; widen through int32 on the VPU
    x = x_ref[...].astype(jnp.int32).astype(jnp.float32)
    o_ref[...] = ((x - offset) * scale).astype(out_dtype)


@functools.partial(jax.jit,
                   static_argnames=("scale", "offset", "dtype", "interpret"))
def _normalize_pallas(x2d, scale: float, offset: float, dtype,
                      interpret: bool = False):
    from jax.experimental import pallas as pl

    rows = x2d.shape[0]
    tile = min(_TILE_ROWS, rows)
    grid = (rows + tile - 1) // tile
    return pl.pallas_call(
        functools.partial(_kernel, scale, offset, dtype),
        grid=(grid,),
        in_specs=[pl.BlockSpec((tile, x2d.shape[1]),
                               lambda i: (i, 0))],
        out_specs=pl.BlockSpec((tile, x2d.shape[1]), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, dtype),
        interpret=interpret,
    )(x2d)


def fused_normalize(x, scale: float = 1.0 / 127.5, offset: float = 127.5,
                    dtype=jnp.bfloat16, force_pallas: bool = False):
    """(x - offset) * scale as one fused on-chip pass.

    Accepts any rank; internally reshaped to 2D lane-aligned tiles when
    the element count allows, else padded. Uses Pallas on TPU, the jnp
    oracle elsewhere; ``force_pallas`` runs the kernel in interpret mode
    off-TPU (how tests exercise the kernel body on the CPU mesh).
    """
    platform = jax.devices()[0].platform
    interpret = False
    if platform != "tpu":
        if not force_pallas:
            return normalize_reference(x, scale, offset, dtype)
        interpret = True
    n = x.size
    # widest lane count (multiple of 128) that divides the element count
    # exactly: no padding copies on the common frame shapes
    cols = 0
    for cand in (_LANES, 512, 256, 128):
        if n % cand == 0:
            cols = cand
            break
    flat = jnp.ravel(x)
    if cols == 0:
        cols = 128
        rows = (n + cols - 1) // cols
        flat = jnp.pad(flat, (0, rows * cols - n))
    rows = flat.size // cols
    out = _normalize_pallas(flat.reshape(rows, cols),
                            float(scale), float(offset), dtype,
                            interpret=interpret)
    out = jnp.ravel(out)
    if out.size != n:
        out = out[:n]
    return out.reshape(x.shape)
