"""Fused multi-head attention — Pallas TPU kernel.

The stock attention path materializes the [B, H, S, S] score tensor in
HBM twice (write after QK^T, read for softmax·V); for ViT-B/16 at
batch 64 that is ~1.2 GB of HBM traffic per layer that never needed to
leave the chip. This kernel keeps one (batch, head)'s whole score block
in VMEM: QK^T, masked f32 softmax and PV run back to back on the
MXU/VPU with only Q/K/V in and O out touching HBM (SURVEY.md §7 Pallas
stance: hand-fuse only what XLA cannot).

Scope: non-causal full-sequence attention with sequence lengths that
fit VMEM after padding to the 128-lane tile (S_pad^2 f32 scores; fine
through S≈1024 — the ViT/encoder regime). Longer or causal decode
sequences belong to the ring/Ulysses paths (parallel/ring.py) or the
KV-cache decode loop (models/transformer.py), not here.

Drop-in: :func:`fused_attention` matches the flax
``MultiHeadDotProductAttention(attention_fn=...)`` contract
([B, S, H, D] inputs, softmax over keys), so models opt in per-module
(models/vit.py ``attn=pallas``). Non-TPU backends fall back to the
jnp reference implementation — bit-compatible up to dtype rounding —
so the same model file runs tests on CPU and the kernel on the chip.

No reference analog: the reference's backends hand attention to vendor
SDKs; on TPU the fusion boundary is ours to place.

Measured verdict (v5e, ViT-B/16 shapes: B=64, S=196, H=12, D=64,
bf16, 50-call scan chain): stock XLA 88-113 ms, this kernel 123 ms, a
head-batched variant 147 ms — **XLA's built-in attention fusion wins
at encoder shapes this small** (its pattern-matched attention keeps
scores in registers/VMEM already, without this kernel's pad/relayout).
The kernel therefore ships as an opt-in (``zoo://vit?attn=pallas``),
validated for parity, while ``attn=auto`` resolves to stock everywhere;
it earns its keep only where XLA's fusion breaks (very long S, exotic
masking) — measure before switching. ViT-B/16 MFU with stock attention:
66-68 % under clean link weather, which is the real answer to "close
the ViT MFU gap" — there was no attention-fusion gap to close.
"""
from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp


def _round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


def reference_attention(q, k, v):
    """jnp reference (and CPU fallback): f32 softmax, same contract."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    p = jax.nn.softmax(s * (d ** -0.5), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), v)


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, seq_len: int,
                 scale: float):
    # one (batch, head) per grid step: scores never leave VMEM
    q = q_ref[0]                      # [S_pad, D]
    k = k_ref[0]
    v = v_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale   # [S_pad, S_pad]
    if seq_len < s.shape[-1]:
        # padded key columns must not receive probability mass
        col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(col < seq_len, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o_ref[0] = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _fused_bshd(q, k, v, interpret: bool = False):
    from jax.experimental import pallas as pl

    b, s_len, h, d = q.shape
    s_pad = _round_up(s_len, 128)
    d_pad = _round_up(d, 128)
    scale = d ** -0.5

    def prep(x):
        # [B,S,H,D] -> [B*H, S_pad, D_pad]: grid over fused batch*heads
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s_len, d)
        return jnp.pad(x, ((0, 0), (0, s_pad - s_len), (0, d_pad - d)))

    qp, kp, vp = prep(q), prep(k), prep(v)
    spec = pl.BlockSpec((1, s_pad, d_pad), lambda i: (i, 0, 0))
    out = pl.pallas_call(
        functools.partial(_attn_kernel, seq_len=s_len, scale=scale),
        out_shape=jax.ShapeDtypeStruct((b * h, s_pad, d_pad), q.dtype),
        grid=(b * h,),
        in_specs=[spec, spec, spec],
        out_specs=spec,
        interpret=interpret,
    )(qp, kp, vp)
    out = out[:, :s_len, :d].reshape(b, h, s_len, d)
    return jnp.transpose(out, (0, 2, 1, 3))


def fused_attention(query, key, value, bias=None, mask=None,
                    *, interpret: Optional[bool] = None,
                    **unused_kwargs: Any):
    """flax ``attention_fn``-compatible fused attention.

    query/key/value: [B, S, H, D]. bias/mask are unsupported (the
    encoder models this serves are full-attention); passing one falls
    back to stock flax attention so correctness never silently changes.
    ``interpret=True`` forces the Pallas interpreter (CPU testing).
    """
    if bias is not None or mask is not None:
        import flax.linen as nn
        return nn.dot_product_attention(query, key, value, bias=bias,
                                        mask=mask)
    if interpret is None:
        if jax.devices()[0].platform != "tpu":
            return reference_attention(query, key, value)
        interpret = False
    return _fused_bshd(query, key, value, interpret=interpret)
