"""Per-tile change energy for the temporal-delta gate (tensor_delta).

The detector needs one number per ``tile x tile`` block: the mean
absolute difference between the current frame and the reference, with
channels collapsed.  That is a pure blocked reduction — exactly the
shape XLA's reshape+mean lowering is optimal for (same honest-framing
rule as ops/normalize.py and ops/sparse.py: don't hand-schedule what
the compiler already fuses), so this is a jitted jnp op, not a Pallas
kernel.  Inputs must be pre-collapsed to 2-D and pre-padded to tile
multiples; the host caller (elements/delta.py) owns the padding so the
jit cache keys stay small.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def tile_error_reference(cur: np.ndarray, ref: np.ndarray,
                         tile: int) -> np.ndarray:
    """NumPy oracle: (H/t, W/t) mean-abs-diff per tile. ``cur``/``ref``
    are 2-D with dims that are multiples of ``tile``."""
    h, w = cur.shape
    d = np.abs(cur.astype(np.float32) - ref.astype(np.float32))
    return d.reshape(h // tile, tile, w // tile, tile).mean(axis=(1, 3))


@partial(jax.jit, static_argnums=(2,))
def tile_error(cur, ref, tile: int):
    """Device twin of :func:`tile_error_reference` for device-resident
    chunks — the full frames stay in HBM; only the (H/t, W/t) error
    grid crosses D2H."""
    h, w = cur.shape
    d = jnp.abs(cur.astype(jnp.float32) - ref.astype(jnp.float32))
    return d.reshape(h // tile, tile, w // tile, tile).mean(axis=(1, 3))
