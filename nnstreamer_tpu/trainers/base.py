"""TrainerFramework: the trainer-subplugin ABI.

≙ GstTensorTrainerFramework (include/nnstreamer_plugin_api_trainer.h:31-72)
— create/destroy/start/stop/push_data/getStatus with epoch/loss/accuracy
feedback and an event notifier (EPOCH_COMPLETION, TRAINING_COMPLETION).
The reference's implementation is NNTrainer; ours is JAX/optax on TPU
(jax_trainer.py).
"""
from __future__ import annotations

import dataclasses
import enum
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence


class TrainerEvent(enum.Enum):
    EPOCH_COMPLETION = "epoch_completion"
    TRAINING_COMPLETION = "training_completion"


@dataclasses.dataclass
class TrainerProperties:
    """(ref: GstTensorTrainerProperties struct in the trainer ABI)."""

    model_config: str = ""
    model_save_path: str = ""
    model_load_path: str = ""
    num_inputs: int = 1
    num_labels: int = 1
    num_training_samples: int = 0
    num_validation_samples: int = 0
    epochs: int = 1
    # multi-chip: "DxSxT" / "auto" device mesh + sharding rule table name
    # (this framework's extension — the reference delegates device
    # placement to the NNTrainer subplugin)
    mesh: str = ""
    rules: str = ""


@dataclasses.dataclass
class TrainerStatus:
    """(ref: epoch/loss/accuracy feedback fields)."""

    epoch: int = 0
    training_loss: float = 0.0
    training_accuracy: float = 0.0
    validation_loss: float = 0.0
    validation_accuracy: float = 0.0


class TrainerFramework:
    NAME = ""

    def create(self, props: TrainerProperties) -> None:
        raise NotImplementedError

    def destroy(self) -> None:
        pass

    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass

    def push_data(self, tensors: Sequence[Any]) -> None:
        """One sample: num_inputs input tensors + num_labels label tensors.
        May block (pipeline backpressure, ≙ fw->push_data blocking,
        gsttensor_trainer.c:487-501)."""
        raise NotImplementedError

    def get_status(self) -> TrainerStatus:
        raise NotImplementedError

    def set_event_notifier(self,
                           notify: Callable[[TrainerEvent, TrainerStatus],
                                            None]) -> None:
        self._notify = notify

    def _emit(self, event: TrainerEvent, status: TrainerStatus) -> None:
        cb = getattr(self, "_notify", None)
        if cb is not None:
            cb(event, status)


_lock = threading.Lock()
_trainers: Dict[str, type] = {}


def register_trainer(cls: type) -> type:
    with _lock:
        _trainers[cls.NAME] = cls
    return cls


def find_trainer(name: str) -> type:
    with _lock:
        if name not in _trainers:
            raise ValueError(
                f"unknown trainer framework {name!r}; known: {sorted(_trainers)}")
        return _trainers[name]
