"""Training subplugins and checkpointing (L3 trainer backend)."""
from .checkpoint import restore_params, save_params

__all__ = ["restore_params", "save_params"]
