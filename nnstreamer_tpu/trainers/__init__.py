"""Training subplugins and checkpointing (trainer backend layer).

≙ the reference's trainer-subplugin slot (GstTensorTrainerFramework,
include/nnstreamer_plugin_api_trainer.h) whose implementation there is
NNTrainer; here it is JAX/optax (jax_trainer.py) with orbax checkpoints.
"""
from .base import (TrainerEvent, TrainerFramework, TrainerProperties,
                   TrainerStatus, find_trainer, register_trainer)
from .checkpoint import restore_params, save_params
from . import jax_trainer  # noqa: F401 — registers the jax trainer

__all__ = ["restore_params", "save_params", "TrainerFramework",
           "TrainerProperties", "TrainerStatus", "TrainerEvent",
           "find_trainer", "register_trainer"]
