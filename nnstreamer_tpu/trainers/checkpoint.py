"""Param checkpoint save/restore via orbax.

≙ the reference's model-save-path / model-load-path trainer properties
(ref: include/nnstreamer_plugin_api_trainer.h:35-36 — save at training end,
resume by loading). Orbax is the TPU-native answer: sharding-aware,
async-capable checkpoints.
"""
from __future__ import annotations

import os
from typing import Any


def save_params(path: str, params: Any) -> None:
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(os.path.join(path, "params"), params, force=True)


def restore_params(path: str, like: Any = None) -> Any:
    """Restore params saved by :func:`save_params`. ``like`` provides the
    target structure/shardings (restores as-saved when None).

    When ``like`` leaves are jax.Arrays their shardings are passed as
    explicit restore args, so a mesh-resident tree restores straight
    onto its mesh — no orbax "Sharding info not provided ... unsafe when
    restoring on a different topology" path, no host round trip."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    target = os.path.join(path, "params")
    if like is not None:
        import jax

        def rarg(leaf):
            if isinstance(leaf, jax.Array):
                return ocp.ArrayRestoreArgs(
                    sharding=leaf.sharding,
                    global_shape=leaf.shape,
                    dtype=leaf.dtype)
            return ocp.RestoreArgs()

        restored = ckptr.restore(
            target, item=like,
            restore_args=jax.tree_util.tree_map(rarg, like))
    else:
        restored = ckptr.restore(target)
    return restored
