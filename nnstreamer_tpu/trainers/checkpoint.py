"""Param checkpoint save/restore via orbax.

≙ the reference's model-save-path / model-load-path trainer properties
(ref: include/nnstreamer_plugin_api_trainer.h:35-36 — save at training end,
resume by loading). Orbax is the TPU-native answer: sharding-aware,
async-capable checkpoints.
"""
from __future__ import annotations

import os
from typing import Any


def save_params(path: str, params: Any) -> None:
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(os.path.join(path, "params"), params, force=True)


def restore_params(path: str, like: Any = None) -> Any:
    """Restore params saved by :func:`save_params`. ``like`` provides the
    target structure/shardings (restores as-saved when None)."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    target = os.path.join(path, "params")
    if like is not None:
        import jax
        restored = ckptr.restore(target, item=like)
    else:
        restored = ckptr.restore(target)
    return restored
