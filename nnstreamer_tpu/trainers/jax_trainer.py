"""The JAX/optax trainer subplugin — this framework's NNTrainer analog.

model-config is a python file defining::

    def get_trainer():
        # returns (loss_fn, params, optimizer)
        # loss_fn(params, inputs: list[jax.Array], labels: list[jax.Array])
        #   -> (scalar loss, scalar accuracy)
        ...

or ``zoo://<name>?...`` for a zoo classifier trained with softmax
cross-entropy. Samples pushed by tensor_trainer accumulate into
device batches; epochs run on a background thread that DRAINS the
queue each epoch (the streaming-training model of gsttensor_trainer.c:
the src replays the dataset per epoch, e.g. datareposrc epochs=N, and
the trainer consumes num-training-samples every epoch). If the stream
ends early the last complete dataset is reused for remaining epochs,
and once training finishes further pushed samples are discarded so EOS
can propagate.
Checkpoints go through orbax (trainers/checkpoint.py). With the ``mesh``
property set (``tensor_trainer mesh=4x1x2 rules=gpt``) the loop really
uses parallel/train.py: params+optimizer moments placed by the rule
table via create_train_state, the batch sharded over the ``data`` axis
via shard_batch, and make_train_step's jit letting GSPMD insert the
gradient psum/reduce-scatter collectives over ICI.
"""
from __future__ import annotations

import queue as _pyqueue
import threading
import urllib.parse
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..utils.log import logger
from .base import (TrainerEvent, TrainerFramework, TrainerProperties,
                   TrainerStatus, register_trainer)


def _zoo_classifier_trainer(name: str, **kwargs):
    """Wrap a zoo model as (loss_fn, params, optimizer) for
    cross-entropy classification (labels = int class or one-hot)."""
    import jax
    import jax.numpy as jnp
    import optax

    from ..models import zoo

    lr = float(kwargs.pop("lr", "1e-3"))  # trainer knob, not a model kwarg
    apply_fn, params, _, _ = zoo.build(name, **kwargs)

    def loss_fn(p, inputs, labels):
        logits = jax.vmap(lambda x: apply_fn(p, x))(inputs[0])
        y = labels[0]
        if y.ndim > 1 and y.shape[-1] == logits.shape[-1]:
            targets = jnp.argmax(y, axis=-1)
        else:
            targets = y.reshape(-1).astype(jnp.int32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[:, None], axis=-1).mean()
        acc = jnp.mean(jnp.argmax(logits, -1) == targets)
        return nll, acc

    return loss_fn, params, optax.adam(lr)


@register_trainer
class JaxTrainer(TrainerFramework):
    NAME = "jax"

    def __init__(self):
        self._props: Optional[TrainerProperties] = None
        self._queue: _pyqueue.Queue = _pyqueue.Queue(maxsize=256)
        self._thread: Optional[threading.Thread] = None
        self._status = TrainerStatus()
        self._status_lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._done_evt = threading.Event()
        self._eos_evt = threading.Event()
        self.params = None
        # coherent (epoch, params, opt_state) published after every
        # completed step — the ONLY state the preemption snapshot reads,
        # so a snapshot can never see params from step N with optimizer
        # moments from step N-1
        self._ckpt_lock = threading.Lock()
        self._ckpt = None
        # restore-and-resume (checkpoint/): epoch to resume AFTER, and
        # the host-side optimizer state to rebuild from
        self._resume_epoch = 0
        self._resume_opt = None

    # -- lifecycle --------------------------------------------------------
    def create(self, props: TrainerProperties) -> None:
        self._props = props
        cfg = props.model_config
        if cfg.startswith("zoo://"):
            parsed = urllib.parse.urlparse(cfg)
            kwargs = {k: v[0] for k, v in
                      urllib.parse.parse_qs(parsed.query).items()}
            name = parsed.netloc or parsed.path.lstrip("/")
            self._loss_fn, self.params, self._optimizer = \
                _zoo_classifier_trainer(name, **kwargs)
        elif cfg.endswith(".py"):
            ns: Dict[str, Any] = {}
            with open(cfg) as f:
                exec(compile(f.read(), cfg, "exec"), ns)  # noqa: S102 — user model config
            if "get_trainer" not in ns:
                raise ValueError(f"{cfg}: must define get_trainer()")
            self._loss_fn, self.params, self._optimizer = ns["get_trainer"]()
        else:
            raise ValueError(f"jax trainer cannot load model-config {cfg!r}")
        if props.model_load_path:
            from .checkpoint import restore_params
            like = self.params
            if props.mesh:
                # place the template on the mesh FIRST so the restore
                # lands directly sharded (explicit restore args, no
                # orbax topology warning, no host round trip)
                from ..parallel.mesh import mesh_from_spec
                from ..parallel.sharding import rules_by_name, shard_params
                like = shard_params(self.params,
                                    rules_by_name(props.rules or ""),
                                    mesh_from_spec(props.mesh))
            self.params = restore_params(props.model_load_path, like)

    def start(self) -> None:
        self._stop_evt.clear()
        self._done_evt.clear()
        self._eos_evt.clear()
        self._thread = threading.Thread(target=self._train_loop,
                                        name="jax-trainer", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=60.0)
            self._thread = None
        if self._props and self._props.model_save_path and \
                self.params is not None:
            from .checkpoint import save_params
            save_params(self._props.model_save_path, self.params)
            logger.info("jax trainer: saved model to %s",
                        self._props.model_save_path)

    def destroy(self) -> None:
        self._stop_evt.set()

    # -- preemption checkpoint/restore (checkpoint/) -----------------------
    def pause(self) -> None:
        """Preemption quiesce: stop at the next step boundary (the loop's
        stop-checks guarantee no partial optimizer update) and join the
        training thread so :meth:`snapshot` reads settled state. Unlike
        ``stop()`` this saves nothing to model-save-path — the snapshot
        store owns persistence on this path."""
        self._stop_evt.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=60.0)

    def snapshot(self, snap_dir: str) -> Optional[Dict]:
        """Serialize the last published (epoch, params, opt_state):
        params through the orbax path (trainers/checkpoint.py) into
        ``snap_dir``, optimizer moments host-side into the returned
        blob. Epoch semantics: ``epoch`` steps are COMPLETE; resume runs
        ``epoch+1..epochs`` — never a repeated or skipped update."""
        import jax
        with self._ckpt_lock:
            ckpt = self._ckpt
        if ckpt is None:
            # no step completed since create/restore: snapshot initial
            # params so restore still lands on a runnable model
            ckpt = (self._resume_epoch, self.params, self._resume_opt)
        epoch, params, opt_state = ckpt
        if params is None:
            return None
        import os
        from .checkpoint import save_params
        save_params(os.path.join(snap_dir, "params"), params)
        host_opt = None
        if opt_state is not None:
            host_opt = jax.device_get(opt_state)
        return {"epoch": int(epoch), "opt_state": host_opt,
                "status": vars(self.get_status())}

    def resume_from(self, state: Dict, snap_dir: str) -> None:
        """Apply a :meth:`snapshot` blob after :meth:`create` and before
        :meth:`start`: params reload through orbax (mesh-aware like the
        model-load-path route), the epoch counter resumes exactly after
        the recorded step, and the optimizer moments are handed to the
        training loop to rebuild on device."""
        import os
        from .checkpoint import restore_params
        assert self._props is not None, "resume_from requires create()"
        like = self.params
        if self._props.mesh:
            from ..parallel.mesh import mesh_from_spec
            from ..parallel.sharding import rules_by_name, shard_params
            like = shard_params(self.params,
                                rules_by_name(self._props.rules or ""),
                                mesh_from_spec(self._props.mesh))
        self.params = restore_params(os.path.join(snap_dir, "params"), like)  # racecheck: ok(resume_from runs from restore_state before start(): the training worker does not exist yet)
        self._resume_epoch = int(state.get("epoch", 0))
        self._resume_opt = state.get("opt_state")
        st = state.get("status") or {}
        with self._status_lock:
            self._status = TrainerStatus(**st) if st else TrainerStatus(
                epoch=self._resume_epoch)
        with self._ckpt_lock:
            self._ckpt = (self._resume_epoch, self.params,
                          self._resume_opt)
        logger.info("jax trainer: resuming after epoch %d",
                    self._resume_epoch)

    # -- data -------------------------------------------------------------
    def push_data(self, tensors: Sequence[Any]) -> None:
        # discard once training has finished so upstream never blocks on a
        # full queue after the last epoch (EOS must still propagate)
        while not self._stop_evt.is_set() and not self._done_evt.is_set():
            try:
                self._queue.put(list(tensors), timeout=0.5)
                return
            except _pyqueue.Full:
                continue

    def end_of_data(self) -> None:
        """Upstream EOS: no more samples will arrive. The training loop
        stops waiting on the queue and reuses the last complete dataset
        for any remaining epochs."""
        self._eos_evt.set()

    def get_status(self) -> TrainerStatus:
        with self._status_lock:
            return TrainerStatus(**vars(self._status))

    def wait_training_complete(self, timeout: Optional[float] = None) -> bool:
        return self._done_evt.wait(timeout)

    # -- training loop ----------------------------------------------------
    def _collect(self, n: int) -> Optional[List[List[np.ndarray]]]:
        samples: List[List[np.ndarray]] = []
        while len(samples) < n and not self._stop_evt.is_set():
            try:
                samples.append(self._queue.get(timeout=0.1))
            except _pyqueue.Empty:
                if self._eos_evt.is_set() and self._queue.empty():
                    break  # stream ended mid-epoch; caller reuses last set
        return samples if len(samples) == n else None

    def _train_loop(self) -> None:
        import jax
        import jax.numpy as jnp

        assert self._props is not None
        p = self._props
        n_in = p.num_inputs

        def batch_of(samples):
            cols = list(zip(*samples))
            arrays = [jnp.asarray(np.stack(c)) for c in cols]
            return arrays[:n_in], arrays[n_in:]

        opt = self._optimizer
        mesh = None
        if p.mesh:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from ..parallel import train as ptrain
            from ..parallel.mesh import mesh_from_spec
            from ..parallel.sharding import rules_by_name
            mesh = mesh_from_spec(p.mesh)
            rules = rules_by_name(p.rules or "")
            state = ptrain.create_train_state(self.params, opt, mesh, rules)
            if self._resume_opt is not None:
                # land the restored host moments directly on each fresh
                # moment's sharding; on any mismatch keep the fresh init
                # (training stays correct, momentum restarts cold)
                try:
                    state.opt_state = jax.tree_util.tree_map(
                        lambda h, l: jax.device_put(
                            jnp.asarray(h), l.sharding)
                        if hasattr(l, "sharding") else jnp.asarray(h),
                        self._resume_opt, state.opt_state)
                except (TypeError, ValueError):
                    logger.warning("jax trainer: restored optimizer state "
                                   "does not match; reinitializing moments")
            self.params = state.params
            ndp = mesh.shape.get("data", 1)

            def loss_on_batch(params, batch):
                return self._loss_fn(params, batch[0], batch[1])

            sharded_step = ptrain.make_train_step(loss_on_batch, opt,
                                                  has_aux=True)

            def shard(batch):
                n = batch[0][0].shape[0]
                spec = P("data") if ndp > 1 and n % ndp == 0 else P()
                return jax.device_put(batch, NamedSharding(mesh, spec))

            def step(params, opt_state, inputs, labels):
                nonlocal state
                state, loss, acc = sharded_step(state,
                                                shard((inputs, labels)))
                return state.params, state.opt_state, loss, acc

            opt_state = state.opt_state
        else:
            if self._resume_opt is not None:
                opt_state = jax.tree_util.tree_map(jnp.asarray,
                                                   self._resume_opt)
            else:
                # jitcheck: ok(one-shot optimizer init at train start, not per-step)
                opt_state = jax.jit(opt.init)(self.params)

            @jax.jit
            def step(params, opt_state, inputs, labels):
                (loss, acc), grads = jax.value_and_grad(
                    self._loss_fn, has_aux=True)(params, inputs, labels)
                updates, opt_state = opt.update(grads, opt_state, params)
                import optax
                params = optax.apply_updates(params, updates)
                return params, opt_state, loss, acc

        @jax.jit
        def evaluate(params, inputs, labels):
            return self._loss_fn(params, inputs, labels)

        try:
            train: Optional[List[List[np.ndarray]]] = None
            val: Optional[List[List[np.ndarray]]] = None
            for epoch in range(self._resume_epoch + 1, p.epochs + 1):
                if self._stop_evt.is_set():
                    return
                # drain this epoch's samples from the stream; on a short
                # stream (src stopped replaying) reuse the previous epoch's
                t = self._collect(p.num_training_samples)
                if self._stop_evt.is_set():
                    return  # stop requested mid-collection: no extra step
                if t is not None:
                    train = t
                    if p.num_validation_samples:
                        v = self._collect(p.num_validation_samples)
                        if v is not None:
                            val = v
                if train is None:
                    logger.warning("jax trainer: stream ended before a full "
                                   "training set arrived; aborting")
                    return
                inputs, labels = batch_of(train)
                self.params, opt_state, loss, acc = step(
                    self.params, opt_state, inputs, labels)
                vloss = vacc = 0.0
                if val:
                    vi, vl = batch_of(val)
                    vloss, vacc = (float(x) for x in
                                   evaluate(self.params, vi, vl))
                with self._status_lock:
                    self._status = TrainerStatus(
                        epoch, float(loss), float(acc), vloss, vacc)
                # publish the step-coherent checkpoint tuple the
                # preemption snapshot reads — epoch N fully applied
                with self._ckpt_lock:
                    self._ckpt = (epoch, self.params, opt_state)
                self._emit(TrainerEvent.EPOCH_COMPLETION, self.get_status())
            self._emit(TrainerEvent.TRAINING_COMPLETION, self.get_status())
        except Exception:  # noqa: BLE001
            logger.exception("jax trainer loop failed")
        finally:
            self._done_evt.set()
