"""Parent-side replica process management.

:class:`ReplicaSpec` describes how to launch one replica of the fleet
(the launch-description template plus checkpoint/cache roots);
:class:`ReplicaProcess` owns one child built from it — spawn, readiness,
preemption (SIGTERM → drain → snapshot → exit 0), and the machine-
readable markers the child prints (see :mod:`.replica_main`).

The process boundary is deliberate: a replica is a *real* unit of
preemptible capacity — its own interpreter, its own JAX runtime, its
own snapshot directory — exactly what the subprocess dryrun scaffold
(parallel/dryrun.py) established for multi-process validation. The
autoscaler composes these into a fleet.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..utils.log import logger


def _repo_root() -> str:
    import nnstreamer_tpu
    return os.path.dirname(os.path.dirname(
        os.path.abspath(nnstreamer_tpu.__file__)))


@dataclass
class ReplicaSpec:
    """How to build one replica. ``desc_template`` is a launch
    description with ``{port}``, ``{ident}``, ``{ckpt}`` and
    ``{version}`` placeholders — e.g.::

        tensor_serve_src name=src port={port} id=7 connect-type=HYBRID
          topic=fleet dest-port=4100 version={version}
          ! tensor_filter framework=jax model=zoo://mlp
          ! tensor_serve_sink id=7
    """

    desc_template: str
    ckpt_root: str
    grace_s: float = 2.0
    compile_cache: str = ""
    prelude: str = ""
    version: str = ""
    ready_timeout_s: float = 120.0
    env: Dict[str, str] = field(default_factory=dict)


class ReplicaProcess:
    """One live (or resurrectable) replica child process."""

    def __init__(self, spec: ReplicaSpec, ident: str, port: int = 0,
                 version: Optional[str] = None, restore: bool = False):
        self.spec = spec
        self.ident = ident
        self.port = int(port)  # 0 until the child reports its bound port
        self.version = spec.version if version is None else str(version)
        self.restore = bool(restore)
        self.proc: Optional[subprocess.Popen] = None
        self.pid = 0
        self.preempt_report: Optional[Dict] = None
        self._ready = threading.Event()
        self._lines: List[str] = []
        self._llock = threading.Lock()

    # -- identity ----------------------------------------------------------
    @property
    def ckpt_dir(self) -> str:
        return os.path.join(self.spec.ckpt_root, self.ident)

    def key(self, host: str = "localhost") -> str:
        """The router's replica key for this endpoint."""
        return f"{host}:{self.port}"

    # -- lifecycle ---------------------------------------------------------
    def spawn(self) -> "ReplicaProcess":
        desc = self.spec.desc_template.format(
            port=self.port, ident=self.ident, ckpt=self.ckpt_dir,
            version=self.version)
        argv = [sys.executable, "-m", "nnstreamer_tpu.fleet.replica_main",
                "--desc", desc, "--ckpt", self.ckpt_dir,
                "--grace-s", str(float(self.spec.grace_s))]
        if self.restore:
            argv.append("--restore")
        if self.spec.compile_cache:
            argv += ["--compile-cache", self.spec.compile_cache]
        if self.spec.prelude:
            argv += ["--prelude", self.spec.prelude]
        root = _repo_root()
        env = dict(os.environ)
        env.setdefault("PYTHONPATH", root)
        if self.spec.compile_cache:
            from .cache import ENV_VAR
            env[ENV_VAR] = self.spec.compile_cache
        env.update(self.spec.env)
        os.makedirs(self.ckpt_dir, exist_ok=True)
        self._ready.clear()
        self.preempt_report = None  # racecheck: ok(reset before this incarnation's reader thread exists; only that reader writes it afterwards)
        self.proc = subprocess.Popen(
            argv, cwd=root, env=env, text=True,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        threading.Thread(target=self._reader, args=(self.proc,),
                         name=f"replica-out:{self.ident}",
                         daemon=True).start()
        return self

    def _reader(self, proc: subprocess.Popen) -> None:
        # one reader per child life: parses the stdout markers and keeps
        # a bounded tail for post-mortems
        assert proc.stdout is not None
        for line in proc.stdout:
            line = line.rstrip("\n")
            with self._llock:
                self._lines.append(line)
                if len(self._lines) > 400:
                    del self._lines[:200]
            if line.startswith("replica-ready "):
                for tok in line.split()[1:]:
                    k, _, v = tok.partition("=")
                    if k == "port" and v.isdigit():
                        self.port = int(v)
                    elif k == "pid" and v.isdigit():
                        self.pid = int(v)
                self._ready.set()
            elif line.startswith("replica-preempted "):
                try:
                    self.preempt_report = json.loads(
                        line.split(" ", 1)[1])
                except ValueError:
                    self.preempt_report = {}

    def wait_ready(self, timeout: Optional[float] = None) -> int:
        """Block until the child printed ``replica-ready``; returns its
        bound port. Raises on timeout or child death (with the tail)."""
        deadline = time.monotonic() + (self.spec.ready_timeout_s
                                       if timeout is None else timeout)
        while not self._ready.wait(0.1):
            if self.proc is not None and self.proc.poll() is not None:
                raise RuntimeError(
                    f"replica {self.ident} died before ready "
                    f"(rc={self.proc.returncode}):\n{self.tail()}")
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"replica {self.ident} not ready in time:\n"
                    f"{self.tail()}")
        return self.port

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def ready(self) -> bool:
        """True once the child reported ``replica-ready`` this life."""
        return self._ready.is_set()

    def preempt(self, timeout: float = 30.0) -> Optional[Dict]:
        """SIGTERM → PreemptGuard (drain + snapshot) → exit 0. Returns
        the child's preempt report (None if it died reportless)."""
        if self.proc is None or self.proc.poll() is not None:
            return self.preempt_report
        try:
            self.proc.send_signal(signal.SIGTERM)
        except OSError:
            return self.preempt_report
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            logger.warning("replica %s ignored SIGTERM for %.1fs; killing",
                           self.ident, timeout)
            self.kill()
        return self.preempt_report

    def kill(self) -> None:
        """Unconditional teardown (chaos / cleanup): no drain, no
        snapshot beyond whatever the guard already published."""
        if self.proc is None:
            return
        try:
            self.proc.kill()
            self.proc.wait(timeout=10)
        except (OSError, subprocess.TimeoutExpired):
            pass

    def tail(self, n: int = 40) -> str:
        with self._llock:
            return "\n".join(self._lines[-n:])
