"""Autoscaler control plane: the fleet acts on the health it reports.

The :class:`Autoscaler` closes the loop between the telemetry plane and
fleet size. Signals come from two existing sources — the per-replica
occupancy loads the router collects from PONG heartbeats (including the
``queue_delay_us_p95`` tail the scheduler piggybacks), and optionally an
aggregate ``/metrics`` scrape — and actuation uses only existing verbs:

* **scale up** — spawn a :class:`~.replica.ReplicaProcess` (subprocess
  replica on the ``parallel/dryrun.py`` scaffold); the persistent
  compile cache (:mod:`.cache`) makes it warm before it REGISTERs;
* **scale down** — *preempt* the least-loaded replica: router
  ``drain_replica()`` settlement first, then SIGTERM → ``PreemptGuard``
  → snapshot → exit 0. Every scale-down exercises the resurrect path's
  write side, not just chaos runs;
* **resurrect** — an unexpectedly dead replica respawns from its own
  snapshot directory at the same endpoint (``--restore``), advertising
  ``restored_sessions`` so the router counts the resurrection.

Replica lifecycle accounting is a conservation identity (flowcheck
``fleet-replica-lifecycle``, declared in analysis/flow/registry.py and
provable from this file's counter productions)::

    replicas_spawned == replicas_serving + replicas_draining
                        + replicas_retired + replicas_resurrecting

Every transition below moves exactly one unit between the right-hand
terms (or mints a ``spawned`` with its initial state), so the identity
holds at *every* quiescent point — scale-up, scale-down, rollout, and
death included. ``check()`` asserts it over the live snapshot via
:func:`~..analysis.flow.runtime.check_identities`.
"""
from __future__ import annotations

import contextlib
import threading
import time
import weakref
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..pipeline.element import Element
from ..pipeline.registry import register_element
from ..utils.atomic import Counters
from ..utils.log import logger
from .replica import ReplicaProcess, ReplicaSpec

# states of the per-replica lifecycle (the identity's RHS vocabulary)
SERVING = "serving"
DRAINING = "draining"
RESURRECTING = "resurrecting"

# live autoscalers, exposed to obs/metrics.py's render()
_LIVE: "weakref.WeakSet[Autoscaler]" = weakref.WeakSet()


def live_autoscalers() -> List["Autoscaler"]:
    return list(_LIVE)


@dataclass
class AutoscalerConfig:
    """Control-law knobs. ``target_delay_ms`` is the p95 queue-delay
    ceiling; the fleet grows while the tail is above it and shrinks
    (to ``min_replicas``) while under ``low_water`` of it."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_delay_ms: float = 50.0
    low_water: float = 0.3
    interval_s: float = 0.25
    scale_up_cooldown_s: float = 1.0
    scale_down_cooldown_s: float = 3.0
    drain_deadline_ms: float = 2000.0
    metrics_url: str = ""  # "host:port" of a MetricsServer to scrape
    resurrect: bool = True


class Autoscaler:
    """Fleet-size control loop over preemptible subprocess replicas."""

    def __init__(self, spec: ReplicaSpec, router=None,
                 config: Optional[AutoscalerConfig] = None,
                 name: str = "autoscaler",
                 stats: Optional[Counters] = None):
        self.spec = spec
        self.router = router  # FleetRouter or TensorServeRouter element
        self.cfg = config or AutoscalerConfig()
        self.name = name
        self.stats = stats if stats is not None else Counters()
        self.stats.update({
            "replicas_spawned": 0, "replicas_serving": 0,
            "replicas_draining": 0, "replicas_retired": 0,
            "replicas_resurrecting": 0,
            "scale_ups": 0, "scale_downs": 0, "resurrections": 0,
            "rollouts": 0})
        self._replicas: Dict[str, ReplicaProcess] = {}
        self._state: Dict[str, str] = {}
        self._lock = threading.RLock()
        self._next_id = 0
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_up = 0.0
        self._last_down = 0.0
        self._hold = 0
        _LIVE.add(self)

    # -- plumbing ----------------------------------------------------------
    def _router(self):
        # accept the element wrapper or the embeddable core
        return getattr(self.router, "router", self.router)

    def replicas(self) -> Dict[str, str]:
        """ident -> lifecycle state snapshot."""
        with self._lock:
            return dict(self._state)

    def handle(self, ident: str) -> Optional[ReplicaProcess]:
        with self._lock:
            return self._replicas.get(ident)

    def lifecycle(self) -> Dict[str, int]:
        return self.stats.snapshot()

    def check(self) -> None:
        """Assert the replica-lifecycle conservation identity over the
        live counters (raises AssertionError with the breakdown)."""
        from ..analysis.flow.runtime import check_identities
        check_identities(self.stats.snapshot(),
                         names=["fleet-replica-lifecycle"])

    @contextlib.contextmanager
    def hold_scaling(self):
        """Suspend the control law (reaping and resurrection-promotion
        continue). A blue/green rollout holds this while it carries
        surge capacity — otherwise the scale-down path reads the surged
        fleet as surplus and preempts a replica out from under the
        rollout's own ledger."""
        with self._lock:
            self._hold += 1
        try:
            yield
        finally:
            with self._lock:
                self._hold -= 1

    # -- lifecycle transitions (the identity's production sites) -----------
    def spawn_replica(self, version: Optional[str] = None,
                      wait: bool = True) -> str:
        """Scale-up unit: one fresh replica. Counts ``spawned`` +
        ``serving`` (a spawn that dies before ready retires)."""
        with self._lock:
            self._next_id += 1
            ident = f"{self.name}-r{self._next_id}"
            rp = ReplicaProcess(self.spec, ident, version=version)
            self._replicas[ident] = rp
            self._state[ident] = SERVING
            self.stats.add(replicas_spawned=1, replicas_serving=1)
        try:
            rp.spawn()
            if wait:
                rp.wait_ready()
        except Exception:
            rp.kill()
            with self._lock:
                self._replicas.pop(ident, None)
                self._state.pop(ident, None)
                self.stats.add(replicas_serving=-1, replicas_retired=1)
            raise
        logger.info("%s: scaled up: %s on port %d", self.name, ident,
                    rp.port)
        return ident

    def retire_replica(self, ident: str, sync: bool = True) -> bool:
        """Scale-down unit: drain (router settlement) then preempt.
        ``sync=False`` runs the drain+preempt on a worker thread; the
        control loop reaps the exit into ``retired``."""
        with self._lock:
            rp = self._replicas.get(ident)
            if rp is None or self._state.get(ident) != SERVING:
                return False
            self._state[ident] = DRAINING
            self.stats.add(replicas_serving=-1, replicas_draining=1)
        if sync:
            self._drain_and_preempt(rp)
            self._reap(ident, rp)
        else:
            threading.Thread(target=self._drain_and_preempt, args=(rp,),
                             name=f"fleet-drain:{ident}",
                             daemon=True).start()
        return True

    def _drain_and_preempt(self, rp: ReplicaProcess) -> None:
        rt = self._router()
        key = rp.key()
        if rt is not None:
            try:
                rt.drain_replica(key)
                deadline = time.monotonic() + \
                    float(self.cfg.drain_deadline_ms) / 1e3
                while time.monotonic() < deadline:
                    info = rt.report().get(key) or {}
                    if not int(info.get("in_flight", 0)):
                        break  # settlement reached: nothing unsettled
                    time.sleep(0.02)
            except Exception:
                logger.warning("%s: drain of %s failed; preempting anyway",
                               self.name, rp.ident, exc_info=True)
        rp.preempt()

    def _retire_exit(self, ident: str, was: str) -> None:
        """Book one replica's exit into ``retired`` from whichever
        state it died in — the single place the identity's sink term is
        produced."""
        with self._lock:
            if was == SERVING:
                self.stats.add(replicas_serving=-1, replicas_retired=1)
            elif was == DRAINING:
                self.stats.add(replicas_draining=-1, replicas_retired=1)
            elif was == RESURRECTING:
                self.stats.add(replicas_resurrecting=-1,
                               replicas_retired=1)

    def _resurrect(self, ident: str, dead: ReplicaProcess) -> None:
        """Respawn an unexpectedly-dead replica from its snapshot at
        the same endpoint. Counts a NEW ``spawned`` in ``resurrecting``
        until the child reports ready."""
        rp = ReplicaProcess(self.spec, ident, port=dead.port,
                            version=dead.version, restore=True)
        with self._lock:
            self._replicas[ident] = rp
            self._state[ident] = RESURRECTING
            self.stats.add(replicas_spawned=1, replicas_resurrecting=1)
            self.stats.inc("resurrections")
        try:
            rp.spawn()
        except Exception:
            logger.warning("%s: resurrect spawn of %s failed", self.name,
                           ident, exc_info=True)
            with self._lock:
                self._state.pop(ident, None)
                self._replicas.pop(ident, None)
                self.stats.add(replicas_resurrecting=-1,
                               replicas_retired=1)

    def _reap(self, ident: str, rp: ReplicaProcess) -> None:
        """One replica process exited: settle its lifecycle state."""
        with self._lock:
            was = self._state.pop(ident, None)
            self._replicas.pop(ident, None)
        if was is None:
            return
        if was == SERVING and self.cfg.resurrect \
                and not self._stop_evt.is_set():
            # death while serving is NOT the scale-down path: book the
            # corpse retired, then resurrect as a fresh spawned unit
            self._retire_exit(ident, was)
            logger.warning("%s: replica %s died unexpectedly; "
                           "resurrecting from %s", self.name, ident,
                           rp.ckpt_dir)
            self._resurrect(ident, rp)
            return
        self._retire_exit(ident, was)

    # -- signals -----------------------------------------------------------
    def observe(self) -> Dict[str, float]:
        """One control-law input sample: worst per-replica p95 queue
        delay (PONG loads via the router), total reported depth, and —
        when ``metrics_url`` is set — the aggregate p95 from a
        ``/metrics`` scrape (max of the two wins: either signal over
        target means the fleet is late)."""
        p95_us = 0.0
        depth = 0
        rt = self._router()
        if rt is not None:
            try:
                for info in rt.report().values():
                    if info.get("state") not in ("healthy", "suspect"):
                        continue
                    load = info.get("load") or {}
                    d = load.get("queue_delay_us_p95",
                                 load.get("queue_delay_us_p50", 0.0))
                    p95_us = max(p95_us, float(d or 0.0))
                    depth += int(load.get("depth", 0) or 0)
                    depth += int(info.get("in_flight", 0) or 0)
            except Exception:
                logger.warning("%s: router report failed", self.name,
                               exc_info=True)
        if self.cfg.metrics_url:
            p95_us = max(p95_us, self._scrape_p95_us())
        with self._lock:
            serving = sum(1 for s in self._state.values() if s == SERVING)
            resurrecting = sum(1 for s in self._state.values()
                               if s == RESURRECTING)
        return {"p95_ms": p95_us / 1e3, "depth": float(depth),
                "serving": float(serving),
                "resurrecting": float(resurrecting)}

    def _scrape_p95_us(self) -> float:
        from ..obs.metrics import parse as parse_metrics
        from ..obs.server import scrape
        host, _, port = str(self.cfg.metrics_url).rpartition(":")
        try:
            text = scrape(host or "localhost", int(port))
        except (OSError, ValueError):
            return 0.0
        worst = 0.0
        for (mname, labels), val in parse_metrics(text).items():
            if mname == "nns_serve_queue_delay_us" \
                    and ("quantile", "p95") in labels:
                worst = max(worst, float(val))
        return worst

    # -- the control loop --------------------------------------------------
    def step(self, now: Optional[float] = None) -> Dict[str, float]:
        """One deterministic control-loop iteration: reap exits, sample
        signals, act. Public so tests drive the loop without the
        thread; returns the observation it acted on."""
        now = time.monotonic() if now is None else now
        with self._lock:
            snap = list(self._replicas.items())
        for ident, rp in snap:
            with self._lock:
                state = self._state.get(ident)
            if state == RESURRECTING and rp.ready() and rp.alive():
                with self._lock:
                    if self._state.get(ident) == RESURRECTING:
                        self._state[ident] = SERVING
                        self.stats.add(replicas_resurrecting=-1,
                                       replicas_serving=1)
                        logger.info("%s: replica %s resurrected and "
                                    "serving", self.name, ident)
            elif not rp.alive():
                self._reap(ident, rp)
        obs = self.observe()
        with self._lock:
            if self._hold > 0:
                return obs  # a rollout owns fleet shape right now
        cfg = self.cfg
        capacity = obs["serving"] + obs["resurrecting"]
        if obs["p95_ms"] > cfg.target_delay_ms \
                and capacity < cfg.max_replicas \
                and now - self._last_up >= cfg.scale_up_cooldown_s:
            with self._lock:
                self._last_up = now
                self.stats.inc("scale_ups")
            try:
                self.spawn_replica()
            except Exception:
                logger.warning("%s: scale-up failed", self.name,
                               exc_info=True)
        elif obs["p95_ms"] < cfg.low_water * cfg.target_delay_ms \
                and obs["depth"] == 0 \
                and obs["serving"] > cfg.min_replicas \
                and now - max(self._last_up, self._last_down) \
                >= cfg.scale_down_cooldown_s:
            victim = self._least_loaded_serving()
            if victim is not None:
                with self._lock:
                    self._last_down = now
                    self.stats.inc("scale_downs")
                logger.info("%s: scaling down: preempting %s", self.name,
                            victim)
                self.retire_replica(victim, sync=False)
        elif obs["serving"] < cfg.min_replicas and not obs["resurrecting"]:
            # floor repair (a retire raced a death, or startup shortfall)
            try:
                self.spawn_replica()
            except Exception:
                logger.warning("%s: floor-repair spawn failed", self.name,
                               exc_info=True)
        return obs

    def _least_loaded_serving(self) -> Optional[str]:
        rt = self._router()
        report = {}
        if rt is not None:
            try:
                report = rt.report()
            except Exception:
                report = {}

        def load_of(rp: ReplicaProcess) -> float:
            info = report.get(rp.key()) or {}
            load = info.get("load") or {}
            return (float(info.get("in_flight", 0) or 0)
                    + float(load.get("depth", 0) or 0))

        with self._lock:
            serving = [(ident, self._replicas[ident])
                       for ident, s in self._state.items() if s == SERVING]
        if not serving:
            return None
        return min(serving, key=lambda kv: load_of(kv[1]))[0]

    # -- thread lifecycle --------------------------------------------------
    def start(self) -> "Autoscaler":
        self._stop_evt.clear()
        for _ in range(int(self.cfg.min_replicas)):
            self.spawn_replica()
        self._thread = threading.Thread(target=self._loop,
                                        name=f"autoscaler:{self.name}",
                                        daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop_evt.wait(float(self.cfg.interval_s)):
            try:
                self.step()
            except Exception:
                logger.warning("%s: control step failed", self.name,
                               exc_info=True)

    def stop(self) -> None:
        """Quiesce the loop, then preempt every replica through the
        same drain-first scale-down path (identity holds at exit)."""
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        while True:
            with self._lock:
                idents = [i for i, s in self._state.items()
                          if s in (SERVING, RESURRECTING)]
            if not idents:
                break
            for ident in idents:
                with self._lock:
                    rp = self._replicas.get(ident)
                    state = self._state.get(ident)
                if rp is None:
                    continue
                if state == SERVING:
                    self.retire_replica(ident, sync=True)
                else:  # resurrecting: nothing to drain, just preempt
                    rp.preempt()
                    self._reap(ident, rp)
        # whatever is mid-drain on worker threads: wait for the exits
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with self._lock:
                left = [(i, r) for i, r in self._replicas.items()]
            if not left:
                break
            for ident, rp in left:
                if not rp.alive():
                    self._reap(ident, rp)
            time.sleep(0.05)


@register_element("tensor_autoscaler")
class TensorAutoscaler(Element):
    """Elastic-fleet control element: owns an :class:`Autoscaler` that
    spawns/preempts subprocess replicas built from ``desc-template``,
    steering on the router element named by ``router`` and/or a
    ``metrics-url`` scrape. Pad-less — it is a control-plane element,
    not a dataflow one (launch it beside the router)::

        tensor_serve_router name=rt topic=fleet dest-port=4100
        tensor_autoscaler router=rt min-replicas=1 max-replicas=4
          target-delay-ms=50 desc-template="tensor_serve_src ..."
    """

    PROPS = {
        # the tensor_serve_router element (by name) whose PONG loads
        # feed the control law and whose drain_replica() settles
        # scale-downs; "" = metrics-url only
        "router": "",
        # fleet size bounds (lint rejects min > max)
        "min-replicas": 1, "max-replicas": 4,
        # p95 queue-delay ceiling the fleet defends, and the fraction
        # of it under which capacity is surplus
        "target-delay-ms": 50.0, "low-water": 0.3,
        # control-loop cadence and anti-flap cooldowns
        "interval-ms": 250.0, "scale-up-cooldown-ms": 1000.0,
        "scale-down-cooldown-ms": 3000.0,
        # settlement budget between drain_replica() and SIGTERM
        # (lint rejects <= 0)
        "drain-deadline-ms": 2000.0,
        # optional aggregate signal: "host:port" of a MetricsServer
        "metrics-url": "",
        # replica recipe: launch template ({port}/{ident}/{ckpt}/
        # {version}), snapshot root, preemption grace, compile cache
        "desc-template": "", "ckpt-root": "", "grace-s": 2.0,
        "compile-cache": "",
        # model/config version stamped on spawned replicas (blue/green
        # rollouts spawn the new version, then retire the old ring)
        "version": "",
        # resurrect unexpectedly-dead replicas from their snapshots
        "resurrect": True}

    # conservation identity flowcheck proves statically over this
    # package and check_identities() asserts over live snapshots
    SETTLEMENT_IDENTITY = ("fleet-replica-lifecycle",)

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.autoscaler: Optional[Autoscaler] = None

    def _router_element(self):
        pipe = getattr(self, "pipeline", None)
        if pipe is None or not str(self.router):
            return None
        return pipe.elements.get(str(self.router))

    def start(self) -> None:
        if str(self.desc_template):
            import tempfile
            ckpt_root = str(self.ckpt_root) or tempfile.mkdtemp(
                prefix=f"fleet-{self.name}-")
            spec = ReplicaSpec(
                desc_template=str(self.desc_template),
                ckpt_root=ckpt_root, grace_s=float(self.grace_s),
                compile_cache=str(self.compile_cache),
                version=str(self.version))
            cfg = AutoscalerConfig(
                min_replicas=int(self.min_replicas),
                max_replicas=int(self.max_replicas),
                target_delay_ms=float(self.target_delay_ms),
                low_water=float(self.low_water),
                interval_s=float(self.interval_ms) / 1e3,
                scale_up_cooldown_s=float(self.scale_up_cooldown_ms) / 1e3,
                scale_down_cooldown_s=(
                    float(self.scale_down_cooldown_ms) / 1e3),
                drain_deadline_ms=float(self.drain_deadline_ms),
                metrics_url=str(self.metrics_url),
                resurrect=bool(self.resurrect))
            self.autoscaler = Autoscaler(
                spec, router=self._router_element(), config=cfg,
                name=self.name, stats=self.stats)
            self.autoscaler.start()
        super().start()

    def stop(self) -> None:
        if self.autoscaler is not None:
            self.autoscaler.stop()
            self.autoscaler = None
        super().stop()

    def session_info(self) -> Dict:
        if self.autoscaler is None:
            return {}
        return {"replicas": self.autoscaler.replicas()}
