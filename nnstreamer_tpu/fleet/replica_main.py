"""Fleet replica child entry: one serve pipeline per process.

``python -m nnstreamer_tpu.fleet.replica_main --desc '...' --ckpt DIR``
builds the pipeline from a launch description, optionally restores it
from its snapshot directory (the resurrect path), installs the SIGTERM
:class:`~..fault.preempt.PreemptGuard` (preemptible by default — the
autoscaler's scale-down IS a preemption), and parks. The parent-side
:class:`~.replica.ReplicaProcess` drives it entirely through the
process boundary:

* stdout markers — ``replica-ready port=N pid=P`` once serving, and
  ``replica-preempted {json report}`` as the guard's last words, so the
  parent can audit the exact drain/abandoned accounting of every
  scale-down;
* signals — SIGTERM is the one and only scale-down/rollout verb.

The ``--compile-cache`` directory (or an inherited ``NNS_COMPILE_CACHE``
env) installs the fleet's persistent compile cache before the pipeline
is built, so the filter prewarns its jit signatures before the serve
src REGISTERs on the broker — readiness means *warm*.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _bound_port(pipe) -> int:
    for elem in pipe.elements.values():
        port = getattr(elem, "bound_port", None)
        if port:
            return int(port)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="nnstreamer_tpu.fleet.replica_main",
        description="one fleet replica: launch, serve, preempt on SIGTERM")
    ap.add_argument("--desc", required=True,
                    help="pipeline launch description")
    ap.add_argument("--ckpt", required=True,
                    help="snapshot directory (PreemptGuard target; "
                         "--restore resurrects from it)")
    ap.add_argument("--grace-s", type=float, default=2.0,
                    help="preemption grace budget (drain + snapshot)")
    ap.add_argument("--restore", action="store_true",
                    help="restore from the latest snapshot before start")
    ap.add_argument("--compile-cache", default="",
                    help="persistent compile cache root (also inherited "
                         "via NNS_COMPILE_CACHE)")
    ap.add_argument("--prelude", default="",
                    help="python snippet run before parse_launch (e.g. "
                         "register_custom_easy for test filters)")
    args = ap.parse_args(argv)

    if args.compile_cache:
        from . import cache
        cache.install(args.compile_cache)
    if args.prelude:
        # the autoscaler owns both ends of this string; it exists so
        # tests can register custom-easy filters inside the child
        exec(compile(args.prelude, "<replica-prelude>", "exec"), {})

    from .. import parse_launch
    from ..fault.preempt import install_sigterm

    pipe = parse_launch(args.desc)
    if args.restore:
        try:
            pipe.restore(args.ckpt)
        except Exception as exc:  # no/bad snapshot: cold start, say so
            print(f"replica-restore-skipped {exc!r}", flush=True)

    def last_words(report) -> None:
        # machine-readable settlement accounting for the parent: the
        # chaos arm asserts drained/abandoned against router settlement
        print("replica-preempted " + json.dumps(report or {}), flush=True)

    install_sigterm(pipe, args.ckpt, grace_s=float(args.grace_s),
                    exit_code=0, on_done=last_words)
    pipe.start()
    print(f"replica-ready port={_bound_port(pipe)} pid={os.getpid()}",
          flush=True)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pipe.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
