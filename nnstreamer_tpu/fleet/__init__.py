"""Elastic fleet: autoscaler control plane, preemptible replicas, and
zero-downtime blue/green rollouts.

The layer that closes the loop between the telemetry plane (router PONG
loads, ``/metrics``) and fleet size: replicas are subprocess serve
pipelines that are **preemptible by default** (SIGTERM → PreemptGuard →
snapshot → router drain settlement), scale-down *is* a preemption, and
an unexpected death resurrects from its own snapshot. The persistent
compile cache keeps every spawn warm before it advertises readiness.

See ``Documentation/robustness.md`` ("Elastic fleet") for the ladder
rung and the grace-budget math; ``tests/test_fleet.py`` is the chaos
harness driving all of it.
"""
from .autoscaler import (Autoscaler, AutoscalerConfig, DRAINING,
                         RESURRECTING, SERVING, live_autoscalers)
from .cache import CompileCache, active as active_compile_cache, \
    deactivate as deactivate_compile_cache, install as install_compile_cache
from .replica import ReplicaProcess, ReplicaSpec
from .rollout import BlueGreenRollout, rollout

__all__ = [
    "Autoscaler", "AutoscalerConfig",
    "SERVING", "DRAINING", "RESURRECTING",
    "live_autoscalers",
    "ReplicaProcess", "ReplicaSpec",
    "BlueGreenRollout", "rollout",
    "CompileCache", "install_compile_cache", "active_compile_cache",
    "deactivate_compile_cache",
]
