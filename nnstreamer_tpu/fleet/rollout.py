"""Zero-downtime blue/green rollouts over the autoscaler's fleet.

A rollout replaces the serving ring with replicas on a new model
version without ever dropping below the starting capacity and without
losing a frame:

1. **surge**: spawn one replica on the new version (the compile cache
   and ``--restore``-free cold path; ``wait_ready`` + routability mean
   it is warm and dialed before anything is taken away);
2. **steer**: ``drain_replica()`` one old-version replica — the
   consistent-hash ring drops it, so its affinity sessions remap to
   survivors (which now include green capacity) while its in-flight
   requests settle normally;
3. **retire**: preempt the drained replica (SIGTERM → snapshot →
   exit 0) and repeat until no old-version replica serves.

Throughout, the router settlement identity
``router_requests == delivered + shed + orphaned`` keeps holding (the
rollout only uses drain + preempt, both settlement-preserving), and the
fleet's ``replicas_spawned == serving + draining + retired +
resurrecting`` identity books every replacement — the bench/chaos arms
assert both via :func:`~..analysis.flow.runtime.check_identities`.
"""
from __future__ import annotations

import time
from typing import Dict, Optional

from ..utils.log import logger
from .autoscaler import SERVING, Autoscaler


class BlueGreenRollout:
    """One fleet-wide version swap, driven step-by-step."""

    def __init__(self, autoscaler: Autoscaler, version: str,
                 routable_timeout_s: float = 30.0):
        self.autoscaler = autoscaler
        self.version = str(version)
        self.routable_timeout_s = float(routable_timeout_s)

    # -- helpers -----------------------------------------------------------
    def _old_serving(self) -> list:
        auto = self.autoscaler
        out = []
        with auto._lock:
            for ident, state in auto._state.items():
                rp = auto._replicas.get(ident)
                if state == SERVING and rp is not None \
                        and rp.version != self.version:
                    out.append(ident)
        return sorted(out)

    def _wait_routable(self, ident: str) -> None:
        """Block until the router holds a healthy link to the new
        replica — green capacity must be *dispatchable* before any blue
        capacity drains (the zero-downtime invariant)."""
        auto = self.autoscaler
        rt = auto._router()
        rp = auto.handle(ident)
        if rt is None or rp is None:
            return
        deadline = time.monotonic() + self.routable_timeout_s
        while time.monotonic() < deadline:
            info = rt.report().get(rp.key()) or {}
            if info.get("state") == "healthy":
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"rollout: green replica {ident} ({rp.key()}) never became "
            f"routable")

    # -- the swap ----------------------------------------------------------
    def run(self) -> Dict:
        """Replace every old-version serving replica, one surge-and-
        retire round at a time. Returns ``{"version", "replaced",
        "spawned"}``."""
        auto = self.autoscaler
        replaced = 0
        spawned = []
        with auto.hold_scaling():
            # the surge replica must not read as scale-down surplus
            for old_ident in self._old_serving():
                green = auto.spawn_replica(version=self.version)
                spawned.append(green)
                self._wait_routable(green)
                ok = auto.retire_replica(old_ident, sync=True)
                logger.info("rollout %s: %s -> %s (%s)", self.version,
                            old_ident, green,
                            "retired" if ok else "missed")
                replaced += 1 if ok else 0
        auto.stats.inc("rollouts")
        return {"version": self.version, "replaced": replaced,
                "spawned": spawned}


def rollout(autoscaler: Autoscaler, version: str,
            routable_timeout_s: float = 30.0) -> Dict:
    """Convenience wrapper: run one blue/green swap to ``version``."""
    return BlueGreenRollout(
        autoscaler, version,
        routable_timeout_s=routable_timeout_s).run()
