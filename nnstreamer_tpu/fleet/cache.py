"""Persistent compile cache: kill the replica cold-start.

A replica's first frame normally pays the ``jax.jit`` trace+compile for
its input signature — hundreds of milliseconds the autoscaler cannot
afford on a scale-up or resurrect (the fleet added capacity precisely
because latency was already over target). This module persists the
*signature registry* — which (shape, dtype) tuples each model and each
fused segment actually compiled — through the crash-consistent
:class:`~..checkpoint.store.SnapshotStore` idiom, so a fresh process
replays them at ``open()``/``start()`` time and serves its first frame
from a warm jit cache.

Two layers compose:

* **signature replay** (always on when a cache is installed): the
  backend records every compiled signature; a restarted replica
  compiles them *before* advertising readiness, moving the cost out of
  the serving path entirely — correct on every JAX version/platform;
* **XLA persistent compilation cache** (best-effort): when the
  installed JAX supports ``jax_compilation_cache_dir``, the replayed
  compiles themselves become disk hits, so even the warmup is cheap.

Processes share one cache through the ``NNS_COMPILE_CACHE`` environment
variable — the autoscaler exports it to every replica it spawns, so the
whole fleet converges on one signature registry.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..checkpoint.store import SnapshotError, SnapshotStore
from ..utils.log import logger

ENV_VAR = "NNS_COMPILE_CACHE"
_SIGS_FILE = "signatures.json"

# one entry: ((shape tuple, dtype str), ...) per input, plus the
# 1-based donated-arg indices (donation changes the compiled program,
# so it is part of the identity — mirrors JaxFilter._executable's key)
SigEntry = Tuple[Tuple[Tuple[Tuple[int, ...], str], ...], Tuple[int, ...]]


def canon_dtype(dtype) -> str:
    """Canonical dtype spelling: ``'<f4'``, ``'=f4'``, ``'single'``,
    ``np.float32`` and ``'float32'`` are ONE signature, not five. An
    alias spelling in the registry would prewarm one jit-cache entry
    and then still miss at invoke time (which keys on ``str(x.dtype)``)
    — a genuine double compile of the same logical program. Dtypes
    NumPy doesn't know (``bfloat16`` on builds without ml_dtypes
    registration) keep their string form, which is already canonical
    on the producing side."""
    try:
        return np.dtype(dtype).name          # objects, np types, '<f4'
    except TypeError:
        try:
            return np.dtype(str(dtype)).name  # dtype-like reprs
        except TypeError:
            return str(dtype)


def _sig_to_json(sig) -> list:
    return [[list(shape), canon_dtype(dtype)] for shape, dtype in sig]


def _sig_from_json(data) -> Tuple:
    return tuple((tuple(int(d) for d in shape), canon_dtype(dtype))
                 for shape, dtype in data)


class CompileCache:
    """Retain-N persisted registry of compiled signatures per model key.

    ``record()`` is called from the backend's compile-miss path;
    ``signatures()`` is replayed by a fresh process at open time. Both
    are cheap: the registry is a small JSON document, re-published
    atomically (tmp + fsync + rename via :class:`SnapshotStore`) only
    when a genuinely new signature appears.
    """

    def __init__(self, root: str, retain: int = 3):
        self.root = root
        self._store = SnapshotStore(root, retain=retain)
        self._lock = threading.Lock()
        # "kind:key" -> [{"sig": [...], "donate": [...]}, ...]
        self._sigs: Dict[str, List[dict]] = {}
        self._load()

    # -- persistence -------------------------------------------------------
    def _load(self) -> None:
        snap = self._store.latest()
        if snap is None:
            return
        try:
            self._store.verify(snap)
            with open(os.path.join(snap, _SIGS_FILE),
                      encoding="utf-8") as f:
                data = json.load(f)
            if isinstance(data, dict):
                self._sigs = {str(k): list(v) for k, v in data.items()
                              if isinstance(v, list)}
        except (SnapshotError, OSError, ValueError) as exc:
            # a torn/corrupt registry only costs warmup, never
            # correctness: start empty and re-learn
            logger.warning("compile cache at %s unreadable (%s); "
                           "starting cold", self.root, exc)
            self._sigs = {}

    def _save_locked(self) -> None:
        blob = json.dumps(self._sigs, sort_keys=True)

        def writer(tmp: str) -> None:
            with open(os.path.join(tmp, _SIGS_FILE), "w",
                      encoding="utf-8") as f:
                f.write(blob)

        try:
            self._store.save(writer, meta={
                "models": len(self._sigs),
                "entries": sum(len(v) for v in self._sigs.values())})
        except OSError as exc:  # read-only disk etc: cache is optional
            logger.warning("compile cache save failed: %s", exc)

    # -- API ---------------------------------------------------------------
    def record(self, kind: str, key: str, sig,
               donate: Tuple[int, ...] = ()) -> bool:
        """Remember one compiled signature; returns True when it was
        new (and the registry was re-published)."""
        ent = {"sig": _sig_to_json(sig), "donate": [int(i) for i in donate]}
        bucket_key = f"{kind}:{key}"
        with self._lock:
            bucket = self._sigs.setdefault(bucket_key, [])
            if ent in bucket:
                return False
            bucket.append(ent)
            self._save_locked()
        return True

    def signatures(self, kind: str, key: str) -> List[SigEntry]:
        """Recorded (sig, donate_idx) entries for one model key."""
        with self._lock:
            bucket = list(self._sigs.get(f"{kind}:{key}", []))
        out: List[SigEntry] = []
        for ent in bucket:
            try:
                out.append((_sig_from_json(ent["sig"]),
                            tuple(int(i) for i in ent.get("donate", []))))
            except (KeyError, TypeError, ValueError):
                continue  # one malformed entry must not spoil the rest
        return out

    def kinds(self) -> List[str]:
        """Distinct compile kinds ("jax", "fusion", ...) that recorded
        at least one signature — the observed half of jitcheck's
        static↔runtime contract."""
        with self._lock:
            return sorted({k.split(":", 1)[0] for k in self._sigs})

    def entry_count(self) -> int:
        """Total recorded signatures across all model keys."""
        with self._lock:
            return sum(len(v) for v in self._sigs.values())

    def enable_xla_cache(self) -> bool:
        """Best-effort: point JAX's persistent compilation cache at a
        subdirectory, so replayed compiles become disk hits. Harmless
        no-op on JAX builds without the knob."""
        xla_dir = os.path.join(self.root, "xla")
        try:
            os.makedirs(xla_dir, exist_ok=True)
            import jax
            jax.config.update("jax_compilation_cache_dir", xla_dir)
            try:
                # cache everything, not just slow compiles: the warmup
                # signatures are exactly the small programs the default
                # min-compile-time heuristic would skip
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.0)
            except Exception:
                pass
            return True
        except Exception:
            return False


# -- process-wide installation (inherited by spawned replicas) -------------
_active_lock = threading.Lock()
_active: Optional[CompileCache] = None
_env_checked = False


def install(root: str, retain: int = 3,
            export_env: bool = True) -> CompileCache:
    """Install a process-wide compile cache rooted at ``root``.
    ``export_env`` also sets :data:`ENV_VAR` so child processes (the
    autoscaler's replicas) inherit the same cache."""
    global _active, _env_checked
    with _active_lock:
        if _active is None or _active.root != root:
            _active = CompileCache(root, retain=retain)
        _env_checked = True
        if export_env:
            os.environ[ENV_VAR] = root
        return _active


def active() -> Optional[CompileCache]:
    """The installed cache, auto-installing from :data:`ENV_VAR` on
    first call (how a spawned replica picks up the fleet's cache
    without any code in between)."""
    global _active, _env_checked
    with _active_lock:
        if _active is None and not _env_checked:
            _env_checked = True
            root = os.environ.get(ENV_VAR, "")
            if root:
                try:
                    _active = CompileCache(root)
                except OSError as exc:
                    logger.warning("compile cache %s from $%s unusable: %s",
                                   root, ENV_VAR, exc)
        return _active


def deactivate() -> None:
    """Forget the installed cache (tests; does not touch the env)."""
    global _active, _env_checked
    with _active_lock:
        _active = None
        _env_checked = False
