"""tensor_src_iio — Linux IIO (industrial I/O) sensor source.

≙ gst/nnstreamer/elements/gsttensor_srciio.c: enumerates an IIO device
under ``base-dir`` (default /sys/bus/iio/devices) by name or number,
parses its ``scan_elements`` channel descriptions (enable flags, index
order, type strings like ``le:s12/16>>4``), applies per-channel
scale/offset, and streams buffered samples from the character device in
``dev-dir`` as float32 tensors: ``value = (raw + offset) * scale``
(ref :127-129). ``merge-channels-data`` packs all channels into one
(capacity, channels) tensor; otherwise one (capacity, 1) tensor per
enabled channel (ref dims :560-568, :1560-1561).

``base-dir``/``dev-dir`` are properties exactly because the reference
made them properties — tests mount a fake sysfs tree.
"""
from __future__ import annotations

import os
import re
import time
from typing import List, Optional, Tuple

import numpy as np

from ..pipeline.element import SrcElement
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorInfo, TensorsConfig, TensorsInfo
from ..tensors.types import TensorType
from ..utils.log import logger

_TYPE_RE = re.compile(
    r"^(?P<endian>[lb])e:(?P<sign>[su])(?P<bits>\d+)/(?P<storage>\d+)"
    r"(?:X(?P<repeat>\d+))?>>(?P<shift>\d+)$")


class _Channel:
    def __init__(self, name: str, index: int, enabled: bool,
                 endian: str, signed: bool, bits: int, storage: int,
                 shift: int, scale: float, offset: float):
        self.name, self.index, self.enabled = name, index, enabled
        self.endian, self.signed = endian, signed
        self.bits, self.storage, self.shift = bits, storage, shift
        self.scale, self.offset = scale, offset
        self.frame_offset = 0  # aligned byte offset within a scan frame

    @property
    def nbytes(self) -> int:
        return self.storage // 8

    def extract(self, raw: np.ndarray) -> np.ndarray:
        """raw: (n, storage_bytes) uint8 -> float32 values
        (≙ the shift/mask/sign-extend macro, gsttensor_srciio.c:113-130)."""
        dt = np.dtype(f"{'<' if self.endian == 'l' else '>'}u{self.nbytes}")
        vals = raw.view(dt).reshape(-1).astype(np.uint64)
        vals >>= np.uint64(self.shift)
        vals &= np.uint64((1 << self.bits) - 1)
        if self.signed:
            sign_bit = np.uint64(1 << (self.bits - 1))
            signed = vals.astype(np.int64)
            signed = np.where(vals & sign_bit,
                              signed - (1 << self.bits), signed)
            out = signed.astype(np.float32)
        else:
            out = vals.astype(np.float32)
        return (out + self.offset) * self.scale


@register_element("tensor_src_iio")
class TensorSrcIio(SrcElement):
    PROPS = {
        "mode": "continuous",          # continuous | one-shot
        "base-dir": "/sys/bus/iio/devices",
        "dev-dir": "/dev",
        "device": "",                  # device name (in the `name` file)
        "device-number": -1,
        "channels": "auto",            # auto (enabled only) | all
        "buffer-capacity": 1,
        "frequency": 0,                # sampling frequency to request
        "merge-channels-data": True,
        "poll-timeout": 10000,         # ms
        "silent": True,
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._dev_dir_path = ""
        self._dev_node = ""
        self._chans: List[_Channel] = []
        self._frame_bytes = 0
        self._dev_fp = None

    # -- device discovery --------------------------------------------------
    def _find_device(self) -> str:
        base = self.base_dir
        if self.device_number >= 0:
            path = os.path.join(base, f"iio:device{self.device_number}")
            if not os.path.isdir(path):
                raise ValueError(
                    f"{self.name}: no IIO device {self.device_number} "
                    f"under {base}")
            return path
        if not self.device:
            raise ValueError(
                f"{self.name}: set 'device' (name) or 'device-number'")
        for entry in sorted(os.listdir(base)):
            name_file = os.path.join(base, entry, "name")
            if os.path.isfile(name_file):
                with open(name_file) as f:
                    if f.read().strip() == self.device:
                        return os.path.join(base, entry)
        raise ValueError(f"{self.name}: IIO device {self.device!r} "
                         f"not found under {base}")

    @staticmethod
    def _read_value(path: str, default=None):
        try:
            with open(path) as f:
                return f.read().strip()
        except OSError:
            return default

    def _parse_channels(self, dev_path: str) -> List[_Channel]:
        scan = os.path.join(dev_path, "scan_elements")
        if not os.path.isdir(scan):
            raise ValueError(f"{self.name}: {dev_path} has no scan_elements")
        chans = []
        for fname in sorted(os.listdir(scan)):
            if not fname.endswith("_en"):
                continue
            cname = fname[:-3]
            enabled = self._read_value(os.path.join(scan, fname)) == "1"
            if self.channels == "all" and not enabled:
                # channels=all must actually ENABLE the channel (write
                # the _en flag like the reference) — otherwise our frame
                # layout would include channels the kernel won't stream
                try:
                    with open(os.path.join(scan, fname), "w") as f:
                        f.write("1")
                    enabled = True
                except OSError:
                    logger.warning("%s: cannot enable channel %s; "
                                   "skipping it", self.name, cname)
                    continue
            if not enabled:
                continue
            tstr = self._read_value(os.path.join(scan, f"{cname}_type"), "")
            m = _TYPE_RE.match(tstr)
            if not m:
                raise ValueError(
                    f"{self.name}: cannot parse channel type {tstr!r} "
                    f"for {cname}")
            idx = int(self._read_value(
                os.path.join(scan, f"{cname}_index"), "0"))
            # scale/offset live next to the raw value in the device dir
            # (specific name first, then the generic one, ≙ :984-1000)
            generic = re.sub(r"\d+$", "", cname)
            scale = offset = None
            for nm in (cname, generic):
                if scale is None:
                    scale = self._read_value(
                        os.path.join(dev_path, f"{nm}_scale"))
                if offset is None:
                    offset = self._read_value(
                        os.path.join(dev_path, f"{nm}_offset"))
            chans.append(_Channel(
                cname, idx, enabled, m["endian"], m["sign"] == "s",
                int(m["bits"]), int(m["storage"]), int(m["shift"]),
                float(scale) if scale is not None else 1.0,
                float(offset) if offset is not None else 0.0))
        chans.sort(key=lambda c: c.index)
        if not chans:
            raise ValueError(f"{self.name}: no enabled IIO channels")
        return chans

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        dev_path = self._find_device()
        self._chans = self._parse_channels(dev_path)
        # the kernel aligns each scan element to its own storage size and
        # pads the frame to the largest element's alignment
        pos = 0
        for c in self._chans:
            pos = (pos + c.nbytes - 1) // c.nbytes * c.nbytes
            c.frame_offset = pos
            pos += c.nbytes
        maxb = max(c.nbytes for c in self._chans)
        self._frame_bytes = (pos + maxb - 1) // maxb * maxb
        self._dev_node = os.path.join(self.dev_dir,
                                      os.path.basename(dev_path))
        if self.frequency > 0:
            # best-effort request (≙ writing sampling_frequency)
            freq_file = os.path.join(dev_path, "sampling_frequency")
            try:
                with open(freq_file, "w") as f:
                    f.write(str(self.frequency))
            except OSError:
                logger.info("%s: cannot set sampling frequency", self.name)
        if self.mode == "continuous":
            # O_NONBLOCK: a quiet real char device must not park the src
            # thread in an unkillable blocking read (regular files are
            # unaffected); pacing/timeout is handled in _read_frames
            fd = os.open(self._dev_node, os.O_RDONLY | os.O_NONBLOCK)
            self._dev_fp = os.fdopen(fd, "rb", buffering=0)
        self._dev_path = dev_path
        super().start()

    def stop(self) -> None:
        # close the device FIRST so a reader inside _read_frames gets an
        # immediate OSError instead of the join timing out
        fp, self._dev_fp = self._dev_fp, None
        if fp is not None:
            try:
                fp.close()
            except OSError:
                pass
        super().stop()

    # -- caps ---------------------------------------------------------------
    def negotiate_src_caps(self) -> Optional[Caps]:
        cap = int(self.buffer_capacity)
        n_ch = len(self._chans)
        rate = int(self.frequency) or 0
        if self.merge_channels_data:
            infos = TensorsInfo([TensorInfo(None, TensorType.FLOAT32,
                                            (cap, n_ch))])
        else:
            infos = TensorsInfo(
                TensorInfo(c.name, TensorType.FLOAT32, (cap, 1))
                for c in self._chans)
        return Caps.from_config(TensorsConfig(infos, rate_n=rate, rate_d=1))

    # -- data ---------------------------------------------------------------
    def _read_frames(self) -> Tuple[Optional[np.ndarray], bool]:
        want = self._frame_bytes * int(self.buffer_capacity)
        if self.mode == "one-shot":
            # read instantaneous values from in_<ch>_raw sysfs files
            rows = []
            for _ in range(int(self.buffer_capacity)):
                row = []
                for c in self._chans:
                    v = self._read_value(
                        os.path.join(self._dev_path, f"{c.name}_raw"), "0")
                    row.append((float(v) + c.offset) * c.scale)
                rows.append(row)
            return np.asarray(rows, np.float32), True
        data = b""
        deadline = time.monotonic() + self.poll_timeout / 1000.0
        while len(data) < want:
            fp = self._dev_fp
            if fp is None or self._stop_evt.is_set():
                return None, False
            try:
                chunk = fp.read(want - len(data))
            except (BlockingIOError, ValueError, OSError):
                chunk = None  # no data yet (nonblocking) or closing
            if not chunk:
                # b"" is a true EOF (regular file / closed fifo) — terminal
                # even mid-frame; None means no data yet (nonblocking
                # device), so retry until poll-timeout
                if chunk == b"":
                    return None, False
                if time.monotonic() > deadline:
                    return None, False
                time.sleep(0.001)
                continue
            data += chunk
        raw = np.frombuffer(data, np.uint8)
        cols = []
        frames = raw.reshape(int(self.buffer_capacity), self._frame_bytes)
        for c in self._chans:
            off = c.frame_offset
            cols.append(c.extract(
                np.ascontiguousarray(frames[:, off:off + c.nbytes])))
        return np.stack(cols, axis=1), False

    def create(self) -> Optional[Buffer]:
        out = self._read_frames()
        if out is None or out[0] is None:
            return None
        merged, oneshot = out
        if oneshot:
            # pace sysfs polling: configured rate, else a 100 Hz default
            # so an unset frequency doesn't busy-spin on _raw reads
            rate = self.frequency if self.frequency > 0 else 100.0
            time.sleep(int(self.buffer_capacity) / rate)
        if self.merge_channels_data:
            chunks = [Chunk(np.ascontiguousarray(merged))]
        else:
            chunks = [Chunk(np.ascontiguousarray(merged[:, i:i + 1]))
                      for i in range(merged.shape[1])]
        return Buffer(chunks)
