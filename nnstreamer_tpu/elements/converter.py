"""tensor_converter — media streams -> other/tensors.

≙ gst/nnstreamer/elements/gsttensor_converter.c: video/x-raw, audio/x-raw,
text/x-raw, application/octet-stream, and flexible->static conversion,
with frames-per-tensor temporal batching and PTS synthesis, plus external
converter subplugins for arbitrary media (_NNS_MEDIA_ANY).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..converters.registry import find_converter
from ..pipeline.element import TransformElement
from ..pipeline.pad import Pad
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorsConfig, TensorsInfo, parse_dimension
from ..tensors.types import TensorFormat, TensorType
from .media import _VIDEO_CHANNELS


@register_element("tensor_converter")
class TensorConverter(TransformElement):
    SINK_TEMPLATES = {"sink": None}
    SRC_TEMPLATES = {"src": "other/tensors"}
    STRIPS_META = True  # mints fresh tensor buffers from media frames
    PROPS = {
        "frames-per-tensor": 1,
        "input-dim": "",     # required for octet / text streams
        "input-type": "",
        "mode": "",          # "custom-code:<name>" / "custom-script:<path>"
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._out_config: Optional[TensorsConfig] = None
        self._media: Optional[str] = None
        self._frame_shape = None
        self._accum = []
        self._custom = None

    # -- negotiation ------------------------------------------------------
    def on_sink_caps(self, pad: Pad, caps: Caps) -> None:
        pad.set_caps(caps)
        self._media = caps.structures[0].name
        if self.mode:
            kind, _, arg = self.mode.partition(":")
            self._custom = find_converter(kind, arg)
            cfg = self._apply_frames(self._custom.get_out_config(caps))
        else:
            cfg = self._declared_out_config(caps)
        self._out_config = cfg
        self.set_src_caps(Caps.from_config(cfg))

    def _declared_out_config(self, caps: Caps) -> TensorsConfig:
        """Pure per-media out-config computation: the piece of
        negotiation shared by the runtime path and pipelint (custom
        ``mode`` converters are handled separately)."""
        s = caps.structures[0]
        if s.name == "video/x-raw":
            cfg = self._video_config(caps)
        elif s.name == "audio/x-raw":
            cfg = self._audio_config(caps)
        elif s.name in ("text/x-raw", "application/octet-stream"):
            cfg = self._octet_config(caps)
        elif s.name == "other/tensors":
            cfg = self._flex_config(caps)
        elif s.name == "other/tensor":
            base = caps.to_config()
            cfg = TensorsConfig(base.info, TensorFormat.STATIC,
                                base.rate_n, base.rate_d)
        else:
            conv = find_converter("media", s.name, optional=True)
            if conv is None:
                raise ValueError(
                    f"{self.name}: unsupported media type {s.name!r}")
            self._custom = conv
            cfg = conv.get_out_config(caps)
        return self._apply_frames(cfg)

    def _apply_frames(self, cfg: TensorsConfig) -> TensorsConfig:
        n = self.frames_per_tensor
        if n > 1 and cfg.info.is_valid():
            for info in cfg.info:
                info.shape = (n, *info.shape)
            if cfg.rate_n > 0:
                cfg.rate_d *= n
        return cfg

    def static_transfer(self, in_caps):
        """Out config per declared media type (video/audio/text/octet/
        tensors); custom ``mode`` converters are unknown until runtime."""
        caps = in_caps.get("sink")
        if caps is None or caps.any or not caps.structures \
                or not caps.is_fixed() or self.mode:
            return {"src": None}
        cfg = self._declared_out_config(caps)
        if not len(cfg.info) or not cfg.info.is_valid():
            return {"src": None}  # dims lock from the first buffer
        return {"src": Caps.from_config(cfg)}

    def _video_config(self, caps: Caps) -> TensorsConfig:
        s = caps.structures[0]
        fmt = str(s.fields.get("format", "RGB"))
        c = _VIDEO_CHANNELS.get(fmt)
        if c is None:
            raise ValueError(f"{self.name}: unsupported video format {fmt}")
        h, w = int(s.fields["height"]), int(s.fields["width"])
        self._frame_shape = (h, w, c)
        rate = s.fields.get("framerate")
        rn = getattr(rate, "numerator", 0)
        rd = getattr(rate, "denominator", 1)
        info = TensorsInfo.make("uint8", f"{c}:{w}:{h}")
        return TensorsConfig(info, TensorFormat.STATIC, rn, rd)

    def _audio_config(self, caps: Caps) -> TensorsConfig:
        s = caps.structures[0]
        fmt = str(s.fields.get("format", "S16LE"))
        ttype = {"S8": "int8", "U8": "uint8", "S16LE": "int16",
                 "U16LE": "uint16", "S32LE": "int32", "U32LE": "uint32",
                 "F32LE": "float32", "F64LE": "float64"}.get(fmt)
        if ttype is None:
            raise ValueError(f"{self.name}: unsupported audio format {fmt}")
        ch = int(s.fields.get("channels", 1))
        rate = int(s.fields.get("rate", 16000))
        # per-buffer frame count is data-dependent; negotiated per first buffer
        self._audio_meta = (ttype, ch, rate)
        info = TensorsInfo.make(ttype, f"{ch}:0")
        return TensorsConfig(info, TensorFormat.STATIC, rate, 1)

    def _octet_config(self, caps: Caps) -> TensorsConfig:
        if not self.input_dim or not self.input_type:
            raise ValueError(
                f"{self.name}: text/octet streams need explicit input-dim/"
                "input-type properties (ref: gsttensor_converter.c octet mode)")
        info = TensorsInfo.make(self.input_type, self.input_dim)
        rate = caps.structures[0].fields.get("framerate")
        return TensorsConfig(info, TensorFormat.STATIC,
                             getattr(rate, "numerator", 0),
                             getattr(rate, "denominator", 1))

    def _flex_config(self, caps: Caps) -> TensorsConfig:
        cfg = caps.to_config()
        if cfg.format == TensorFormat.STATIC:
            return cfg
        if self.input_dim and self.input_type:
            info = TensorsInfo.make(self.input_type, self.input_dim)
            return TensorsConfig(info, TensorFormat.STATIC,
                                 cfg.rate_n, cfg.rate_d)
        # flexible->static: dims locked from the first buffer's meta
        return TensorsConfig(TensorsInfo(), TensorFormat.STATIC,
                             cfg.rate_n, cfg.rate_d)

    # -- dataflow ---------------------------------------------------------
    def transform(self, buf: Buffer) -> Optional[Buffer]:
        if self._custom is not None:
            out = self._custom.convert(buf)
        elif self._media == "video/x-raw":
            out = self._convert_video(buf)
        elif self._media == "audio/x-raw":
            out = self._convert_audio(buf)
        elif self._media in ("text/x-raw", "application/octet-stream"):
            out = self._convert_octet(buf)
        elif self._media in ("other/tensors", "other/tensor"):
            out = self._convert_flex(buf)
        else:
            out = buf
        if out is None:
            return None
        n = self.frames_per_tensor
        if n <= 1:
            return out
        self._accum.append(out)
        if len(self._accum) < n:
            return None
        frames = self._accum
        self._accum = []
        chunks = []
        for i in range(len(frames[0].chunks)):
            arrs = [f.chunks[i].host() for f in frames]
            chunks.append(Chunk(np.stack(arrs)))
        return Buffer(chunks, pts=frames[0].pts,
                      duration=(frames[-1].pts - frames[0].pts +
                                (frames[-1].duration or 0))
                      if frames[0].pts is not None else None)

    def _convert_video(self, buf: Buffer) -> Buffer:
        arr = buf.chunks[0].host()
        if arr.ndim == 1:  # raw bytes from filesrc
            arr = arr.reshape(self._frame_shape)
        return buf.with_chunks([Chunk(np.ascontiguousarray(arr))])

    def _convert_audio(self, buf: Buffer) -> Buffer:
        arr = buf.chunks[0].host()
        ttype, ch, _ = self._audio_meta
        dt = TensorType.from_string(ttype).np_dtype
        if arr.ndim == 1 and arr.dtype == np.uint8 and dt != np.uint8:
            arr = arr.view(dt)
        if arr.ndim == 1:
            arr = arr.reshape(-1, ch)
        return buf.with_chunks([Chunk(arr.astype(dt, copy=False))])

    def _convert_octet(self, buf: Buffer) -> Buffer:
        info = self._out_config.info[0]
        dt = info.type.np_dtype
        raw = buf.chunks[0].host().tobytes()
        frame_bytes = info.size_bytes // max(1, self.frames_per_tensor) \
            if self.frames_per_tensor > 1 else info.size_bytes
        if info.num_elements and len(raw) < frame_bytes:
            raw = raw + b"\x00" * (frame_bytes - len(raw))  # text padding
        arr = np.frombuffer(raw[:frame_bytes], dtype=dt)
        shape = info.shape if self.frames_per_tensor <= 1 else info.shape[1:]
        return buf.with_chunks([Chunk(arr.reshape(shape))])

    def _convert_flex(self, buf: Buffer) -> Buffer:
        # strip per-chunk meta; shapes become the static negotiated dims
        if self._out_config is not None and not len(self._out_config.info):
            cfg = TensorsConfig(buf.to_infos(), TensorFormat.STATIC,
                                self._out_config.rate_n,
                                self._out_config.rate_d)
            self._out_config = cfg
            self.set_src_caps(Caps.from_config(cfg))
        out = buf.with_chunks([Chunk(c.raw) for c in buf.chunks])
        exp = self._out_config.info
        got = out.to_infos()
        if len(exp) and not got.is_equal(exp):
            raise ValueError(
                f"{self.name}: flexible frame {got!r} does not match locked "
                f"static dims {exp!r}")
        return out
