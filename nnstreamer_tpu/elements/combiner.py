"""tensor_mux / tensor_merge / join — N-to-1 stream combiners with the
reference's time-sync engine.

≙ gst/nnstreamer/elements/gsttensor_mux.c, gsttensor_merge.c and the
shared PTS algebra in nnstreamer_plugin_api_impl.c:101-520
(gst_tensor_time_sync_get_current_time / _buffer_update /
_buffer_from_collectpad), policies documented in
Documentation/synchronization-policies-at-mux-merge.md:

* nosync  — first-come collection, no PTS logic
* slowest — base = max of head PTS; older heads are consumed; each pad
            contributes whichever of {last, head} is closer to base
* basepad — base = designated pad's head PTS; other pads contribute their
            head only if within the option duration, else their last
* refresh — any arrival emits, absent pads reuse their last buffer
"""
from __future__ import annotations

import collections
import threading
from typing import Deque, Dict, List, Optional

import numpy as np

from ..pipeline.element import Element, TransferError
from ..pipeline.events import CapsEvent, EosEvent, Event
from ..pipeline.pad import Pad
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorInfo, TensorsConfig, TensorsInfo
from ..tensors.types import TensorFormat

_MAX_QUEUED = 16


def pad_sort_key(name: str):
    """Natural order for request pads: sink_2 before sink_10."""
    base, _, idx = name.rpartition("_")
    return (base, int(idx)) if idx.isdigit() else (name, -1)


class _PadState:
    __slots__ = ("queue", "last", "eos", "config")

    def __init__(self):
        self.queue: Deque[Buffer] = collections.deque()
        self.last: Optional[Buffer] = None
        self.eos = False
        self.config: Optional[TensorsConfig] = None


class _CollectBase(Element):
    """GstCollectPads analog: per-sink-pad queues + the 4 sync policies."""

    SINK_TEMPLATES = {"sink_%u": "other/tensors"}
    SRC_TEMPLATES = {"src": "other/tensors"}
    STRIPS_META = True  # combined output is a fresh buffer, N legs -> 1
    PROPS = {"sync-mode": "slowest", "sync-option": ""}

    # -- device placement (fusion compiler) --------------------------------
    # deliberately None: collection is stateful fan-in — per-pad queues
    # under a condition variable, PTS time-sync policies deciding WHICH
    # buffers pair up — so the pairing itself is host control flow. The
    # planner also rejects it structurally (N sink pads); fusible runs
    # resume downstream of the combined stream.
    DEVICE_FUSIBLE = None

    def device_veto(self) -> Optional[str]:
        return ("stateful N-to-1 collection (time-sync pairing is host "
                "control flow)")

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._states: Dict[str, _PadState] = {}
        self._lock = threading.Condition()
        self._sent_eos = False
        self._caps_sent = False

    def _state(self, pad: Pad) -> _PadState:
        if pad.name not in self._states:
            self._states[pad.name] = _PadState()
        return self._states[pad.name]

    def _pads_in_order(self) -> List[Pad]:
        return [p for _, p in sorted(self.sink_pads.items(),
                                     key=lambda kv: pad_sort_key(kv[0]))
                if p.is_linked]

    # -- events / caps ----------------------------------------------------
    def handle_event(self, pad: Pad, event: Event) -> None:
        if isinstance(event, CapsEvent):
            pad.set_caps(event.caps)
            with self._lock:
                self._state(pad).config = event.caps.to_config()
                self._maybe_send_caps()
            return
        if isinstance(event, EosEvent):
            with self._lock:
                self._state(pad).eos = True
                self._drain()
            return
        pads = self._pads_in_order()
        if pads and pad is pads[0]:
            self.forward_event(event)  # segment/stream-start from first pad

    def _combined_config(self) -> Optional[TensorsConfig]:
        return self._combine_configs(
            [self._state(p).config for p in self._pads_in_order()])

    def _combine_configs(
            self, cfgs: List[TensorsConfig]) -> Optional[TensorsConfig]:
        """Pure N-config -> combined-config computation; shared by the
        runtime caps path and pipelint."""
        raise NotImplementedError

    def static_transfer(self, in_caps):
        """Combine the per-leg declared configs (legs in pad order)."""
        cfgs = []
        for pname in sorted(in_caps, key=pad_sort_key):
            caps = in_caps[pname]
            if caps is None or caps.any or not caps.structures \
                    or not caps.is_fixed():
                return {"src": None}
            try:
                cfgs.append(caps.to_config())
            except ValueError as exc:
                raise TransferError(f"{self.name}: {exc}", pad=pname)
        if not cfgs:
            return {"src": None}
        cfg = self._combine_configs(cfgs)
        return {"src": Caps.from_config(cfg) if cfg is not None else None}

    def _maybe_send_caps(self) -> None:
        if self._caps_sent:
            return
        pads = self._pads_in_order()
        if not pads or any(self._state(p).config is None for p in pads):
            return
        cfg = self._combined_config()
        if cfg is not None:
            self._caps_sent = True
            self.set_src_caps(Caps.from_config(cfg))

    @staticmethod
    def _out_rate(configs: List[TensorsConfig]):
        """min numerator / min denominator, each independently
        (ref: old_numerator/old_denominator logic, :409-415)."""
        return (min(c.rate_n for c in configs),
                min(c.rate_d for c in configs))

    # -- dataflow ---------------------------------------------------------
    def chain(self, pad: Pad, item) -> None:
        if isinstance(item, Event):
            self.stats.inc("events")
            self.handle_event(pad, item)
            return
        with self._lock:
            st = self._state(pad)
            while len(st.queue) >= _MAX_QUEUED and not self._sent_eos:
                # backpressure upstream thread; collection happens under
                # other pads' chains
                if not self._try_collect_locked():
                    self._lock.wait(timeout=0.1)
            st.queue.append(item)
            if self.sync_mode == "refresh":
                self._refresh_collect(pad)
            else:
                self._drain()
            self._lock.notify_all()

    def _drain(self) -> None:
        while self._try_collect_locked():
            pass
        self._check_eos()

    def _check_eos(self) -> None:
        if self._sent_eos:
            return
        pads = self._pads_in_order()
        if not pads:
            return
        if self.sync_mode == "refresh":
            done = all(self._state(p).eos and not self._state(p).queue
                       for p in pads)
        else:
            done = any(self._state(p).eos and not self._state(p).queue
                       for p in pads)
        if done:
            self._sent_eos = True
            self.forward_event(EosEvent())

    # -- policy engine ----------------------------------------------------
    def _try_collect_locked(self) -> bool:
        """One collection attempt; True if a buffer was pushed."""
        if self._sent_eos:
            return False
        pads = self._pads_in_order()
        if not pads:
            return False
        mode = self.sync_mode
        if mode == "nosync":
            return self._collect_nosync(pads)
        if mode in ("slowest", "basepad"):
            return self._collect_synced(pads, mode)
        return False  # refresh collects on arrival

    def _collect_nosync(self, pads) -> bool:
        sts = [self._state(p) for p in pads]
        if any(not st.queue for st in sts):
            return False
        bufs = [st.queue.popleft() for st in sts]
        pts = max((b.pts or 0) for b in bufs)
        self._emit(pads, bufs, pts)
        return True

    def _collect_synced(self, pads, mode) -> bool:
        sts = [self._state(p) for p in pads]
        # GstCollectPads gate: collection fires only when every live
        # (non-EOS) pad has queued data — collecting earlier would have
        # to abort halfway and lose the buffers it already consumed
        if any(not st.queue and not st.eos for st in sts):
            return False
        # pick current (base) timestamp
        if mode == "basepad":
            opt = (self.sync_option or "0").split(":")
            base_id = int(opt[0] or 0)
            if base_id >= len(sts):
                return False
            bst = sts[base_id]
            if not bst.queue:
                return False
            current = bst.queue[0].pts or 0
            # ≙ nnstreamer_plugin_api_impl.c:368-377 — the window is
            # MIN(duration, ABS(pts_delta)-1), assigned only once the base
            # pad has a previous buffer; before that it stays 0.  The
            # delta term is clamped >= 0 (reference leaves -1 for equal
            # consecutive PTS) so stale buffers can't wedge other pads.
            duration = int(opt[1]) if len(opt) > 1 and opt[1] else None
            if bst.last is not None:
                delta_win = max(0, abs(current - (bst.last.pts or 0)) - 1)
                base_win = delta_win if duration is None \
                    else min(duration, delta_win)
            else:
                base_win = 0
        else:
            heads = [st.queue[0].pts or 0 for st in sts if st.queue]
            if not heads:
                return False
            current = max(heads)
            base_win = 0

        # per-pad buffer update (≙ _gst_tensor_time_sync_buffer_update),
        # two-phase: decide every pad's contribution by peeking, and only
        # commit (pop queues / advance .last) once the whole tuple is
        # known to be assemblable — an aborted collection must not
        # consume buffers, or tuples are silently lost
        chosen: List[Buffer] = []
        plans: List[tuple] = []  # (n_outdated_pops, take_head)
        for st in sts:
            q = st.queue
            k = 0
            while k < len(q) and (q[k].pts or 0) < current:
                k += 1
            last = q[k - 1] if k else st.last
            take = False
            if k < len(q):
                head = q[k]
                if mode == "slowest" and last is not None and \
                        abs(current - (last.pts or 0)) < \
                        abs(current - (head.pts or 0)):
                    pass  # keep last
                elif mode == "basepad" and last is not None and \
                        abs((head.pts or 0) - current) > base_win:
                    pass  # out of window: keep last
                else:
                    take = True
            elif not st.eos:
                return False  # need more data to decide
            buf = head if take else last
            if buf is None:
                return False
            plans.append((k, take))
            chosen.append(buf)
        for st, (k, take) in zip(sts, plans):
            for _ in range(k + (1 if take else 0)):
                st.last = st.queue.popleft()
        self._emit(pads, chosen, current)
        return True

    def _refresh_collect(self, pad: Pad) -> None:
        st = self._state(pad)
        if st.queue:
            st.last = st.queue.popleft()
        pads = self._pads_in_order()
        sts = [self._state(p) for p in pads]
        if any(s.last is None for s in sts):
            return
        self._emit(pads, [s.last for s in sts], st.last.pts or 0)

    # -- output -----------------------------------------------------------
    def _emit(self, pads, bufs: List[Buffer], pts) -> None:
        out = self._combine(pads, bufs)
        if out is not None:
            out.pts = pts
            self.srcpad.push(out)

    def _combine(self, pads, bufs: List[Buffer]) -> Optional[Buffer]:
        raise NotImplementedError


@register_element("tensor_mux")
class TensorMux(_CollectBase):
    """N tensor streams -> one stream whose num_tensors is the sum
    (≙ gsttensor_mux.c)."""

    def _combine_configs(self, cfgs) -> Optional[TensorsConfig]:
        info = TensorsInfo()
        fmt = TensorFormat.STATIC
        for c in cfgs:
            if c.format != TensorFormat.STATIC:
                fmt = TensorFormat.FLEXIBLE
            for i in c.info:
                info.append(i.copy())
        rn, rd = self._out_rate(cfgs)
        return TensorsConfig(info, fmt, rn, rd)

    def _combine(self, pads, bufs: List[Buffer]) -> Buffer:
        chunks = []
        for b in bufs:
            chunks.extend(b.chunks)
        return Buffer(chunks)


@register_element("tensor_merge")
class TensorMerge(_CollectBase):
    """N single-tensor streams -> one tensor concatenated along a chosen
    dim (≙ gsttensor_merge.c, mode=linear option=<ref dim index>)."""

    PROPS = {"mode": "linear", "option": "3"}

    def _np_axis(self, ndim: int) -> int:
        ref_dim = int(self.option or 0)
        if ref_dim >= ndim:
            # reference pads rank; concat on a new outermost axis
            return 0
        return ndim - 1 - ref_dim

    def _combine_configs(self, cfgs) -> Optional[TensorsConfig]:
        infos = [c.info[0] for c in cfgs]
        base = infos[0]
        ndim = max(len(i.shape) for i in infos)
        shapes = [list(i.shape) + [1] * (ndim - len(i.shape)) for i in infos]
        axis = self._np_axis(ndim)
        merged = list(shapes[0])
        merged[axis] = sum(s[axis] for s in shapes)
        for s in shapes[1:]:
            for d in range(ndim):
                if d != axis and s[d] != shapes[0][d]:
                    raise ValueError(
                        f"{self.name}: cannot merge shapes {shapes} on "
                        f"axis {axis}")
        info = TensorsInfo([TensorInfo(base.name, base.type, tuple(merged))])
        rn, rd = self._out_rate(cfgs)
        return TensorsConfig(info, TensorFormat.STATIC, rn, rd)

    def _combine(self, pads, bufs: List[Buffer]) -> Buffer:
        arrs = [b.chunks[0].host() for b in bufs]
        ndim = max(a.ndim for a in arrs)
        arrs = [a.reshape(a.shape + (1,) * (ndim - a.ndim)) for a in arrs]
        axis = self._np_axis(ndim)
        return Buffer([Chunk(np.concatenate(arrs, axis=axis))])


@register_element("join")
class Join(Element):
    """N-to-1 first-come forwarding, no synchronization
    (≙ gst/join/gstjoin.c)."""

    SINK_TEMPLATES = {"sink_%u": None}
    SRC_TEMPLATES = {"src": None}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._eos_pads: set = set()
        self._caps_done = False
        self._lock = threading.Lock()

    def handle_event(self, pad: Pad, event: Event) -> None:
        if isinstance(event, CapsEvent):
            pad.set_caps(event.caps)
            with self._lock:
                if not self._caps_done:
                    self._caps_done = True
                    self.set_src_caps(event.caps)
            return
        if isinstance(event, EosEvent):
            with self._lock:
                self._eos_pads.add(pad.name)
                linked = [p.name for p in self.sink_pads.values() if p.is_linked]
                done = all(n in self._eos_pads for n in linked)
            if done:
                self.forward_event(event)
            return

    def do_chain(self, pad: Pad, buf: Buffer) -> None:
        self.srcpad.push(buf)

    def static_transfer(self, in_caps):
        """First leg's caps when every known leg agrees; differing legs
        are unknown here (the combiner-dtype rule reports them)."""
        known = [c for c in in_caps.values() if c is not None]
        if not known or any(c != known[0] for c in known[1:]):
            return {"src": None}
        return {"src": known[0]}
