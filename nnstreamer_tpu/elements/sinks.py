"""tensor_sink / tensor_debug — terminal & diagnostic elements.

≙ gst/nnstreamer/elements/gsttensor_sink.c (appsink-like callback sink
emitting new-data signals) and gsttensor_debug.c (passthrough that logs
caps/metadata).
"""
from __future__ import annotations

import threading
from typing import Callable, List, Optional

from ..pipeline.basic import AppSink
from ..pipeline.element import TransformElement
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer
from ..tensors.caps import Caps
from ..utils.log import logger


@register_element("tensor_sink")
class TensorSink(AppSink):
    """new-data / stream-start / eos signal emission on tensor streams."""

    PROPS = {"emit-signal": True, "signal-rate": 0, "silent": True,
             "max-buffers": 0}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._signal_count = 0
        self._handlers = {"new-data": [], "eos": []}

    def connect_signal(self, signal: str,
                       handler: Callable) -> None:
        self._handlers[signal].append(handler)

    def render(self, buf: Buffer) -> None:
        with self._lock:
            self.buffers.append(buf)
            if self.max_buffers > 0 and len(self.buffers) > self.max_buffers:
                self.buffers.pop(0)
        # honor both spellings: "emit-signal" (reference tensor_sink) and
        # the inherited appsink "emit-signals"
        if not (self.emit_signal and self.emit_signals):
            return
        self._signal_count += 1
        if self.signal_rate > 0 and \
                (self._signal_count % max(1, self.signal_rate)) != 0:
            return
        if self.callback is not None:
            self.callback(buf)
        for h in self._handlers["new-data"]:
            h(buf)

    def on_eos(self) -> None:
        for h in self._handlers["eos"]:
            h()
        super().on_eos()


@register_element("tensor_debug")
class TensorDebug(TransformElement):
    """Passthrough logging caps/timing/shape metadata
    (output-type: none | console | cap | metadata)."""

    PROPS = {"output-type": "console", "capability": True, "metadata": True}

    def transform(self, buf: Buffer) -> Buffer:
        if self.output_type != "none":
            parts = [f"{self.name}: pts={buf.pts}"]
            if self.metadata:
                parts.append(f"chunks={[str(c) for c in buf.chunks]}")
            if self.capability and self.sinkpad.caps is not None:
                parts.append(f"caps={self.sinkpad.caps}")
            logger.info(" ".join(parts))
        return buf
