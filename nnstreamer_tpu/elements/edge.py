"""edgesink / edgesrc — tensor stream pub/sub between pipelines/hosts.

≙ gst/edge/edge_sink.c + edge_src.c (thin publisher/subscriber over
nnstreamer-edge): edgesink accepts N subscribers and broadcasts every
buffer; edgesrc connects and replays the feed into its pipeline.
Topic filtering mirrors the MQTT-hybrid topic semantics: a subscriber
passes ``topic`` at SUBSCRIBE and only receives matching streams.

Delivery guarantees (edge/session.py, negotiated per link at SUBSCRIBE
exactly like wire v2 — a subscriber that doesn't advertise a session
gets byte-identical v1 traffic):

* the publisher stamps every broadcast frame with one monotonic seq and
  retains unacknowledged frames in a bytes-budgeted replay ring;
* each session subscriber returns cumulative ACKs and, after a
  reconnect, presents RESUME(sid, last-delivered); the publisher
  replays exactly the gap while the subscriber dedups by seq;
* if the ring evicted frames the gap needed, the loss is *declared* —
  an exact frames_lost count in the RESUME_ACK plus a structured bus
  warning on both ends, never a silent hole;
* PING/PONG heartbeats detect half-open links, feeding the per-link
  circuit breaker (fault/breaker.py) that paces re-dials.
"""
from __future__ import annotations

import collections
import select
import socket
import threading
import time
from typing import Dict, List, Optional

from ..edge import session as sess_mod
from ..edge import wire
from ..edge.protocol import MsgKind, recv_msg, send_msg, sever_socket as _sever
from ..obs import events as _obs_events
from ..pipeline.element import SinkElement, SrcElement
from ..pipeline.pad import Pad
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer
from ..tensors.caps import Caps
from ..utils.log import logger


class _Sub:
    """One attached subscriber: socket, negotiated wire config, a send
    lock (broadcast bytes and the reader thread's PONGs must not
    interleave on the socket), and the session id (None = v1/sessionless
    link: no seqs, no reader thread)."""

    __slots__ = ("sock", "cfg", "lock", "sid")

    def __init__(self, sock, cfg, sid=None):
        self.sock = sock
        self.cfg = cfg
        self.lock = threading.Lock()
        self.sid = sid


@register_element("edgesink")
class EdgeSink(SinkElement):
    PROPS = {"host": "localhost", "port": 3000, "topic": "",
             "connect-type": "TCP",
             # wire v2 link request, applied per subscriber that
             # advertises support (v1 subscribers keep plain framing):
             # lossless payload codec + opt-in lossy fp32 downcast.
             # wire-codec=delta ships keyframes every wire-delta-k
             # frames and sparse diffs between them (per-link reference
             # state; v1/v2-old subscribers fall back to raw)
             "wire-codec": "raw", "wire-precision": "none",
             "wire-delta-k": wire.DELTA_KEYFRAME_INTERVAL,
             # frame coalescing: broadcast up to N frames per message
             # (DATA_BATCH, v2 subscribers only), flushing a partial
             # batch once its oldest frame has waited coalesce-ms
             "coalesce-frames": 1, "coalesce-ms": 5.0,
             # session layer: accept subscriber sessions (acked
             # delivery + resume); the replay ring retains this many KB
             # of unacknowledged frames for gap replay before evicting
             # (evictions become *declared* loss, never silent)
             "session": True, "session-ring-kb": 8192}

    # conservation identity flowcheck proves statically and
    # check_identities() asserts over live stats snapshots
    SETTLEMENT_IDENTITY = ("session-delivery",)

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._listener: Optional[socket.socket] = None
        self._subs: List[_Sub] = []
        self._subs_lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._caps_str = ""
        # seeded so sent == acked + ring + declared_lost is readable
        # from any snapshot (same discipline as EdgeSrc/query client)
        self.stats.update({"session_sent": 0, "session_replayed": 0,
                           "session_declared_lost": 0})
        # coalesce state: the chain thread appends + size-flushes, the
        # flush worker age-flushes. _co_lock is held across the whole
        # take-and-send so the two flushers can neither interleave bytes
        # on a subscriber socket nor reorder batches; it also serializes
        # broadcast against RESUME replay, which is what makes "replayed
        # frames always precede newer live frames" true.
        self._co_lock = threading.Lock()
        self._co_pending: List[Buffer] = []
        self._co_t0 = 0.0
        self._flush_thread: Optional[threading.Thread] = None
        # session-layer publisher state: one global seq space + one
        # bytes-budgeted ring shared by all sessions (frames are packed
        # once per config, so seqs must be identical across links);
        # per-session acked watermarks decide what the ring may drop
        self._next_seq = 0  # written under _co_lock
        self._ring = sess_mod.ReplayRing(
            int(self.session_ring_kb) * 1024)
        self._sessions: Dict[str, Dict] = {}
        self._sess_lock = threading.Lock()

    @property
    def bound_port(self) -> int:
        return self._listener.getsockname()[1] if self._listener else self.port

    def start(self) -> None:
        super().start()
        self._stop_evt.clear()
        # parse_launch sets properties after construction, so the ring
        # budget is only final here
        self._ring.budget = max(0, int(self.session_ring_kb) * 1024)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self._listener.listen(16)
        threading.Thread(target=self._accept_loop,
                         name=f"edgesink-accept:{self.name}",
                         daemon=True).start()
        if int(self.coalesce_frames) > 1:
            self._flush_thread = threading.Thread(
                target=self._flush_loop,
                name=f"edgesink-flush:{self.name}", daemon=True)
            self._flush_thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        with self._subs_lock:
            for sub in self._subs:
                _sever(sub.sock)
            self._subs.clear()
        super().stop()

    def kill_link(self) -> int:
        """Chaos hook (tensor_fault mode=kill-link): force-close every
        live subscriber socket, exactly like a network partition mid
        stream. Session state and the replay ring survive, so resumed
        subscribers replay the gap."""
        with self._subs_lock:
            victims = list(self._subs)
            self._subs.clear()
        for sub in victims:
            _sever(sub.sock)
        self.stats.inc("link_kills", len(victims))
        return len(victims)

    def on_sink_caps(self, pad: Pad, caps: Caps) -> None:
        self._caps_str = str(caps)

    def handle_event(self, pad, event) -> None:
        from ..pipeline.events import CapsEvent
        if isinstance(event, CapsEvent):
            pad.set_caps(event.caps)
            self.on_sink_caps(pad, event.caps)
            return
        super().handle_event(pad, event)

    def _accept_loop(self) -> None:
        while not self._stop_evt.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            try:
                kind, meta, _ = recv_msg(conn)
                want = meta.get("topic", "")
                if kind != MsgKind.SUBSCRIBE or \
                        (self.topic and want and want != self.topic):
                    send_msg(conn, MsgKind.ERROR, {"reason": "topic mismatch"})
                    conn.close()
                    continue
                # wire v2: fold the subscriber's advertisement into OUR
                # requested codec/precision; a v1 subscriber (no "wire"
                # block) gets plain framing and never sees DATA_BATCH
                cfg = wire.negotiate(meta.get("wire"),
                                     codec=str(self.wire_codec),
                                     precision=str(self.wire_precision),
                                     delta_k=int(self.wire_delta_k))
                # session fold, same shape: no "session" block in the
                # SUBSCRIBE = no session = strict v1 on this link
                scfg = None
                if self.session:
                    scfg = sess_mod.negotiate(
                        meta.get("session"),
                        ring_bytes=int(self.session_ring_kb) * 1024)
                ack = {"caps": self._caps_str, "topic": self.topic}
                if cfg is not None:
                    ack["wire"] = cfg.to_meta()
                if scfg is not None:
                    ack["session"] = scfg.to_meta()
                send_msg(conn, MsgKind.CAPS_ACK, ack)
                wire.tune_socket(conn)
                if scfg is not None:
                    # a session subscriber ALWAYS follows with RESUME
                    # (last=0 on first attach); it is handled — and the
                    # gap replayed — before the link joins the broadcast
                    # set, so replays can never arrive after newer
                    # live frames
                    conn.settimeout(5.0)
                    kind, rmeta, _ = recv_msg(conn)
                    conn.settimeout(None)
                    if kind != MsgKind.RESUME:
                        raise ConnectionError(f"expected RESUME, got {kind}")
                    self._attach_session(conn, cfg, scfg,
                                         int(rmeta.get("last", 0)))
                    continue
            except (ConnectionError, OSError, ValueError):
                try:
                    conn.close()
                except OSError:
                    pass
                continue
            with self._subs_lock:
                self._subs.append(_Sub(conn, cfg))

    def _attach_session(self, conn, cfg, scfg, last: int) -> None:
        """RESUME handling: register/resume the session, replay exactly
        the gap (or declare what the ring already evicted), then attach.
        Runs under _co_lock so no broadcast interleaves: every replayed
        seq is on the wire before any newer live frame."""
        sub = _Sub(conn, cfg, sid=scfg.sid)
        with self._co_lock:
            with self._sess_lock:
                state = self._sessions.get(scfg.sid)
                if state is None:
                    # fresh attach: only frames broadcast from now on
                    # are owed to this session
                    resumed = False
                    base = self._next_seq
                    self._sessions[scfg.sid] = {"acked": base, "resumes": 0}
                    replay, lost = [], 0
                else:
                    resumed = True
                    base = last
                    state["acked"] = max(state["acked"], last)
                    state["resumes"] += 1
                    replay, lost = self._ring.replay_from(last + 1)
            # count BEFORE anything reaches the wire: the subscriber
            # learns the loss from the RESUME_ACK, so any observer it
            # tips off must already see the counters updated — never a
            # window where the peer knows about declared loss that the
            # publisher's own stats have not recorded yet
            if replay:
                self.stats.inc("session_replayed", len(replay))
            if lost:
                # the ring could not cover the whole gap: the loss is
                # exact and DECLARED — counted here, counted by the
                # subscriber from the RESUME_ACK, and posted to the bus
                self.stats.inc("session_declared_lost", lost)
                self.post_message(
                    "warning", session=scfg.sid[:8], frames_lost=lost,
                    detail="replay ring evicted part of the resume gap")
            if resumed:
                self.stats.inc("session_resumes")
                _obs_events.emit("resume", source=self.name, element=self,
                                 session=scfg.sid[:8],
                                 replayed=len(replay), lost=lost)
            with sub.lock:
                send_msg(conn, MsgKind.RESUME_ACK,
                         {"sid": scfg.sid, "resumed": resumed,
                          "lost": lost, "base": base}, stats=self.stats)
                for seq, frame in replay:
                    meta, payloads = wire.pack_buffer(frame, cfg,
                                                      stats=self.stats)
                    meta["seq"] = seq
                    if self.topic:
                        meta["topic"] = self.topic
                    send_msg(conn, MsgKind.DATA, meta, payloads,
                             stats=self.stats)
            with self._subs_lock:
                self._subs.append(sub)
        threading.Thread(target=self._sub_reader, args=(sub,),
                         name=f"edgesink-ack:{self.name}",
                         daemon=True).start()

    def _sub_reader(self, sub: _Sub) -> None:
        """Per-session-subscriber reader: consumes ACKs (release the
        ring), PINGs (answer PONG under the send lock) and EOS. Ends
        with the socket."""
        while not self._stop_evt.is_set():
            try:
                kind, meta, _ = recv_msg(sub.sock)
            except (ConnectionError, OSError, ValueError):
                return
            if kind == MsgKind.ACK:
                self._on_ack(sub.sid, int(meta.get("seq", 0)))
            elif kind == MsgKind.PING:
                try:
                    with sub.lock:
                        send_msg(sub.sock, MsgKind.PONG,
                                 {"t": meta.get("t", 0.0)})
                except (ConnectionError, OSError):
                    return
            elif kind == MsgKind.EOS:
                return

    def _on_ack(self, sid: str, seq: int) -> None:
        with self._sess_lock:
            state = self._sessions.get(sid)
            if state is None:
                return
            state["acked"] = max(state["acked"], seq)
            floor = min(s["acked"] for s in self._sessions.values())
        # release only what EVERY session has acknowledged; a detached
        # (reconnecting) session keeps its gap replayable until the
        # bytes budget forces eviction — which is then declared
        self._ring.release(floor)
        self.stats.inc("session_acks_in")

    def render(self, buf: Buffer) -> None:
        if int(self.coalesce_frames) <= 1:
            with self._co_lock:
                self._broadcast([buf])
            return
        with self._co_lock:
            if self._co_pending and \
                    not wire.batch_compatible(self._co_pending[0], buf):
                # layout change: ship what we have, open a new batch
                self._broadcast(self._co_pending)
                self._co_pending = []
            if not self._co_pending:
                self._co_t0 = time.monotonic()
            self._co_pending.append(buf)
            if len(self._co_pending) >= int(self.coalesce_frames):
                take, self._co_pending = self._co_pending, []
                self._broadcast(take)

    def _flush_loop(self) -> None:
        """Age flush: a partial batch never waits longer than
        coalesce-ms for stragglers (mirrors the serve batcher's
        max-wait discipline)."""
        max_age = max(1e-3, float(self.coalesce_ms) / 1e3)
        while not self._stop_evt.is_set():
            self._stop_evt.wait(max_age / 2)
            with self._co_lock:
                if self._co_pending and \
                        time.monotonic() - self._co_t0 >= max_age:
                    take, self._co_pending = self._co_pending, []
                    self._broadcast(take)

    def _broadcast(self, frames: List[Buffer]) -> None:
        """Fan one or more frames out to every subscriber: v2 links get
        one DATA_BATCH per flush (or codec'd DATA for a single frame),
        v1 links always get per-frame plain DATA. Messages are packed
        once per distinct (config, session-ness), not once per
        subscriber — session links carry seqs, v1 links stay
        byte-identical to pre-session builds. Callers hold _co_lock, so
        flushes can neither interleave bytes nor reorder batches, and
        seq stamping is strictly monotonic in send order."""
        with self._subs_lock:
            subs = list(self._subs)
        # stamp + retain while ANY session is registered (attached or
        # resuming): a detached subscriber's gap accrues in the ring
        with self._sess_lock:
            stamp = bool(self._sessions)
        seqs: Optional[List[int]] = None
        if stamp:
            seqs = []
            for f in frames:
                self._next_seq += 1
                self._ring.append(self._next_seq, f)
                seqs.append(self._next_seq)
            self.stats.inc("session_sent", len(frames))
        dead = []
        packed: dict = {}
        for sub in subs:
            cfg = sub.cfg
            with_seq = sub.sid is not None and seqs is not None
            if cfg is not None and cfg.codec == wire.CODEC_DELTA:
                # delta frames are encoded against this link's own
                # reference state — never share packed bytes across
                # subscribers (id(cfg) is unique per connection)
                key = (id(cfg), with_seq)
            else:
                key = (None if cfg is None
                       else (cfg.codec, cfg.precision, len(frames) > 1),
                       with_seq)
            msgs = packed.get(key)
            if msgs is None:
                if cfg is not None and len(frames) > 1:
                    msgs = [(MsgKind.DATA_BATCH,
                             wire.pack_batch(frames, cfg, stats=self.stats,
                                             seqs=seqs if with_seq
                                             else None))]
                else:
                    msgs = [(MsgKind.DATA,
                             wire.pack_buffer(f, cfg, stats=self.stats))
                            for f in frames]
                    if with_seq:
                        for i, (_k, (meta, _p)) in enumerate(msgs):
                            meta["seq"] = seqs[i]
                if self.topic:
                    for _, (meta, _pls) in msgs:
                        meta["topic"] = self.topic
                packed[key] = msgs
            try:
                with sub.lock:
                    for kind, (meta, payloads) in msgs:
                        send_msg(sub.sock, kind, meta, payloads,
                                 stats=self.stats)
            except (ConnectionError, OSError):
                dead.append(sub)
        if dead:
            # the socket died but the SESSION did not: its acked
            # watermark stays registered, the gap accrues in the ring,
            # and a RESUME replays it (or declares what was evicted)
            self.stats.inc("link_errors", len(dead))
            with self._subs_lock:
                self._subs = [s for s in self._subs if s not in dead]

    def session_info(self) -> Dict:
        """Live (non-counter) session gauges for the trace report."""
        with self._sess_lock:
            n = len(self._sessions)
        if not n:
            return {}
        return {"sessions": n, "ring_frames": len(self._ring),
                "ring_bytes": self._ring.nbytes}

    # -- checkpoint/restore (checkpoint/) ----------------------------------
    CHECKPOINTABLE = ("publisher seq space + unacked replay-ring frames "
                      "+ per-session acked watermarks")

    def snapshot_state(self, snap_dir):
        from ..checkpoint.state import dump_buffer
        # _co_lock serializes against broadcast, so (next_seq, ring,
        # watermarks) are one coherent instant — a restored subscriber's
        # RESUME replays exactly the frames this snapshot retained
        with self._co_lock:
            frames, evicted = self._ring.dump()
            with self._sess_lock:
                sessions = {sid: dict(st)
                            for sid, st in self._sessions.items()}
            next_seq = self._next_seq
        if not sessions and not frames and next_seq == 0:
            return None
        return {"next_seq": next_seq, "evicted_through": evicted,
                "sessions": sessions,
                "ring": [(s, dump_buffer(b)) for s, b in frames]}

    def restore_state(self, state, snap_dir):
        from ..checkpoint.state import load_buffer
        with self._co_lock:
            self._next_seq = int(state["next_seq"])
            self._ring.load([(s, load_buffer(d))
                             for s, d in state.get("ring", [])],
                            int(state.get("evicted_through", 0)))
            with self._sess_lock:
                self._sessions = {sid: dict(st) for sid, st in
                                  (state.get("sessions") or {}).items()}

    def on_eos(self) -> None:
        # ship any coalesced frames still waiting before the EOS marker
        with self._co_lock:
            take, self._co_pending = self._co_pending, []
            if take:
                self._broadcast(take)
        with self._subs_lock:
            subs = list(self._subs)
        for sub in subs:
            try:
                with sub.lock:
                    send_msg(sub.sock, MsgKind.EOS, {})
            except (ConnectionError, OSError):
                pass
        super().on_eos()


@register_element("edgesrc")
class EdgeSrc(SrcElement):
    # reconnect=true: a dropped publisher link is re-dialed with
    # exponential backoff + jitter inside the timeout window instead of
    # ending the stream as EOS (set false to keep the old die-on-drop
    # behavior — e.g. when a supervisor owns restarts)
    PROPS = {"dest-host": "localhost", "dest-port": 3000, "topic": "",
             "connect-type": "TCP", "timeout": 10.0, "reconnect": True,
             # session=true: negotiate acked delivery + resume (the
             # publisher replays reconnect gaps; what it cannot replay
             # is declared, never silent). ack cadence: a cumulative
             # ACK every ack-every frames or ack-ms of silence.
             "session": False, "ack-every": 8, "ack-ms": 50.0,
             # heartbeat-ms>0: PING an idle publisher link this often;
             # heartbeat-miss unanswered PINGs declare the peer dead
             # (close + reconnect) and feed the link circuit breaker
             "heartbeat-ms": 0.0, "heartbeat-miss": 3}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._sock: Optional[socket.socket] = None
        # the wire config adopted from the publisher's CAPS_ACK echo —
        # minted fresh at every (re)subscribe, which is what resets the
        # delta receiver reference state in lockstep with the
        # publisher's fresh per-connection sender state
        self._wire_cfg: Optional[wire.WireConfig] = None
        # frames from an unpacked DATA_BATCH beyond the first, drained
        # before the next recv (only the source loop touches this)
        self._rxq: "collections.deque" = collections.deque()
        # session counters seeded at zero so the accounting identity
        # (delivered + declared_lost vs the publisher's sent) is always
        # readable from a snapshot, not only after the first increment
        self.stats.update({"reconnects": 0, "link_errors": 0,
                           "session_delivered": 0, "session_dup_drops": 0,
                           "session_declared_lost": 0})
        # session id minted HERE (the connecting peer) and stable across
        # reconnects: it is the resume key
        self._sid = sess_mod.new_session_id()
        # delivery watermark recovered by restore_state (checkpoint/):
        # the first RESUME after a restart presents it so the publisher
        # replays the process-death gap instead of resetting the stream
        self._restored_last: Optional[int] = None
        self._sess: Optional[sess_mod.SessionReceiver] = None
        self._hb: Optional[sess_mod.Heartbeat] = None
        # link circuit breaker: consecutive link failures / dead-peer
        # declarations open it, pacing re-dials; a successful
        # resubscribe (or pong) closes it. Transitions go to the bus.
        from ..fault.breaker import CircuitBreaker
        self._breaker = CircuitBreaker(
            threshold=max(1, int(self.heartbeat_miss)), reset_s=1.0,
            name=f"{self.name}-link", on_transition=self._breaker_moved)

    def start(self) -> None:
        # parse_launch sets properties after construction: the breaker
        # threshold is only final here
        self._breaker.threshold = max(1, int(self.heartbeat_miss))
        super().start()

    def _breaker_moved(self, old: str, new: str) -> None:
        self.post_message("warning", breaker=new,
                          detail=f"publisher link breaker {old} -> {new}")

    def _subscribe(self) -> Caps:
        """Connect + SUBSCRIBE handshake (the one dial site: first
        connect and every reconnect share it), backed off with jitter
        inside the timeout budget. With session=true the handshake
        continues RESUME -> RESUME_ACK: the publisher replays the gap
        since our last delivered frame before any live traffic."""
        from ..fault.backoff import Backoff
        deadline = time.monotonic() + self.timeout
        backoff = Backoff(base=0.05, multiplier=2.0, max_s=1.0)
        last_err = None
        sock = None
        while time.monotonic() < deadline and not self._stop_evt.is_set():
            if not self._breaker.allow():
                # breaker OPEN: the peer kept failing; wait out the
                # reset window instead of hammering a dead endpoint
                backoff.sleep(self._stop_evt)
                continue
            try:
                sock = socket.create_connection(
                    (self.dest_host, int(self.dest_port)),
                    timeout=self.timeout)
                break
            except OSError as e:
                last_err = e
                self._breaker.record_failure()
                backoff.sleep(self._stop_evt)
        if sock is None:
            raise ConnectionError(
                f"{self.name}: cannot reach edgesink at "
                f"{self.dest_host}:{self.dest_port}: {last_err}")
        wire.tune_socket(sock)
        # advertise v2 support; the publisher's wire-codec/precision
        # props decide what this link actually uses (echoed in the ack)
        sub_meta = {"topic": self.topic, "wire": wire.advertise()}
        if self.session:
            sub_meta["session"] = sess_mod.advertise(
                self._sid, int(self.ack_every), float(self.ack_ms))
        send_msg(sock, MsgKind.SUBSCRIBE, sub_meta)
        kind, meta, _ = recv_msg(sock)
        if kind != MsgKind.CAPS_ACK:
            raise ConnectionError(f"{self.name}: subscribe rejected ({kind})")
        # adopt the publisher's choice; a fresh WireConfig per
        # (re)connect means fresh delta reference state on both ends
        self._wire_cfg = wire.accept(meta.get("wire"))
        scfg = sess_mod.accept(meta.get("session")) if self.session else None
        if scfg is not None:
            self._resume(sock, scfg)
        else:
            self._sess = None
            self._hb = None
        # a per-op timeout so a peer dying mid-frame cannot wedge the
        # recv loop forever; idle waits use select (see create), so this
        # never fires between messages on a healthy link
        sock.settimeout(max(0.1, float(self.timeout)))
        # published only now: a concurrent stop() severs either the old
        # socket (handshake fails cleanly) or this one, never a half
        # handshake on a nulled attribute
        self._sock = sock
        if self._stop_evt.is_set():
            _sever(sock)
            raise ConnectionError(f"{self.name}: stopped during subscribe")
        self._breaker.record_success()
        caps_str = meta.get("caps") or "other/tensors,format=flexible"
        return Caps(caps_str)

    def _resume(self, sock, scfg: sess_mod.SessionConfig) -> None:
        """RESUME handshake on a fresh socket: present (sid, last
        delivered), adopt the publisher's answer, account the declared
        gap exactly."""
        if self._sess is not None:
            last = self._sess.last_delivered
        elif self._restored_last is not None:
            last = self._restored_last  # resurrected: resume, not attach
        else:
            last = 0
        send_msg(sock, MsgKind.RESUME,
                 {"sid": self._sid, "last": last})
        kind, meta, _ = recv_msg(sock)
        if kind != MsgKind.RESUME_ACK:
            raise ConnectionError(f"{self.name}: expected RESUME_ACK, "
                                  f"got {kind}")
        if self._sess is None:
            self._sess = sess_mod.SessionReceiver(scfg)
            if meta.get("resumed", False) and self._restored_last is not None:
                # the publisher still knows this session: dedup resumes
                # at the restored watermark, the gap replays below
                self._sess.reset(self._restored_last)
            else:
                self._sess.reset(int(meta.get("base", 0)))
            self._restored_last = None  # racecheck: ok(written by restore_state before start(); afterwards only this source-loop resume path touches it)
        elif not meta.get("resumed", False):
            # the publisher no longer knows us (restarted: ring and seq
            # space gone). The in-flight gap is unresolvable — declare
            # the reset loudly and adopt the new seq space.
            self.stats.inc("session_resets")
            self.post_message(
                "warning", session=self._sid[:8],
                detail="publisher lost our session (restart?); "
                       "in-flight frames from the old session are gone")
            self._sess.reset(int(meta.get("base", 0)))
        lost = int(meta.get("lost", 0))
        if lost:
            # exact declared loss: the publisher's ring evicted this
            # many frames of our gap. Counted, posted, never silent.
            self.stats.inc("session_declared_lost", lost)
            self.post_message("warning", session=self._sid[:8],
                              frames_lost=lost,
                              detail="publisher replay ring evicted part "
                                     "of our reconnect gap")
        hb_ms = float(self.heartbeat_ms)
        if hb_ms > 0 and self._hb is None:
            self._hb = sess_mod.Heartbeat(hb_ms / 1e3,
                                          int(self.heartbeat_miss))

    def negotiate_src_caps(self) -> Optional[Caps]:
        return self._subscribe()

    def _reconnect(self) -> bool:
        """Re-dial after a dropped link; True when resubscribed (and,
        with a session, resumed: the gap is already replayed or
        declared by the time this returns)."""
        sock, self._sock = self._sock, None
        _sever(sock)
        try:
            self._subscribe()
        except (ConnectionError, OSError) as exc:
            logger.warning("%s: reconnect failed: %s", self.name, exc)
            return False
        self.stats.inc("reconnects")
        self.post_message("warning", reconnects=self.stats["reconnects"],
                          detail="publisher link re-established")
        return True

    # -- session housekeeping (source loop only: single socket writer) --
    def _maybe_ack(self) -> None:
        sock = self._sock
        if self._sess is None or sock is None:
            return
        due = self._sess.ack_due()
        if due is not None:
            # advisory: a failed ACK is not a link error here — the
            # next recv on the dead socket reports it exactly once
            try:
                send_msg(sock, MsgKind.ACK,
                         {"sid": self._sid, "seq": due}, stats=self.stats)
            except (ConnectionError, OSError):
                return
            self._sess.mark_acked(due)
            self.stats.inc("session_acks_out")

    def _final_ack(self) -> None:
        """Best-effort cumulative ACK of everything delivered (EOS or
        drain teardown): lets the publisher's accounting settle to
        sent == acked."""
        sock = self._sock
        if self._sess is None or sock is None:
            return
        try:
            send_msg(sock, MsgKind.ACK,
                     {"sid": self._sid, "seq": self._sess.last_delivered})
            self._sess.mark_acked(self._sess.last_delivered)
            self.stats.inc("session_acks_out")
        except (ConnectionError, OSError):
            pass

    def _idle_tick(self, sock) -> None:
        """Between messages: flush a due ACK; run the heartbeat (PING
        an idle link, declare a peer dead after heartbeat-miss
        unanswered PINGs — feeding the circuit breaker)."""
        self._maybe_ack()
        hb = self._hb
        if hb is None:
            return
        if hb.peer_dead:
            self._breaker.record_failure()
            raise ConnectionError(
                f"{self.name}: publisher missed {hb.outstanding} "
                f"heartbeats — declaring the link dead")
        if hb.due():
            send_msg(sock, MsgKind.PING, {"t": time.monotonic()},
                     stats=self.stats)
            hb.sent()
            self.stats.inc("session_pings")

    def _idle_wait(self, sock) -> bool:
        """Wait for readable data, bounded so ACK/heartbeat cadence is
        honored on an idle link. True = data is waiting."""
        tmo = 0.5
        if self._sess is not None:
            tmo = min(tmo, max(0.01, float(self.ack_ms) / 1e3))
        if self._hb is not None:
            tmo = min(tmo, self._hb.interval_s / 2)
        r, _w, _x = select.select([sock], [], [], tmo)
        return bool(r)

    def create(self) -> Optional[Buffer]:
        if self._rxq:
            return self._rxq.popleft()
        while not self._stop_evt.is_set():
            # snapshot: stop()/kill_link() may null/close _sock from
            # another thread mid-iteration
            sock = self._sock
            try:
                if sock is None:
                    raise ConnectionError(f"{self.name}: link closed")
                if not self._idle_wait(sock):
                    self._idle_tick(sock)
                    continue
                kind, meta, payloads = recv_msg(sock, stats=self.stats)
            except (ConnectionError, OSError, ValueError) as exc:
                if self._stop_evt.is_set():
                    return None
                if self._drain_evt.is_set():
                    # deliberate drain teardown, not a link fault: the
                    # received tail was already flushed via _rxq
                    return None
                self.stats.inc("link_errors")
                self._breaker.record_failure()
                logger.info("%s: publisher link lost (%r)", self.name, exc)
                if self.reconnect and self._reconnect():
                    continue
                return None
            if self._hb is not None:
                self._hb.heard()
            if kind == MsgKind.DATA:
                try:
                    buf = wire.unpack_buffer(meta, payloads,
                                             stats=self.stats,
                                             cfg=self._wire_cfg)
                except ValueError as exc:
                    if self._decode_failed(exc):
                        continue
                    return None
                if self._sess is not None:
                    if not self._sess.admit(meta.get("seq")):
                        # a replayed frame we already delivered before
                        # the outage: drop the duplicate, count it
                        self.stats.inc("session_dup_drops")
                        self._maybe_ack()
                        continue
                    self.stats.inc("session_delivered")
                    self._maybe_ack()
                return buf
            if kind == MsgKind.DATA_BATCH:
                try:
                    frames = wire.unpack_batch(meta, payloads,
                                               stats=self.stats,
                                               cfg=self._wire_cfg)
                except ValueError as exc:
                    if self._decode_failed(exc):
                        continue
                    return None
                if self._sess is not None:
                    kept = []
                    for f in frames:
                        if self._sess.admit(f.extras.get("seq")):
                            kept.append(f)
                        else:
                            self.stats.inc("session_dup_drops")
                    self.stats.inc("session_delivered", len(kept))
                    frames = kept
                    self._maybe_ack()
                if not frames:
                    continue
                self._rxq.extend(frames[1:])
                return frames[0]
            if kind == MsgKind.PONG:
                if self._hb is not None:
                    rtt = self._hb.pong(meta.get("t", 0.0))
                    self.stats.add(session_pongs=1,
                                   session_rtt_ns=int(rtt * 1e9))
                self._breaker.record_success()
                continue
            if kind == MsgKind.DRAIN:
                # publisher is draining: it will flush + EOS shortly;
                # nothing to do but note it (we keep receiving the tail)
                self.stats.inc("peer_drains")
                continue
            if kind == MsgKind.EOS:
                self._final_ack()
                return None
        return None

    def _decode_failed(self, exc: ValueError) -> bool:
        """An undecodable frame (e.g. a delta diff against a reference
        this side does not hold — never silently patch the wrong
        baseline) is a link fault: tear the connection down and
        re-handshake. The fresh link restarts from a keyframe and a
        session resume replays the gap. True = reconnected."""
        if self._stop_evt.is_set() or self._drain_evt.is_set():
            return False
        self.stats.inc("link_errors")
        self._breaker.record_failure()
        logger.warning("%s: undecodable frame (%s); re-subscribing",
                       self.name, exc)
        return bool(self.reconnect and self._reconnect())

    def drain_flushed(self) -> bool:
        return not self._rxq

    # -- checkpoint/restore (checkpoint/) ----------------------------------
    CHECKPOINTABLE = ("session id + delivery watermark (the RESUME key "
                      "for gap replay after restart)")

    def snapshot_state(self, snap_dir):
        if not self.session:
            return None
        return {"sid": self._sid,
                "last": (self._sess.last_delivered
                         if self._sess is not None
                         else self._restored_last)}

    def restore_state(self, state, snap_dir):
        self._sid = str(state["sid"])
        last = state.get("last")
        self._restored_last = int(last) if last is not None else None

    def drain(self) -> None:
        """Graceful local teardown: ack what we delivered, then close
        the link so the source loop ends the stream as EOS (frames
        already received — the _rxq tail — are flushed first; nothing
        is counted as a link error)."""
        super().drain()
        sock = self._sock
        if sock is not None:
            self._final_ack()
            _sever(sock)

    def kill_link(self) -> int:
        """Chaos hook (tensor_fault mode=kill-link): force-close the
        live publisher socket mid-stream. The source loop sees the
        failure, reconnects, and resumes the session."""
        sock = self._sock
        if sock is None:
            return 0
        _sever(sock)
        self.stats.inc("link_kills")
        return 1

    def session_info(self) -> Dict:
        if self._sess is None:
            return {}
        return {"sid": self._sid[:8],
                "last_delivered": self._sess.last_delivered}

    def stop(self) -> None:
        # order matters: the stop flag first, so a create() loop that
        # sees its socket die does not dial one more reconnect
        self._stop_evt.set()
        if self._sock is not None:
            _sever(self._sock)
            self._sock = None
        super().stop()
