"""edgesink / edgesrc — tensor stream pub/sub between pipelines/hosts.

≙ gst/edge/edge_sink.c + edge_src.c (thin publisher/subscriber over
nnstreamer-edge): edgesink accepts N subscribers and broadcasts every
buffer; edgesrc connects and replays the feed into its pipeline.
Topic filtering mirrors the MQTT-hybrid topic semantics: a subscriber
passes ``topic`` at SUBSCRIBE and only receives matching streams.
"""
from __future__ import annotations

import collections
import socket
import threading
import time
from typing import Dict, List, Optional

from ..edge import wire
from ..edge.protocol import MsgKind, recv_msg, send_msg
from ..pipeline.element import SinkElement, SrcElement
from ..pipeline.pad import Pad
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer
from ..tensors.caps import Caps
from ..utils.log import logger


@register_element("edgesink")
class EdgeSink(SinkElement):
    PROPS = {"host": "localhost", "port": 3000, "topic": "",
             "connect-type": "TCP",
             # wire v2 link request, applied per subscriber that
             # advertises support (v1 subscribers keep plain framing):
             # lossless payload codec + opt-in lossy fp32 downcast
             "wire-codec": "raw", "wire-precision": "none",
             # frame coalescing: broadcast up to N frames per message
             # (DATA_BATCH, v2 subscribers only), flushing a partial
             # batch once its oldest frame has waited coalesce-ms
             "coalesce-frames": 1, "coalesce-ms": 5.0}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._listener: Optional[socket.socket] = None
        # (socket, negotiated wire config | None) per subscriber
        self._subs: List[tuple] = []
        self._subs_lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._caps_str = ""
        # coalesce state: the chain thread appends + size-flushes, the
        # flush worker age-flushes. _co_lock is held across the whole
        # take-and-send so the two flushers can neither interleave bytes
        # on a subscriber socket nor reorder batches (send_msg itself
        # never blocks under a peer's backpressure longer than the
        # kernel buffer allows — the same exposure render always had)
        self._co_lock = threading.Lock()
        self._co_pending: List[Buffer] = []
        self._co_t0 = 0.0
        self._flush_thread: Optional[threading.Thread] = None

    @property
    def bound_port(self) -> int:
        return self._listener.getsockname()[1] if self._listener else self.port

    def start(self) -> None:
        super().start()
        self._stop_evt.clear()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self._listener.listen(16)
        threading.Thread(target=self._accept_loop,
                         name=f"edgesink-accept:{self.name}",
                         daemon=True).start()
        if int(self.coalesce_frames) > 1:
            self._flush_thread = threading.Thread(
                target=self._flush_loop,
                name=f"edgesink-flush:{self.name}", daemon=True)
            self._flush_thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        with self._subs_lock:
            for s, _cfg in self._subs:
                try:
                    s.close()
                except OSError:
                    pass
            self._subs.clear()
        super().stop()

    def on_sink_caps(self, pad: Pad, caps: Caps) -> None:
        self._caps_str = str(caps)

    def handle_event(self, pad, event) -> None:
        from ..pipeline.events import CapsEvent
        if isinstance(event, CapsEvent):
            pad.set_caps(event.caps)
            self.on_sink_caps(pad, event.caps)
            return
        super().handle_event(pad, event)

    def _accept_loop(self) -> None:
        while not self._stop_evt.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            try:
                kind, meta, _ = recv_msg(conn)
                want = meta.get("topic", "")
                if kind != MsgKind.SUBSCRIBE or \
                        (self.topic and want and want != self.topic):
                    send_msg(conn, MsgKind.ERROR, {"reason": "topic mismatch"})
                    conn.close()
                    continue
                # wire v2: fold the subscriber's advertisement into OUR
                # requested codec/precision; a v1 subscriber (no "wire"
                # block) gets plain framing and never sees DATA_BATCH
                cfg = wire.negotiate(meta.get("wire"),
                                     codec=str(self.wire_codec),
                                     precision=str(self.wire_precision))
                ack = {"caps": self._caps_str, "topic": self.topic}
                if cfg is not None:
                    ack["wire"] = cfg.to_meta()
                send_msg(conn, MsgKind.CAPS_ACK, ack)
                wire.tune_socket(conn)
            except (ConnectionError, OSError):
                continue
            with self._subs_lock:
                self._subs.append((conn, cfg))

    def render(self, buf: Buffer) -> None:
        if int(self.coalesce_frames) <= 1:
            self._broadcast([buf])
            return
        with self._co_lock:
            if self._co_pending and \
                    not wire.batch_compatible(self._co_pending[0], buf):
                # layout change: ship what we have, open a new batch
                self._broadcast(self._co_pending)
                self._co_pending = []
            if not self._co_pending:
                self._co_t0 = time.monotonic()
            self._co_pending.append(buf)
            if len(self._co_pending) >= int(self.coalesce_frames):
                take, self._co_pending = self._co_pending, []
                self._broadcast(take)

    def _flush_loop(self) -> None:
        """Age flush: a partial batch never waits longer than
        coalesce-ms for stragglers (mirrors the serve batcher's
        max-wait discipline)."""
        max_age = max(1e-3, float(self.coalesce_ms) / 1e3)
        while not self._stop_evt.is_set():
            self._stop_evt.wait(max_age / 2)
            with self._co_lock:
                if self._co_pending and \
                        time.monotonic() - self._co_t0 >= max_age:
                    take, self._co_pending = self._co_pending, []
                    self._broadcast(take)

    def _broadcast(self, frames: List[Buffer]) -> None:
        """Fan one or more frames out to every subscriber: v2 links get
        one DATA_BATCH per flush (or codec'd DATA for a single frame),
        v1 links always get per-frame plain DATA. Messages are packed
        once per distinct negotiated config, not once per subscriber.
        When coalescing is on, callers hold _co_lock so size- and
        age-flushes can neither interleave bytes nor reorder batches."""
        with self._subs_lock:
            subs = list(self._subs)
        dead = []
        packed: dict = {}
        for s, cfg in subs:
            key = None if cfg is None \
                else (cfg.codec, cfg.precision, len(frames) > 1)
            msgs = packed.get(key)
            if msgs is None:
                if cfg is not None and len(frames) > 1:
                    msgs = [(MsgKind.DATA_BATCH,
                             wire.pack_batch(frames, cfg, stats=self.stats))]
                else:
                    msgs = [(MsgKind.DATA,
                             wire.pack_buffer(f, cfg, stats=self.stats))
                            for f in frames]
                if self.topic:
                    for _, (meta, _pls) in msgs:
                        meta["topic"] = self.topic
                packed[key] = msgs
            try:
                for kind, (meta, payloads) in msgs:
                    send_msg(s, kind, meta, payloads, stats=self.stats)
            except (ConnectionError, OSError):
                dead.append(s)
        if dead:
            with self._subs_lock:
                self._subs = [(s, c) for s, c in self._subs
                              if s not in dead]

    def on_eos(self) -> None:
        # ship any coalesced frames still waiting before the EOS marker
        with self._co_lock:
            take, self._co_pending = self._co_pending, []
            if take:
                self._broadcast(take)
        with self._subs_lock:
            subs = list(self._subs)
        for s, _cfg in subs:
            try:
                send_msg(s, MsgKind.EOS, {})
            except (ConnectionError, OSError):
                pass
        super().on_eos()


@register_element("edgesrc")
class EdgeSrc(SrcElement):
    # reconnect=true: a dropped publisher link is re-dialed with
    # exponential backoff + jitter inside the timeout window instead of
    # ending the stream as EOS (set false to keep the old die-on-drop
    # behavior — e.g. when a supervisor owns restarts)
    PROPS = {"dest-host": "localhost", "dest-port": 3000, "topic": "",
             "connect-type": "TCP", "timeout": 10.0, "reconnect": True}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._sock: Optional[socket.socket] = None
        # frames from an unpacked DATA_BATCH beyond the first, drained
        # before the next recv (only the source loop touches this)
        self._rxq: "collections.deque" = collections.deque()
        self.stats.update({"reconnects": 0, "link_errors": 0})

    def _subscribe(self) -> Caps:
        """Connect + SUBSCRIBE handshake (the one dial site: first
        connect and every reconnect share it), backed off with jitter
        inside the timeout budget."""
        from ..fault.backoff import Backoff
        deadline = time.monotonic() + self.timeout
        backoff = Backoff(base=0.05, multiplier=2.0, max_s=1.0)
        last_err = None
        while time.monotonic() < deadline and not self._stop_evt.is_set():
            try:
                self._sock = socket.create_connection(
                    (self.dest_host, int(self.dest_port)),
                    timeout=self.timeout)
                break
            except OSError as e:
                last_err = e
                backoff.sleep(self._stop_evt)
        else:
            raise ConnectionError(
                f"{self.name}: cannot reach edgesink at "
                f"{self.dest_host}:{self.dest_port}: {last_err}")
        wire.tune_socket(self._sock)
        # advertise v2 support; the publisher's wire-codec/precision
        # props decide what this link actually uses (echoed in the ack)
        send_msg(self._sock, MsgKind.SUBSCRIBE,
                 {"topic": self.topic, "wire": wire.advertise()})
        kind, meta, _ = recv_msg(self._sock)
        if kind != MsgKind.CAPS_ACK:
            raise ConnectionError(f"{self.name}: subscribe rejected ({kind})")
        caps_str = meta.get("caps") or "other/tensors,format=flexible"
        return Caps(caps_str)

    def negotiate_src_caps(self) -> Optional[Caps]:
        return self._subscribe()

    def _reconnect(self) -> bool:
        """Re-dial after a dropped link; True when resubscribed."""
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        try:
            self._subscribe()
        except (ConnectionError, OSError) as exc:
            logger.warning("%s: reconnect failed: %s", self.name, exc)
            return False
        self.stats.inc("reconnects")
        self.post_message("warning", reconnects=self.stats["reconnects"],
                          detail="publisher link re-established")
        return True

    def create(self) -> Optional[Buffer]:
        if self._rxq:
            return self._rxq.popleft()
        while not self._stop_evt.is_set():
            try:
                kind, meta, payloads = recv_msg(self._sock, stats=self.stats)
            except (ConnectionError, OSError) as exc:
                if self._stop_evt.is_set():
                    return None
                self.stats.inc("link_errors")
                logger.info("%s: publisher link lost (%r)", self.name, exc)
                if self.reconnect and self._reconnect():
                    continue
                return None
            if kind == MsgKind.DATA:
                return wire.unpack_buffer(meta, payloads, stats=self.stats)
            if kind == MsgKind.DATA_BATCH:
                frames = wire.unpack_batch(meta, payloads, stats=self.stats)
                if not frames:
                    continue
                self._rxq.extend(frames[1:])
                return frames[0]
            if kind == MsgKind.EOS:
                return None
        return None

    def stop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        super().stop()
