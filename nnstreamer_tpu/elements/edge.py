"""edgesink / edgesrc — tensor stream pub/sub between pipelines/hosts.

≙ gst/edge/edge_sink.c + edge_src.c (thin publisher/subscriber over
nnstreamer-edge): edgesink accepts N subscribers and broadcasts every
buffer; edgesrc connects and replays the feed into its pipeline.
Topic filtering mirrors the MQTT-hybrid topic semantics: a subscriber
passes ``topic`` at SUBSCRIBE and only receives matching streams.
"""
from __future__ import annotations

import socket
import threading
import time
from typing import Dict, List, Optional

from ..edge.protocol import (MsgKind, buffer_to_wire, recv_msg, send_msg,
                             wire_to_buffer)
from ..pipeline.element import SinkElement, SrcElement
from ..pipeline.pad import Pad
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer
from ..tensors.caps import Caps
from ..utils.log import logger


@register_element("edgesink")
class EdgeSink(SinkElement):
    PROPS = {"host": "localhost", "port": 3000, "topic": "",
             "connect-type": "TCP"}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._listener: Optional[socket.socket] = None
        self._subs: List[socket.socket] = []
        self._subs_lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._caps_str = ""

    @property
    def bound_port(self) -> int:
        return self._listener.getsockname()[1] if self._listener else self.port

    def start(self) -> None:
        super().start()
        self._stop_evt.clear()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self._listener.listen(16)
        threading.Thread(target=self._accept_loop,
                         name=f"edgesink-accept:{self.name}",
                         daemon=True).start()

    def stop(self) -> None:
        self._stop_evt.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        with self._subs_lock:
            for s in self._subs:
                try:
                    s.close()
                except OSError:
                    pass
            self._subs.clear()
        super().stop()

    def on_sink_caps(self, pad: Pad, caps: Caps) -> None:
        self._caps_str = str(caps)

    def handle_event(self, pad, event) -> None:
        from ..pipeline.events import CapsEvent
        if isinstance(event, CapsEvent):
            pad.set_caps(event.caps)
            self.on_sink_caps(pad, event.caps)
            return
        super().handle_event(pad, event)

    def _accept_loop(self) -> None:
        while not self._stop_evt.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            try:
                kind, meta, _ = recv_msg(conn)
                want = meta.get("topic", "")
                if kind != MsgKind.SUBSCRIBE or \
                        (self.topic and want and want != self.topic):
                    send_msg(conn, MsgKind.ERROR, {"reason": "topic mismatch"})
                    conn.close()
                    continue
                send_msg(conn, MsgKind.CAPS_ACK,
                         {"caps": self._caps_str, "topic": self.topic})
            except (ConnectionError, OSError):
                continue
            with self._subs_lock:
                self._subs.append(conn)

    def render(self, buf: Buffer) -> None:
        meta, payloads = buffer_to_wire(buf)
        if self.topic:
            meta["topic"] = self.topic
        dead = []
        with self._subs_lock:
            subs = list(self._subs)
        for s in subs:
            try:
                send_msg(s, MsgKind.DATA, meta, payloads)
            except (ConnectionError, OSError):
                dead.append(s)
        if dead:
            with self._subs_lock:
                for s in dead:
                    if s in self._subs:
                        self._subs.remove(s)

    def on_eos(self) -> None:
        with self._subs_lock:
            subs = list(self._subs)
        for s in subs:
            try:
                send_msg(s, MsgKind.EOS, {})
            except (ConnectionError, OSError):
                pass
        super().on_eos()


@register_element("edgesrc")
class EdgeSrc(SrcElement):
    # reconnect=true: a dropped publisher link is re-dialed with
    # exponential backoff + jitter inside the timeout window instead of
    # ending the stream as EOS (set false to keep the old die-on-drop
    # behavior — e.g. when a supervisor owns restarts)
    PROPS = {"dest-host": "localhost", "dest-port": 3000, "topic": "",
             "connect-type": "TCP", "timeout": 10.0, "reconnect": True}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._sock: Optional[socket.socket] = None
        self.stats.update({"reconnects": 0, "link_errors": 0})

    def _subscribe(self) -> Caps:
        """Connect + SUBSCRIBE handshake (the one dial site: first
        connect and every reconnect share it), backed off with jitter
        inside the timeout budget."""
        from ..fault.backoff import Backoff
        deadline = time.monotonic() + self.timeout
        backoff = Backoff(base=0.05, multiplier=2.0, max_s=1.0)
        last_err = None
        while time.monotonic() < deadline and not self._stop_evt.is_set():
            try:
                self._sock = socket.create_connection(
                    (self.dest_host, int(self.dest_port)),
                    timeout=self.timeout)
                break
            except OSError as e:
                last_err = e
                backoff.sleep(self._stop_evt)
        else:
            raise ConnectionError(
                f"{self.name}: cannot reach edgesink at "
                f"{self.dest_host}:{self.dest_port}: {last_err}")
        send_msg(self._sock, MsgKind.SUBSCRIBE, {"topic": self.topic})
        kind, meta, _ = recv_msg(self._sock)
        if kind != MsgKind.CAPS_ACK:
            raise ConnectionError(f"{self.name}: subscribe rejected ({kind})")
        caps_str = meta.get("caps") or "other/tensors,format=flexible"
        return Caps(caps_str)

    def negotiate_src_caps(self) -> Optional[Caps]:
        return self._subscribe()

    def _reconnect(self) -> bool:
        """Re-dial after a dropped link; True when resubscribed."""
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        try:
            self._subscribe()
        except (ConnectionError, OSError) as exc:
            logger.warning("%s: reconnect failed: %s", self.name, exc)
            return False
        self.stats.inc("reconnects")
        self.post_message("warning", reconnects=self.stats["reconnects"],
                          detail="publisher link re-established")
        return True

    def create(self) -> Optional[Buffer]:
        while not self._stop_evt.is_set():
            try:
                kind, meta, payloads = recv_msg(self._sock)
            except (ConnectionError, OSError) as exc:
                if self._stop_evt.is_set():
                    return None
                self.stats.inc("link_errors")
                logger.info("%s: publisher link lost (%r)", self.name, exc)
                if self.reconnect and self._reconnect():
                    continue
                return None
            if kind == MsgKind.DATA:
                return wire_to_buffer(meta, payloads)
            if kind == MsgKind.EOS:
                return None
        return None

    def stop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        super().stop()
