"""mqttsink / mqttsrc — tensor streams over a real MQTT 3.1.1 broker,
with cross-device base-time synchronization.

≙ gst/mqtt/mqttsink.c + mqttsrc.c (GstBuffer over Paho MQTT): the
transport is the actual MQTT wire protocol (edge/mqtt_wire.py), so these
elements interop with mosquitto or any standard broker — the in-process
MqttBroker (edge/mqtt.py) is just a convenient one. Each PUBLISH payload
is the reference's GstMQTTMessageHdr layout (mqttcommon.h:49-63): a
1024-byte header carrying num_mems/size_mems/base & sent epoch (ns)/
duration/dts/pts/caps-string, followed by the raw tensor memories — so
payloads are byte-compatible with reference publishers/subscribers.

Re-timing (ref: Documentation/synchronization-in-mqtt-elements.md):

    buf.pts = hdr.pts + (hdr.base_time_epoch - sub.base_time_epoch)

With ``ntp-sync=true`` the base-time epoch comes from the configured NTP
servers (``ntp-srvs``, ≙ mqtt-ntp-sync/mqtt-ntp-srvs + ntputil.c)
instead of the local clock, so devices whose clocks drift still agree.
"""
from __future__ import annotations

import socket
import threading
import time
from typing import Optional

import numpy as np

from ..edge import mqtt_wire as mw
from ..edge.ntp import synced_epoch_ns
from ..pipeline.element import SinkElement, SrcElement
from ..pipeline.pad import Pad
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..utils.log import logger


@register_element("mqttsink")
class MqttSink(SinkElement):
    PROPS = {"host": "localhost", "port": 1883, "pub-topic": "",
             "client-id": "", "ntp-sync": False,
             "ntp-srvs": "pool.ntp.org:123", "ntp-timeout": 2.0,
             "debug": False}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._client: Optional[mw.MqttClient] = None
        self._caps_str = ""
        self._base_epoch_ns = 0
        self._base_mono_ns = 0

    def start(self) -> None:
        super().start()
        if not self.pub_topic:
            raise ValueError(f"{self.name}: 'pub-topic' is required")
        # base-time: the universal-time instant this sink went live
        self._base_epoch_ns = synced_epoch_ns(
            self.ntp_srvs if self.ntp_sync else None, self.ntp_timeout)
        self._base_mono_ns = time.monotonic_ns()
        self._client = mw.MqttClient(
            self.host, int(self.port),
            self.client_id or f"nns-tpu-sink-{id(self):x}")

    def stop(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None
        super().stop()

    def on_sink_caps(self, pad: Pad, caps: Caps) -> None:
        self._caps_str = str(caps)

    def handle_event(self, pad, event) -> None:
        from ..pipeline.events import CapsEvent
        if isinstance(event, CapsEvent):
            pad.set_caps(event.caps)
            self.on_sink_caps(pad, event.caps)
            return
        super().handle_event(pad, event)

    def render(self, buf: Buffer) -> None:
        client = self._client
        if client is None:
            return
        mems = [np.ascontiguousarray(c.host()).tobytes() for c in buf.chunks]
        pts = buf.pts
        if pts is None:
            # no timestamp: synthesize the running time at publish
            pts = time.monotonic_ns() - self._base_mono_ns
        # sent-time derives from the start() epoch + monotonic delta: one
        # NTP exchange per element lifetime, none on the streaming path
        sent_epoch = self._base_epoch_ns + (
            time.monotonic_ns() - self._base_mono_ns)
        hdr = mw.pack_msg_hdr([len(m) for m in mems], self._caps_str,
                              self._base_epoch_ns, sent_epoch,
                              buf.duration, buf.dts, pts)
        client.publish(self.pub_topic, hdr + b"".join(mems))
        if self.debug:
            logger.info("%s: published pts=%s to %s", self.name, pts,
                        self.pub_topic)


@register_element("mqttsrc")
class MqttSrc(SrcElement):
    # is-live: accepted for launch-line compatibility (standard basesrc
    # prop on the reference's mqttsrc); this source is inherently live —
    # frames arrive from the broker in real time either way
    PROPS = {"host": "localhost", "port": 1883, "sub-topic": "",
             "client-id": "", "ntp-sync": False,
             "ntp-srvs": "pool.ntp.org:123", "ntp-timeout": 2.0,
             "timeout": 10.0, "is-live": True, "debug": False}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._client: Optional[mw.MqttClient] = None
        self._base_epoch_ns = 0
        self._caps_sent = False
        self._caps_cache: tuple = ("", None, None)  # (str, Caps, infos)

    def negotiate_src_caps(self) -> Optional[Caps]:
        # caps arrive with the first message; negotiated in-stream
        return None

    def start(self) -> None:
        if not self.sub_topic:
            raise ValueError(f"{self.name}: 'sub-topic' is required")
        self._base_epoch_ns = synced_epoch_ns(
            self.ntp_srvs if self.ntp_sync else None, self.ntp_timeout)
        self._client = mw.MqttClient(
            self.host, int(self.port),
            self.client_id or f"nns-tpu-src-{id(self):x}",
            timeout=self.timeout)
        self._client.settimeout(self.timeout)
        self._client.subscribe(self.sub_topic)
        self._caps_sent = False
        super().start()

    def stop(self) -> None:
        # order matters: flag the stop BEFORE closing the socket so a
        # create() racing us re-checks the event instead of touching a
        # nulled client
        self._stop_evt.set()
        client = self._client
        self._client = None
        if client is not None:
            client.close()
        super().stop()

    def create(self) -> Optional[Buffer]:
        while not self._stop_evt.is_set():
            client = self._client
            if client is None:
                return None
            try:
                _topic, payload = client.recv_publish()
            except socket.timeout:
                logger.warning("%s: no message within timeout", self.name)
                return None
            except (ConnectionError, OSError, ValueError):
                return None
            if len(payload) < 1024:
                logger.warning("%s: short mqtt payload dropped", self.name)
                continue
            sizes, caps_str, pub_base, _sent, duration, dts, pts = \
                mw.unpack_msg_hdr(payload)
            # the caps string repeats verbatim frame after frame: parse
            # once and reuse off the hot path
            if caps_str and caps_str == self._caps_cache[0]:
                caps, infos = self._caps_cache[1], self._caps_cache[2]
            elif caps_str:
                caps = Caps(caps_str)
                infos = caps.to_config().info
                self._caps_cache = (caps_str, caps, infos)
            else:
                caps, infos = None, None
            if not self._caps_sent and caps is not None:
                self.set_src_caps(caps)
                self._caps_sent = True
            chunks, off = [], 1024
            for i, sz in enumerate(sizes):
                raw = payload[off:off + sz]
                off += sz
                if infos is not None and i < len(infos):
                    arr = np.frombuffer(
                        raw, dtype=infos[i].type.np_dtype
                    ).reshape(infos[i].shape)
                else:
                    arr = np.frombuffer(raw, np.uint8)
                chunks.append(Chunk(arr))
            buf = Buffer(chunks, pts=pts, dts=dts, duration=duration)
            # re-time into this pipeline's clock domain (see module doc)
            if buf.pts is not None and pub_base:
                buf.pts = max(0, buf.pts + (pub_base - self._base_epoch_ns))
            if self.debug:
                logger.info("%s: received pts=%s", self.name, buf.pts)
            return buf
        return None
