"""mqttsink / mqttsrc — tensor streams over a message broker, with
cross-device base-time synchronization.

≙ gst/mqtt/mqttsink.c + mqttsrc.c (GstBuffer over Paho MQTT): each
published message carries the caps string plus the publisher pipeline's
base-time converted to epoch time; the subscriber re-times buffers into
its own clock domain:

    abs_ts  = pub_base_time_epoch + pts          (publisher side)
    new_pts = abs_ts - sub_base_time_epoch        (subscriber side)

(ref: Documentation/synchronization-in-mqtt-elements.md). With
``ntp-sync=true`` the base-time epoch is taken from the configured NTP
servers (``ntp-srvs``, ≙ mqtt-ntp-sync/mqtt-ntp-srvs + ntputil.c)
instead of the local clock, so devices whose clocks drift still agree.
The broker is edge/mqtt.py's MqttBroker (or anything speaking the same
framing).
"""
from __future__ import annotations

import socket
import threading
import time
from typing import Optional

from ..edge.ntp import synced_epoch_ns
from ..edge.protocol import (MsgKind, buffer_to_wire, recv_msg, send_msg,
                             wire_to_buffer)
from ..pipeline.element import SinkElement, SrcElement
from ..pipeline.pad import Pad
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer
from ..tensors.caps import Caps
from ..utils.log import logger


@register_element("mqttsink")
class MqttSink(SinkElement):
    PROPS = {"host": "localhost", "port": 1883, "pub-topic": "",
             "ntp-sync": False, "ntp-srvs": "pool.ntp.org:123",
             "ntp-timeout": 2.0, "debug": False}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._sock: Optional[socket.socket] = None
        self._send_lock = threading.Lock()
        self._caps_str = ""
        self._base_epoch_ns = 0
        self._base_mono_ns = 0

    def start(self) -> None:
        super().start()
        if not self.pub_topic:
            raise ValueError(f"{self.name}: 'pub-topic' is required")
        # base-time: the universal-time instant this sink went live
        self._base_epoch_ns = synced_epoch_ns(
            self.ntp_srvs if self.ntp_sync else None, self.ntp_timeout)
        self._base_mono_ns = time.monotonic_ns()
        self._sock = socket.create_connection((self.host, int(self.port)),
                                              timeout=10.0)

    def stop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        super().stop()

    def on_sink_caps(self, pad: Pad, caps: Caps) -> None:
        self._caps_str = str(caps)

    def handle_event(self, pad, event) -> None:
        from ..pipeline.events import CapsEvent
        if isinstance(event, CapsEvent):
            pad.set_caps(event.caps)
            self.on_sink_caps(pad, event.caps)
            return
        super().handle_event(pad, event)

    def render(self, buf: Buffer) -> None:
        meta, payloads = buffer_to_wire(buf)
        meta["topic"] = self.pub_topic
        meta["caps"] = self._caps_str
        meta["base_time_epoch_ns"] = self._base_epoch_ns
        if buf.pts is None:
            # no timestamp: synthesize the running time at publish
            meta["pts"] = time.monotonic_ns() - self._base_mono_ns
        with self._send_lock:
            send_msg(self._sock, MsgKind.PUBLISH, meta, payloads)
        if self.debug:
            logger.info("%s: published pts=%s to %s", self.name,
                        meta["pts"], self.pub_topic)


@register_element("mqttsrc")
class MqttSrc(SrcElement):
    # is-live: accepted for launch-line compatibility (standard basesrc
    # prop on the reference's mqttsrc); this source is inherently live —
    # frames arrive from the broker in real time either way
    PROPS = {"host": "localhost", "port": 1883, "sub-topic": "",
             "ntp-sync": False, "ntp-srvs": "pool.ntp.org:123",
             "ntp-timeout": 2.0, "timeout": 10.0, "is-live": True,
             "debug": False}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._sock: Optional[socket.socket] = None
        self._base_epoch_ns = 0
        self._caps_sent = False

    def negotiate_src_caps(self) -> Optional[Caps]:
        # caps arrive with the first message; negotiated in-stream
        return None

    def start(self) -> None:
        if not self.sub_topic:
            raise ValueError(f"{self.name}: 'sub-topic' is required")
        self._base_epoch_ns = synced_epoch_ns(
            self.ntp_srvs if self.ntp_sync else None, self.ntp_timeout)
        self._sock = socket.create_connection((self.host, int(self.port)),
                                              timeout=self.timeout)
        self._sock.settimeout(self.timeout)
        send_msg(self._sock, MsgKind.SUBSCRIBE, {"topic": self.sub_topic})
        self._caps_sent = False
        super().start()

    def stop(self) -> None:
        # order matters: flag the stop BEFORE closing the socket so a
        # create() racing us re-checks the event instead of touching a
        # nulled socket
        self._stop_evt.set()
        ss = self._sock
        self._sock = None
        if ss is not None:
            try:
                ss.close()
            except OSError:
                pass
        super().stop()

    def create(self) -> Optional[Buffer]:
        while not self._stop_evt.is_set():
            sock = self._sock
            if sock is None:
                return None
            try:
                kind, meta, payloads = recv_msg(sock)
            except socket.timeout:
                logger.warning("%s: no message within timeout", self.name)
                return None
            except (ConnectionError, OSError):
                return None
            if kind != MsgKind.PUBLISH:
                continue
            if not self._caps_sent and meta.get("caps"):
                self.set_src_caps(Caps(meta["caps"]))
                self._caps_sent = True
            buf = wire_to_buffer(meta, payloads)
            # re-time into this pipeline's clock domain (see module doc)
            pub_base = meta.get("base_time_epoch_ns")
            if buf.pts is not None and pub_base is not None:
                abs_ts = pub_base + buf.pts
                buf.pts = max(0, abs_ts - self._base_epoch_ns)
            if self.debug:
                logger.info("%s: received pts=%s", self.name, buf.pts)
            return buf
        return None
