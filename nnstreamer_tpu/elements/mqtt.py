"""mqttsink / mqttsrc — tensor streams over a real MQTT 3.1.1 broker,
with cross-device base-time synchronization.

≙ gst/mqtt/mqttsink.c + mqttsrc.c (GstBuffer over Paho MQTT): the
transport is the actual MQTT wire protocol (edge/mqtt_wire.py), so these
elements interop with mosquitto or any standard broker — the in-process
MqttBroker (edge/mqtt.py) is just a convenient one. Each PUBLISH payload
is the reference's GstMQTTMessageHdr layout (mqttcommon.h:49-63): a
1024-byte header carrying num_mems/size_mems/base & sent epoch (ns)/
duration/dts/pts/caps-string, followed by the raw tensor memories — so
payloads are byte-compatible with reference publishers/subscribers.

Re-timing (ref: Documentation/synchronization-in-mqtt-elements.md):

    buf.pts = hdr.pts + (hdr.base_time_epoch - sub.base_time_epoch)

With ``ntp-sync=true`` the base-time epoch comes from the configured NTP
servers (``ntp-srvs``, ≙ mqtt-ntp-sync/mqtt-ntp-srvs + ntputil.c)
instead of the local clock, so devices whose clocks drift still agree.
"""
from __future__ import annotations

import socket
import threading
import time
from typing import Optional

import numpy as np

from ..edge import mqtt_wire as mw
from ..edge.ntp import synced_epoch_ns
from ..pipeline.element import SinkElement, SrcElement
from ..pipeline.pad import Pad
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..utils.log import logger


@register_element("mqttsink")
class MqttSink(SinkElement):
    # mqtt-qos: 0 (default, fire-and-forget — the reference mqttsink's
    # DEFAULT_MQTT_QOS) or 1 (at-least-once: each publish waits for the
    # broker's PUBACK and retransmits with DUP; unconfirmed frames are
    # redelivered over a fresh connection). Named "mqtt-qos" exactly as
    # the reference (mqttsink.c:314) because the base sink owns "qos"
    # (latency-based frame dropping) — two different knobs.
    # max-backlog bounds the qos1 hold queue during a broker outage:
    # when full, the OLDEST frame drops (counted in stats) — unbounded
    # retention would OOM the process on a long outage, losing
    # everything instead of the tail
    PROPS = {"host": "localhost", "port": 1883, "pub-topic": "",
             "client-id": "", "ntp-sync": False,
             "ntp-srvs": "pool.ntp.org:123", "ntp-timeout": 2.0,
             "mqtt-qos": 0, "max-backlog": 256, "debug": False}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._client: Optional[mw.MqttClient] = None
        self._caps_str = ""
        self._base_epoch_ns = 0
        self._base_mono_ns = 0
        # qos1 frames not yet confirmed by any broker (send order);
        # survives reconnect failures — at-least-once means held, not
        # dropped, until a broker acks them (bounded by max-backlog)
        self._q1_backlog: list = []
        self._next_reconnect = 0.0
        # exponential reconnect spacing: a long outage must not pay a
        # 2 s connect stall on every render (reset on the first flush
        # that reaches the broker again)
        from ..fault.backoff import Backoff
        self._reconnect_backoff = Backoff(base=0.25, multiplier=2.0,
                                          max_s=5.0)
        self.stats["backlog_dropped"] = 0

    def _connect(self, timeout: float = 10.0) -> mw.MqttClient:
        """The one connect site: start() and the qos1 reconnect must
        never drift apart in connection options."""
        return mw.MqttClient(
            self.host, int(self.port),
            self.client_id or f"nns-tpu-sink-{id(self):x}",
            timeout=timeout)

    def start(self) -> None:
        super().start()
        if not self.pub_topic:
            raise ValueError(f"{self.name}: 'pub-topic' is required")
        # base-time: the universal-time instant this sink went live
        self._base_epoch_ns = synced_epoch_ns(
            self.ntp_srvs if self.ntp_sync else None, self.ntp_timeout)
        self._base_mono_ns = time.monotonic_ns()
        self._client = self._connect()

    def stop(self) -> None:
        if self._q1_backlog:
            # last best-effort flush: a backlog held through a broker
            # outage gets one final shot (skipping the backoff gate)
            # before the held frames are declared — a failure here must
            # still reach close(), never leak the client
            self._next_reconnect = 0.0
            try:
                self._flush_qos1()
            except Exception:  # noqa: BLE001 — stop() must complete
                logger.warning("%s: final qos1 flush failed",
                               self.name, exc_info=True)
        if self._q1_backlog:
            logger.warning("%s: stopping with %d unconfirmed qos1 "
                           "frame(s)", self.name, len(self._q1_backlog))
        if self._client is not None:
            self._client.close()
            self._client = None
        super().stop()

    def on_sink_caps(self, pad: Pad, caps: Caps) -> None:
        self._caps_str = str(caps)

    def handle_event(self, pad, event) -> None:
        from ..pipeline.events import CapsEvent
        if isinstance(event, CapsEvent):
            pad.set_caps(event.caps)
            self.on_sink_caps(pad, event.caps)
            return
        super().handle_event(pad, event)

    def render(self, buf: Buffer) -> None:
        client = self._client
        if client is None and int(self.mqtt_qos) < 1:
            # qos0 with no connection: fire-and-forget has nowhere to
            # fire. qos1 proceeds WITHOUT a client — _flush_qos1 owns
            # reconnection, and a frame rendered while the broker is
            # down must be HELD in the backlog, not silently dropped
            return
        mems = [np.ascontiguousarray(c.host()).tobytes() for c in buf.chunks]
        pts = buf.pts
        if pts is None:
            # no timestamp: synthesize the running time at publish
            pts = time.monotonic_ns() - self._base_mono_ns
        # sent-time derives from the start() epoch + monotonic delta: one
        # NTP exchange per element lifetime, none on the streaming path
        sent_epoch = self._base_epoch_ns + (
            time.monotonic_ns() - self._base_mono_ns)
        hdr = mw.pack_msg_hdr([len(m) for m in mems], self._caps_str,
                              self._base_epoch_ns, sent_epoch,
                              buf.duration, buf.dts, pts)
        payload = hdr + b"".join(mems)
        if int(self.mqtt_qos) >= 1:
            self._q1_backlog.append((self.pub_topic, payload))
            self._flush_qos1()
        else:
            client.publish(self.pub_topic, payload)
        if self.debug:
            logger.info("%s: published pts=%s to %s", self.name, pts,
                        self.pub_topic)

    def _flush_qos1(self) -> None:
        """Drain the at-least-once backlog, reconnecting on a dead
        broker link. Frames a dead client could not confirm are
        reclaimed (take_unacked) and kept in order; a failed reconnect
        HOLDS the backlog for the next render instead of dropping it,
        and leaves no closed client behind to poison later sends.

        Two stall guards keep the streaming thread live through an
        outage: reconnects use a short (2 s) connect timeout and back
        off exponentially (0.25 s doubling to 5 s) after failures
        (frames keep accumulating in the backlog meanwhile, they just
        don't each pay a connect attempt; the ladder resets once a
        flush succeeds), and the backlog is capped at max-backlog
        (oldest frame drops, counted — bounded memory beats a certain
        OOM that would lose every held frame anyway)."""
        cap = max(1, int(self.max_backlog))
        while len(self._q1_backlog) > cap:
            self._q1_backlog.pop(0)
            self.stats.inc("backlog_dropped")
        if self._client is None and time.monotonic() < self._next_reconnect:
            return  # back off: let frames queue without a connect stall
        for _attempt in range(2):
            try:
                if self._client is None:
                    self._client = self._connect(timeout=2.0)
                while self._q1_backlog:
                    topic, payload = self._q1_backlog.pop(0)
                    # on failure the message sits in client._unacked,
                    # reclaimed below — popped-then-lost cannot happen
                    self._client.publish(topic, payload, qos=1)
                self._reconnect_backoff.reset()
                return
            except (ConnectionError, OSError) as exc:
                dead, self._client = self._client, None
                if dead is not None:
                    self._q1_backlog = dead.take_unacked() \
                        + self._q1_backlog
                    dead.close()
                delay = self._reconnect_backoff.next()
                self._next_reconnect = time.monotonic() + delay
                logger.warning("%s: qos1 publish failed (%s); %d "
                               "frame(s) held for redelivery, next "
                               "reconnect in %.2fs", self.name, exc,
                               len(self._q1_backlog), delay)


@register_element("mqttsrc")
class MqttSrc(SrcElement):
    # is-live: accepted for launch-line compatibility (standard basesrc
    # prop on the reference's mqttsrc); this source is inherently live —
    # frames arrive from the broker in real time either way
    # mqtt-qos: requested subscription qos (granted = min(1, requested)
    # by the broker; qos1 deliveries are PUBACKed by the client layer).
    # Reference-parity name (mqttsrc.c:291) — "qos" belongs to base-sink
    # latency throttling, not to MQTT.
    # reconnect=true: a dropped broker link is re-dialed with
    # exponential backoff within the timeout budget instead of ending
    # the stream as EOS (false restores the old die-on-drop behavior)
    PROPS = {"host": "localhost", "port": 1883, "sub-topic": "",
             "client-id": "", "ntp-sync": False,
             "ntp-srvs": "pool.ntp.org:123", "ntp-timeout": 2.0,
             "timeout": 10.0, "is-live": True, "mqtt-qos": 0,
             "reconnect": True, "debug": False}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._client: Optional[mw.MqttClient] = None
        self._base_epoch_ns = 0
        self._caps_sent = False
        self._caps_cache: tuple = ("", None, None)  # (str, Caps, infos)
        self.stats.update({"reconnects": 0, "link_errors": 0})

    def negotiate_src_caps(self) -> Optional[Caps]:
        # caps arrive with the first message; negotiated in-stream
        return None

    def _connect_subscribe(self) -> mw.MqttClient:
        """The one dial site (start() and every reconnect): connect,
        arm the per-op timeout, subscribe."""
        client = mw.MqttClient(
            self.host, int(self.port),
            self.client_id or f"nns-tpu-src-{id(self):x}",
            timeout=self.timeout)
        client.settimeout(self.timeout)
        client.subscribe(self.sub_topic, qos=int(self.mqtt_qos))
        return client

    def _reconnect(self) -> bool:
        """Re-dial after a dropped broker link; True when resubscribed.
        Bounded by the timeout budget so a permanently-gone broker
        still ends the stream instead of spinning forever."""
        from ..fault.backoff import Backoff
        client, self._client = self._client, None
        if client is not None:
            client.close()
        deadline = time.monotonic() + float(self.timeout)
        backoff = Backoff(base=0.1, multiplier=2.0, max_s=2.0)
        while time.monotonic() < deadline and not self._stop_evt.is_set():
            try:
                self._client = self._connect_subscribe()
            except (ConnectionError, OSError) as exc:
                logger.info("%s: reconnect attempt failed: %r",
                            self.name, exc)
                backoff.sleep(self._stop_evt)
                continue
            self.stats.inc("reconnects")
            self.post_message("warning",
                              reconnects=self.stats["reconnects"],
                              detail="broker link re-established")
            return True
        return False

    def start(self) -> None:
        if not self.sub_topic:
            raise ValueError(f"{self.name}: 'sub-topic' is required")
        self._base_epoch_ns = synced_epoch_ns(
            self.ntp_srvs if self.ntp_sync else None, self.ntp_timeout)
        self._client = self._connect_subscribe()
        self._caps_sent = False
        super().start()

    def stop(self) -> None:
        # order matters: flag the stop BEFORE closing the socket so a
        # create() racing us re-checks the event instead of touching a
        # nulled client
        self._stop_evt.set()
        client = self._client
        self._client = None
        if client is not None:
            client.close()
        super().stop()

    def create(self) -> Optional[Buffer]:
        while not self._stop_evt.is_set():
            client = self._client
            if client is None:
                return None
            try:
                _topic, payload = client.recv_publish()
            except socket.timeout:
                logger.warning("%s: no message within timeout", self.name)
                return None
            except (ConnectionError, OSError, ValueError) as exc:
                if self._stop_evt.is_set():
                    return None
                self.stats.inc("link_errors")
                logger.info("%s: broker link lost (%r)", self.name, exc)
                if self.reconnect and self._reconnect():
                    continue
                return None
            if len(payload) < 1024:
                logger.warning("%s: short mqtt payload dropped", self.name)
                continue
            sizes, caps_str, pub_base, _sent, duration, dts, pts = \
                mw.unpack_msg_hdr(payload)
            # the caps string repeats verbatim frame after frame: parse
            # once and reuse off the hot path
            if caps_str and caps_str == self._caps_cache[0]:
                caps, infos = self._caps_cache[1], self._caps_cache[2]
            elif caps_str:
                caps = Caps(caps_str)
                infos = caps.to_config().info
                self._caps_cache = (caps_str, caps, infos)
            else:
                caps, infos = None, None
            if not self._caps_sent and caps is not None:
                self.set_src_caps(caps)
                self._caps_sent = True
            chunks, off = [], 1024
            for i, sz in enumerate(sizes):
                raw = payload[off:off + sz]
                off += sz
                if infos is not None and i < len(infos):
                    arr = np.frombuffer(
                        raw, dtype=infos[i].type.np_dtype
                    ).reshape(infos[i].shape)
                else:
                    arr = np.frombuffer(raw, np.uint8)
                chunks.append(Chunk(arr))
            buf = Buffer(chunks, pts=pts, dts=dts, duration=duration)
            # re-time into this pipeline's clock domain (see module doc)
            if buf.pts is not None and pub_base:
                buf.pts = max(0, buf.pts + (pub_base - self._base_epoch_ns))
            if self.debug:
                logger.info("%s: received pts=%s", self.name, buf.pts)
            return buf
        return None
