"""tensor_src_grpc / tensor_sink_grpc — real gRPC tensor bridge with
protobuf or flatbuf IDL.

≙ ext/nnstreamer/tensor_source/tensor_src_grpc.c +
tensor_sink/tensor_sink_grpc.c over the C++ core in
ext/nnstreamer/extra/nnstreamer_grpc*.cc. The transport is the actual
gRPC/HTTP2 stack (grpcio — the Python analog of the grpc++ library the
reference links), exposing the reference's TensorService verbatim:

    /nnstreamer.protobuf.TensorService/SendTensors   (client-streaming)
    /nnstreamer.protobuf.TensorService/RecvTensors   (server-streaming)

(and the ``nnstreamer.flatbuf`` service for ``idl=flatbuf``,
≙ nnstreamer.proto:44-50 / nnstreamer.fbs:60-66). Message payloads are
the byte-per-schema ``Tensors`` encodings from interop/tensor_codec.py,
registered as raw-bytes method handlers, so a stock gRPC client built
from the reference's .proto interoperates directly.

Either element can play either role (4 topologies): ``server=true``
hosts the service; ``server=false`` dials a remote TensorService.
"""
from __future__ import annotations

import queue as _pyqueue
import threading
import time
from typing import Any, List, Optional

from ..interop import tensor_codec as tc
from ..pipeline.element import SinkElement, SrcElement
from ..pipeline.pad import Pad
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorInfo, TensorsConfig, TensorsInfo
from ..tensors.types import TensorType
from ..utils.log import logger

_IDL = {
    "protobuf": (tc.pack_protobuf, tc.unpack_protobuf),
    "flatbuf": (tc.pack_flatbuf, tc.unpack_flatbuf),
}

_SENTINEL = object()

# a minimal valid FlatBuffers message holding an empty root table
# (root offset 4 -> table at 8 whose soffset points back to the 2-field
# vtable at 4): protobuf's Empty serializes to b"", flatbuf's does NOT —
# a stock client generated from nnstreamer.fbs reads a real root table
_FLATBUF_EMPTY = bytes([8, 0, 0, 0, 4, 0, 4, 0, 4, 0, 0, 0])


def _service_name(idl: str) -> str:
    return f"nnstreamer.{idl}.TensorService"


def _caps_for_frame(frame: tc.Frame) -> Caps:
    infos = TensorsInfo(
        TensorInfo(n or None, TensorType.from_dtype(a.dtype), a.shape)
        for n, a in zip(frame.names, frame.arrays))
    return Caps.from_config(TensorsConfig(
        infos, rate_n=frame.rate_n, rate_d=frame.rate_d))


class _Endpoint:
    """gRPC plumbing shared by both elements.

    Server role: hosts TensorService with raw-bytes handlers —
    SendTensors feeds ``on_frame``, RecvTensors streams per-subscriber
    queues filled by ``send``. Client role: dials the remote service;
    ``send`` feeds a client-streaming SendTensors call, ``on_frame``
    receives a server-streaming RecvTensors call.
    """

    def __init__(self, element, is_server: bool, host: str, port: int,
                 idl: str, on_frame=None):
        self.element = element
        self.is_server = is_server
        self.host, self.port = host, int(port)
        self.idl = idl
        self.on_frame = on_frame
        self.stop_evt = threading.Event()
        self.lock = threading.Lock()
        self.peers_changed = threading.Condition()
        self._server = None
        self._channel = None
        self._bound = int(port)
        self._subs: List[Any] = []        # per-subscriber queues (server)
        # client-streaming feed; None = not in that role OR stream dead
        self._sendq: Optional[_pyqueue.Queue] = None

    @property
    def bound_port(self) -> int:
        return self._bound

    def peer_count(self) -> int:
        with self.lock:
            n = len(self._subs)
        if not self.is_server:
            # sender liveness = the stream feed; receiver = the channel
            alive = (self._sendq is not None if self.on_frame is None
                     else self._channel is not None)
            n += 1 if alive else 0
        return n

    # -- server role ------------------------------------------------------
    def _serve(self) -> None:
        import grpc
        from concurrent import futures

        ep = self

        def send_tensors(request_iterator, context):
            # client-streaming ingest (≙ SyncServiceImpl::SendTensors)
            with ep.lock:
                ep._subs.append(context)  # count the streamer as a peer
            with ep.peers_changed:
                ep.peers_changed.notify_all()
            try:
                for raw in request_iterator:
                    if ep.stop_evt.is_set():
                        break
                    if ep.on_frame is not None:
                        ep.on_frame(raw)
            finally:
                with ep.lock:
                    if context in ep._subs:
                        ep._subs.remove(context)
            return b"" if ep.idl == "protobuf" else _FLATBUF_EMPTY

        def recv_tensors(request, context):
            # server-streaming feed (≙ SyncServiceImpl::RecvTensors)
            import queue as _q
            sub: "_q.Queue" = _q.Queue(maxsize=64)
            with ep.lock:
                ep._subs.append(sub)
            with ep.peers_changed:
                ep.peers_changed.notify_all()
            try:
                while not ep.stop_evt.is_set() and context.is_active():
                    try:
                        item = sub.get(timeout=0.1)
                    except _q.Empty:
                        continue
                    if item is _SENTINEL:
                        return
                    yield item
            finally:
                with ep.lock:
                    if sub in ep._subs:
                        ep._subs.remove(sub)

        handlers = grpc.method_handlers_generic_handler(
            _service_name(self.idl), {
                "SendTensors": grpc.stream_unary_rpc_method_handler(
                    send_tensors),
                "RecvTensors": grpc.unary_stream_rpc_method_handler(
                    recv_tensors),
            })
        # each streaming handler parks a pool thread for its stream's
        # whole lifetime, so max_workers is the concurrent-peer ceiling
        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=32,
                thread_name_prefix=f"grpc:{self.element.name}"))
        self._server.add_generic_rpc_handlers((handlers,))
        self._bound = self._server.add_insecure_port(
            f"{self.host}:{self.port}")
        if not self._bound:
            raise ConnectionError(
                f"{self.element.name}: cannot bind {self.host}:{self.port}")
        self._server.start()

    # -- client role ------------------------------------------------------
    def _dial(self, receiving: bool, timeout: float) -> None:
        import grpc
        self._channel = grpc.insecure_channel(f"{self.host}:{self.port}")
        try:
            # timeout<=0 means "wait forever for FRAMES" on the element,
            # not "hang start() forever on a down peer" — cap the
            # connect wait so startup always terminates
            grpc.channel_ready_future(self._channel).result(
                timeout=timeout if timeout > 0 else 10.0)
        except grpc.FutureTimeoutError as e:
            self._channel.close()
            self._channel = None
            raise ConnectionError(
                f"{self.element.name}: no gRPC server at "
                f"{self.host}:{self.port}") from e
        svc = _service_name(self.idl)
        if receiving:
            # the Empty request must be a VALID message of the IDL:
            # protobuf's Empty is zero bytes, flatbuf's is a real root
            # table a stock generated server deserializes
            empty = _FLATBUF_EMPTY if self.idl == "flatbuf" else b""
            call = self._channel.unary_stream(f"/{svc}/RecvTensors")(
                empty, wait_for_ready=True)

            def pump():
                try:
                    for raw in call:
                        if self.stop_evt.is_set():
                            break
                        if self.on_frame is not None:
                            self.on_frame(raw)
                except grpc.RpcError as e:
                    if not self.stop_evt.is_set():
                        logger.warning("%s: grpc stream ended: %s",
                                       self.element.name, e)
            self._call = call
        else:
            sendq: "_pyqueue.Queue" = _pyqueue.Queue(maxsize=64)
            self._sendq = sendq

            def feed():
                while True:
                    try:
                        item = sendq.get(timeout=0.1)
                    except _pyqueue.Empty:
                        if self.stop_evt.is_set():
                            return
                        continue
                    if item is _SENTINEL:
                        return
                    yield item

            def pump():
                try:
                    self._channel.stream_unary(f"/{svc}/SendTensors")(
                        feed(), wait_for_ready=True)
                except grpc.RpcError as e:
                    if not self.stop_evt.is_set():
                        logger.warning("%s: grpc send stream failed: %s",
                                       self.element.name, e)
                finally:
                    # stream over (peer died or shutdown): send() must
                    # stop claiming delivery and stop queueing payloads
                    self._sendq = None
                with self.peers_changed:
                    self.peers_changed.notify_all()
        threading.Thread(target=pump, daemon=True,
                         name=f"grpc-pump:{self.element.name}").start()

    def open(self, receiving: bool, timeout: float = 10.0) -> None:
        self.stop_evt.clear()
        if self.is_server:
            self._serve()
        else:
            self._dial(receiving, timeout)

    # -- data -------------------------------------------------------------
    def send(self, payload: bytes) -> int:
        """Hand one serialized frame to every live consumer; returns the
        number of consumers it reached."""
        sendq = self._sendq
        if sendq is not None:  # client-streaming feed (nulled when dead)
            try:
                sendq.put_nowait(payload)
                return 1
            except _pyqueue.Full:  # stream stalled: drop, report undeliverable
                return 0
        if not self.is_server:
            return 0  # client role with a dead stream
        with self.lock:
            subs = [s for s in self._subs if hasattr(s, "put")]
        for sub in subs:
            try:
                sub.put_nowait(payload)
            except Exception:  # noqa: BLE001 — slow subscriber: drop
                pass
        return len(subs)

    def close(self) -> None:
        self.stop_evt.set()
        sendq = self._sendq
        if sendq is not None:
            try:
                sendq.put_nowait(_SENTINEL)
            except _pyqueue.Full:
                pass  # feed() also exits via stop_evt
        with self.lock:
            subs = [s for s in self._subs if hasattr(s, "put")]
        for sub in subs:
            try:
                sub.put_nowait(_SENTINEL)
            except Exception:  # noqa: BLE001
                pass
        call = getattr(self, "_call", None)
        if call is not None:
            call.cancel()
        if self._server is not None:
            self._server.stop(grace=0.5)
            self._server = None
        if self._channel is not None:
            self._channel.close()
            self._channel = None
        with self.peers_changed:
            self.peers_changed.notify_all()


@register_element("tensor_sink_grpc")
class GrpcSink(SinkElement):
    """Outbound: serializes each tensors frame to the IDL and streams it
    over gRPC — SendTensors caller when client, RecvTensors feeder when
    server."""

    PROPS = {"host": "localhost", "port": 55115, "server": True,
             "blocking": True, "idl": "protobuf", "silent": True,
             "timeout": 10.0}  # seconds to wait for a peer; <=0 = forever

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._ep: Optional[_Endpoint] = None
        self._config: Optional[TensorsConfig] = None

    @property
    def bound_port(self) -> int:
        return self._ep.bound_port if self._ep else self.port

    def start(self) -> None:
        super().start()
        if self.idl not in _IDL:
            raise ValueError(f"{self.name}: unknown idl {self.idl!r} "
                             "(protobuf|flatbuf)")
        self._ep = _Endpoint(self, self.server, self.host, self.port,
                             self.idl)
        self._ep.open(receiving=False, timeout=float(self.timeout))

    def stop(self) -> None:
        if self._ep is not None:
            self._ep.close()
            self._ep = None
        super().stop()

    def on_sink_caps(self, pad: Pad, caps: Caps) -> None:
        self._config = caps.to_config()

    def handle_event(self, pad, event) -> None:
        from ..pipeline.events import CapsEvent
        if isinstance(event, CapsEvent):
            pad.set_caps(event.caps)
            self.on_sink_caps(pad, event.caps)
            return
        super().handle_event(pad, event)

    def render(self, buf: Buffer) -> None:
        cfg = self._config
        names = ([i.name or "" for i in cfg.info]
                 if cfg and len(cfg.info) else None)
        frame = tc.Frame([c.host() for c in buf.chunks], names,
                         cfg.rate_n if cfg else 0,
                         cfg.rate_d if cfg else 1)
        payload = _IDL[self.idl][0](frame)
        ep = self._ep  # stop() nulls the attribute while we run
        if ep is None:
            return
        if ep.peer_count() == 0 and self.blocking:
            # blocking mode (≙ the reference's 'blocking' sync stream):
            # wait for a consumer instead of dropping the frame; the
            # reference blocks indefinitely — timeout<=0 matches that
            wait_s = float(self.timeout)
            deadline = (time.monotonic() + wait_s) if wait_s > 0 else None
            with ep.peers_changed:
                while not ep.stop_evt.is_set():
                    if ep.peer_count() or (deadline is not None and
                                           time.monotonic() > deadline):
                        break
                    ep.peers_changed.wait(timeout=0.1)
        if ep.send(payload) == 0 and not self.silent:
            # distinguish the two drop causes: backpressure (peer alive
            # but its stream queue is full) vs genuinely no consumer
            if ep.peer_count():
                logger.warning("%s: peer stream stalled (send queue "
                               "full), frame dropped", self.name)
            else:
                logger.warning("%s: no connected peer, frame dropped",
                               self.name)


@register_element("tensor_src_grpc")
class GrpcSrc(SrcElement):
    """Inbound: receives IDL-serialized tensors frames over gRPC —
    SendTensors service when server, RecvTensors consumer when client —
    and pushes them into the pipeline."""

    # (no 'blocking' knob here: the src is inherently pull-blocking via
    # 'timeout'; an ignored property would mislead, so it is omitted)
    PROPS = {"host": "localhost", "port": 55115, "server": True,
             "idl": "protobuf", "silent": True, "timeout": 10.0}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._ep: Optional[_Endpoint] = None
        self._queue: List[tc.Frame] = []
        self._qcond = threading.Condition()
        self._caps_sent = False

    @property
    def bound_port(self) -> int:
        return self._ep.bound_port if self._ep else self.port

    def negotiate_src_caps(self) -> Optional[Caps]:
        return None  # caps derive from the first received frame

    def start(self) -> None:
        if self.idl not in _IDL:
            raise ValueError(f"{self.name}: unknown idl {self.idl!r} "
                             "(protobuf|flatbuf)")
        unpack = _IDL[self.idl][1]

        def on_frame(raw: bytes) -> None:
            try:
                frame = unpack(raw)
            except Exception:  # noqa: BLE001 — malformed foreign message
                logger.warning("%s: undecodable %s message dropped",
                               self.name, self.idl)
                return
            with self._qcond:
                self._queue.append(frame)
                self._qcond.notify_all()

        self._ep = _Endpoint(self, self.server, self.host, self.port,
                             self.idl, on_frame=on_frame)
        self._caps_sent = False
        self._ep.open(receiving=True, timeout=float(self.timeout))
        super().start()

    def stop(self) -> None:
        if self._ep is not None:
            self._ep.close()
            self._ep = None
        with self._qcond:
            self._qcond.notify_all()
        super().stop()

    def create(self) -> Optional[Buffer]:
        # timeout<=0 = wait forever, matching the sink's blocking prop
        wait_s = float(self.timeout)
        deadline = (time.monotonic() + wait_s) if wait_s > 0 else None
        with self._qcond:
            while not self._queue:
                if self._stop_evt.is_set() or (
                        deadline is not None
                        and time.monotonic() > deadline):
                    if not self.silent and not self._stop_evt.is_set():
                        logger.warning("%s: no frame within timeout",
                                       self.name)
                    return None
                self._qcond.wait(timeout=0.1)
            frame = self._queue.pop(0)
        if not self._caps_sent:
            self.set_src_caps(_caps_for_frame(frame))
            self._caps_sent = True
        return Buffer([Chunk(a) for a in frame.arrays])
