"""tensor_src_grpc / tensor_sink_grpc — RPC tensor bridge with
protobuf or flatbuf IDL.

≙ ext/nnstreamer/tensor_source/tensor_src_grpc.c +
tensor_sink/tensor_sink_grpc.c over the C++ core in
ext/nnstreamer/extra/nnstreamer_grpc*.cc: the TensorService of
nnstreamer.proto / nnstreamer.fbs (client-streaming SendTensors,
server-streaming RecvTensors), with ``server``, ``host``/``port`` and
``idl=protobuf|flatbuf`` properties, and either element able to play
either role (4 topologies).

The grpc C++ stack is not a dependency here; the transport is the edge
framing (length-prefixed TCP) carrying ONE IDL-serialized ``Tensors``
message per frame — the same messages a gRPC stream would carry, so the
IDL layer (interop/tensor_codec.py) is shared and the payloads are
byte-identical to the reference schemas.
"""
from __future__ import annotations

import socket
import threading
import time
from typing import List, Optional

from ..interop import tensor_codec as tc
from ..edge.listener import TcpListener
from ..edge.protocol import MsgKind, recv_msg, send_msg
from ..pipeline.element import SinkElement, SrcElement
from ..pipeline.pad import Pad
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorInfo, TensorsConfig, TensorsInfo
from ..tensors.types import TensorType
from ..utils.log import logger

_IDL = {
    "protobuf": (tc.pack_protobuf, tc.unpack_protobuf),
    "flatbuf": (tc.pack_flatbuf, tc.unpack_flatbuf),
}


def _caps_for_frame(frame: tc.Frame) -> Caps:
    infos = TensorsInfo(
        TensorInfo(n or None, TensorType.from_dtype(a.dtype), a.shape)
        for n, a in zip(frame.names, frame.arrays))
    return Caps.from_config(TensorsConfig(
        infos, rate_n=frame.rate_n, rate_d=frame.rate_d))


class _Endpoint:
    """Shared client/server plumbing: either listen() and collect peer
    connections, or dial out to one peer."""

    def __init__(self, element, is_server: bool, host: str, port: int):
        self.element = element
        self.is_server = is_server
        self.host, self.port = host, int(port)
        self.listener: Optional[TcpListener] = None
        self.peers: List[socket.socket] = []
        self.peers_changed = threading.Condition()
        self.lock = threading.Lock()
        self.stop_evt = threading.Event()

    @property
    def bound_port(self) -> int:
        return self.listener.bound_port if self.listener else self.port

    def _add_peer(self, conn: socket.socket) -> None:
        with self.lock:
            self.peers.append(conn)
        with self.peers_changed:
            self.peers_changed.notify_all()

    def open(self, on_peer) -> None:
        self.stop_evt.clear()
        if self.is_server:
            def handle(conn):
                self._add_peer(conn)
                on_peer(conn)
            self.listener = TcpListener(
                self.host, self.port, handle, backlog=16,
                name=f"grpc-accept:{self.element.name}", spawn_thread=False)
            self.listener.start()
        else:
            conn = socket.create_connection((self.host, self.port),
                                            timeout=10.0)
            # the connect timeout must not linger as a per-op timeout:
            # an idle stream would be torn down after 10 s regardless of
            # the element's own 'timeout' property
            conn.settimeout(None)
            self._add_peer(conn)
            on_peer(conn)

    def close(self) -> None:
        self.stop_evt.set()
        if self.listener is not None:
            self.listener.stop()
            self.listener = None
        with self.lock:
            peers, self.peers = self.peers, []
        for p in peers:
            try:
                p.close()
            except OSError:
                pass
        with self.peers_changed:
            self.peers_changed.notify_all()

    def drop(self, conn: socket.socket) -> None:
        with self.lock:
            if conn in self.peers:
                self.peers.remove(conn)
        try:
            conn.close()
        except OSError:
            pass


@register_element("tensor_sink_grpc")
class GrpcSink(SinkElement):
    """Outbound: serializes each tensors frame to the IDL and streams it
    to the peer(s) — SendTensors when client, RecvTensors feed when
    server."""

    PROPS = {"host": "localhost", "port": 55115, "server": True,
             "blocking": True, "idl": "protobuf", "silent": True,
             "timeout": 10.0}  # seconds to wait for a peer; <=0 = forever

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._ep: Optional[_Endpoint] = None
        self._config: Optional[TensorsConfig] = None

    @property
    def bound_port(self) -> int:
        return self._ep.bound_port if self._ep else self.port

    def start(self) -> None:
        super().start()
        if self.idl not in _IDL:
            raise ValueError(f"{self.name}: unknown idl {self.idl!r} "
                             "(protobuf|flatbuf)")
        self._ep = _Endpoint(self, self.server, self.host, self.port)
        self._ep.open(lambda conn: None)  # sink peers just receive

    def stop(self) -> None:
        if self._ep is not None:
            self._ep.close()
            self._ep = None
        super().stop()

    def on_sink_caps(self, pad: Pad, caps: Caps) -> None:
        self._config = caps.to_config()

    def handle_event(self, pad, event) -> None:
        from ..pipeline.events import CapsEvent
        if isinstance(event, CapsEvent):
            pad.set_caps(event.caps)
            self.on_sink_caps(pad, event.caps)
            return
        super().handle_event(pad, event)

    def render(self, buf: Buffer) -> None:
        cfg = self._config
        names = ([i.name or "" for i in cfg.info]
                 if cfg and len(cfg.info) else None)
        frame = tc.Frame([c.host() for c in buf.chunks], names,
                         cfg.rate_n if cfg else 0,
                         cfg.rate_d if cfg else 1)
        payload = _IDL[self.idl][0](frame)
        ep = self._ep  # stop() nulls the attribute while we run
        if ep is None:
            return
        with ep.lock:
            peers = list(ep.peers)
        if not peers and self.blocking:
            # blocking mode (≙ the reference's 'blocking' sync stream):
            # wait for a consumer instead of dropping the frame; the
            # reference blocks indefinitely — timeout<=0 matches that
            wait_s = float(self.timeout)
            deadline = (time.monotonic() + wait_s) if wait_s > 0 else None
            with ep.peers_changed:
                while not ep.stop_evt.is_set():
                    with ep.lock:
                        peers = list(ep.peers)
                    if peers or (deadline is not None
                                 and time.monotonic() > deadline):
                        break
                    ep.peers_changed.wait(timeout=0.1)
        if not peers and not self.silent:
            logger.warning("%s: no connected peer, frame dropped", self.name)
        for conn in peers:
            try:
                send_msg(conn, MsgKind.DATA, {"idl": self.idl}, [payload])
            except (ConnectionError, OSError):
                ep.drop(conn)


@register_element("tensor_src_grpc")
class GrpcSrc(SrcElement):
    """Inbound: receives IDL-serialized tensors frames from the peer(s)
    — SendTensors service when server, RecvTensors consumer when
    client — and pushes them into the pipeline."""

    # (no 'blocking' knob here: the src is inherently pull-blocking via
    # 'timeout'; an ignored property would mislead, so it is omitted)
    PROPS = {"host": "localhost", "port": 55115, "server": True,
             "idl": "protobuf", "silent": True, "timeout": 10.0}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._ep: Optional[_Endpoint] = None
        self._queue: List[tc.Frame] = []
        self._qcond = threading.Condition()
        self._caps_sent = False

    @property
    def bound_port(self) -> int:
        return self._ep.bound_port if self._ep else self.port

    def negotiate_src_caps(self) -> Optional[Caps]:
        return None  # caps derive from the first received frame

    def start(self) -> None:
        if self.idl not in _IDL:
            raise ValueError(f"{self.name}: unknown idl {self.idl!r} "
                             "(protobuf|flatbuf)")
        self._ep = _Endpoint(self, self.server, self.host, self.port)
        self._caps_sent = False
        self._ep.open(self._spawn_recv)
        super().start()

    def _spawn_recv(self, conn: socket.socket) -> None:
        threading.Thread(target=self._recv_loop, args=(conn,), daemon=True,
                         name=f"grpc-recv:{self.name}").start()

    def _recv_loop(self, conn: socket.socket) -> None:
        unpack = _IDL[self.idl][1]
        ep = self._ep  # stop() nulls the attribute while we run
        try:
            while not ep.stop_evt.is_set():
                kind, meta, payloads = recv_msg(conn)
                if kind != MsgKind.DATA or not payloads:
                    break
                frame = unpack(payloads[0])
                with self._qcond:
                    self._queue.append(frame)
                    self._qcond.notify_all()
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            ep.drop(conn)

    def stop(self) -> None:
        if self._ep is not None:
            self._ep.close()
            self._ep = None
        with self._qcond:
            self._qcond.notify_all()
        super().stop()

    def create(self) -> Optional[Buffer]:
        # timeout<=0 = wait forever, matching the sink's blocking prop
        wait_s = float(self.timeout)
        deadline = (time.monotonic() + wait_s) if wait_s > 0 else None
        with self._qcond:
            while not self._queue:
                if self._stop_evt.is_set() or (
                        deadline is not None
                        and time.monotonic() > deadline):
                    if not self.silent and not self._stop_evt.is_set():
                        logger.warning("%s: no frame within timeout",
                                       self.name)
                    return None
                self._qcond.wait(timeout=0.1)
            frame = self._queue.pop(0)
        if not self._caps_sent:
            self.set_src_caps(_caps_for_frame(frame))
            self._caps_sent = True
        return Buffer([Chunk(a) for a in frame.arrays])
