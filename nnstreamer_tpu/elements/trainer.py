"""tensor_trainer — in-pipeline training.

≙ gst/nnstreamer/elements/gsttensor_trainer.c: receives other/tensors
samples, pushes them into a trainer subplugin (push_data blocks -> natural
backpressure), emits per-epoch [training_loss, training_accuracy,
validation_loss, validation_accuracy] as a float64 tensor stream, waits
for epoch completion at EOS, saves via model-save-path.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..pipeline.element import TransformElement
from ..pipeline.pad import Pad
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorsConfig, TensorsInfo
from ..trainers.base import (TrainerEvent, TrainerProperties, TrainerStatus,
                             find_trainer)
from ..utils.log import logger


@register_element("tensor_trainer")
class TensorTrainer(TransformElement):
    SINK_TEMPLATES = {"sink": "other/tensors"}
    SRC_TEMPLATES = {"src": "other/tensors"}
    RESTART_SAFE = False  # a restart would lose optimizer/step state
    CHECKPOINTABLE = ("completed-epoch counter + params (orbax) + "
                      "optimizer moments")
    PROPS = {
        "framework": "jax",
        "model-config": "",
        "model-save-path": "",
        "model-load-path": "",
        "num-training-samples": 0,
        "num-validation-samples": 0,
        "epochs": 1,
        "num-inputs": 1,
        "num-labels": 1,
        "mesh": "",   # "DxSxT"/"auto": shard the train step over a mesh
        "rules": "",  # param-sharding rule table (e.g. "gpt")
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.fw = None
        self._pushed = 0
        self._restore = None  # (state, snap_dir) stashed until start()

    def start(self) -> None:
        super().start()
        if self.fw is None:
            self.fw = find_trainer(self.framework)()
            self.fw.create(TrainerProperties(
                model_config=self.model_config,
                model_save_path=self.model_save_path,
                model_load_path=self.model_load_path,
                num_inputs=self.num_inputs,
                num_labels=self.num_labels,
                num_training_samples=self.num_training_samples,
                num_validation_samples=self.num_validation_samples,
                epochs=self.epochs,
                mesh=self.mesh,
                rules=self.rules))
            if self._restore is not None and hasattr(self.fw, "resume_from"):
                state, snap_dir = self._restore
                self.fw.resume_from(state, snap_dir)
                self._restore = None
            self.fw.set_event_notifier(self._on_trainer_event)
            self.fw.start()

    def stop(self) -> None:
        if self.fw is not None:
            self.fw.stop()
            self.fw = None
        super().stop()

    def on_sink_caps(self, pad: Pad, caps: Caps) -> None:
        cfg = caps.to_config()
        out = TensorsConfig(TensorsInfo.make("float64", "4"),
                            rate_n=cfg.rate_n, rate_d=cfg.rate_d)
        self.set_src_caps(Caps.from_config(out))

    def transform(self, buf: Buffer) -> Optional[Buffer]:
        needed = self.num_inputs + self.num_labels
        if len(buf.chunks) != needed:
            raise ValueError(
                f"{self.name}: sample has {len(buf.chunks)} tensors, "
                f"expected num-inputs+num-labels = {needed}")
        self.fw.push_data([c.host() for c in buf.chunks])
        self._pushed += 1
        return None  # results flow via _on_trainer_event

    def _on_trainer_event(self, event: TrainerEvent,
                          status: TrainerStatus) -> None:
        arr = np.array([status.training_loss, status.training_accuracy,
                        status.validation_loss, status.validation_accuracy],
                       np.float64)
        self.push(Buffer([Chunk(arr)], pts=status.epoch))
        self.post_message("trainer-epoch", epoch=status.epoch,
                          training_loss=status.training_loss,
                          training_accuracy=status.training_accuracy,
                          validation_loss=status.validation_loss,
                          validation_accuracy=status.validation_accuracy)
        if event == TrainerEvent.TRAINING_COMPLETION:
            logger.info("%s: training complete at epoch %d",
                        self.name, status.epoch)

    def on_eos(self) -> None:
        """Wait for the training thread before forwarding EOS
        (≙ wait_for_epoch_completion, gsttensor_trainer.c:590)."""
        if self.fw is not None and hasattr(self.fw, "end_of_data"):
            self.fw.end_of_data()  # stop waiting on the sample queue
        if self.fw is not None and hasattr(self.fw, "wait_training_complete"):
            self.fw.wait_training_complete(timeout=600.0)

    # -- checkpoint/restore (checkpoint/) ----------------------------------
    def preempt(self) -> None:
        """Preemption pauses training at the step boundary; a regular
        drain must keep FINISHING the remaining epochs (on_eos waits for
        completion), so the default drain-delegating hook is wrong
        here."""
        if self.fw is not None and hasattr(self.fw, "pause"):
            self.fw.pause()

    def snapshot_state(self, snap_dir):
        if self.fw is None:
            # snapshotting a restored-but-never-started pipeline:
            # preserve the stashed state (and its params files) rather
            # than dropping it
            if self._restore is not None:
                import os
                import shutil
                state, old_dir = self._restore
                if os.path.isdir(old_dir):
                    shutil.copytree(old_dir, snap_dir, dirs_exist_ok=True)
                return state
            return None
        if hasattr(self.fw, "snapshot"):
            return self.fw.snapshot(snap_dir)
        return None

    def restore_state(self, state, snap_dir):
        self._restore = (state, snap_dir)
