"""tensor_demux / tensor_split — 1-to-N stream splitters.

≙ gst/nnstreamer/elements/gsttensor_demux.c (split a multi-tensor stream
into per-pad streams, ``tensorpick`` selection/reordering) and
gsttensor_split.c (slice ONE tensor along a dim by ``tensorseg``).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..pipeline.element import Element
from ..pipeline.events import CapsEvent, Event
from ..pipeline.pad import Pad, PadDirection
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorInfo, TensorsConfig, TensorsInfo
from ..tensors.info import parse_dimension


@register_element("tensor_demux")
class TensorDemux(Element):
    """Per-src-pad tensor selection. ``tensorpick`` picks/reorders, e.g.
    "0,1:2,2" gives pad0 tensor 0, pad1 tensors 1+2, pad2 tensor 2;
    default: one pad per tensor."""

    SINK_TEMPLATES = {"sink": "other/tensors"}
    SRC_TEMPLATES = {"src_%u": "other/tensors"}
    PROPS = {"tensorpick": ""}

    def _picks(self, num_tensors: int) -> List[List[int]]:
        if self.tensorpick:
            return [[int(i) for i in grp.split(":")]
                    for grp in self.tensorpick.split(",")]
        return [[i] for i in range(num_tensors)]

    def _ensure_pads(self, n: int) -> List[Pad]:
        while len(self.src_pads) < n:
            self.request_pad(PadDirection.SRC)
        from .combiner import pad_sort_key
        return [p for _, p in sorted(self.src_pads.items(),
                                     key=lambda kv: pad_sort_key(kv[0]))]

    def on_sink_caps(self, pad: Pad, caps: Caps) -> None:
        cfg = caps.to_config()
        picks = self._picks(len(cfg.info))
        pads = self._ensure_pads(len(picks))
        for p, pick in zip(pads, picks):
            info = TensorsInfo(cfg.info[i].copy() for i in pick)
            out = TensorsConfig(info, cfg.format, cfg.rate_n, cfg.rate_d)
            if p.is_linked:
                self.set_src_caps(Caps.from_config(out), pad=p)

    def do_chain(self, pad: Pad, buf: Buffer) -> None:
        picks = self._picks(len(buf.chunks))
        pads = self._ensure_pads(len(picks))
        for p, pick in zip(pads, picks):
            if p.is_linked:
                p.push(buf.with_chunks([buf.chunks[i] for i in pick]))

    def static_transfer(self, in_caps):
        """Per-src-pad pick of the input tensors (pads map to picks by
        their name index; no pads are created)."""
        caps = in_caps.get("sink")
        cfg = caps.to_config() \
            if caps is not None and caps.is_fixed() else None
        if cfg is None or not len(cfg.info):
            return {p: None for p in self.src_pads}
        picks = self._picks(len(cfg.info))
        out = {}
        for pname in self.src_pads:
            _, _, idx = pname.rpartition("_")
            if not idx.isdigit() or int(idx) >= len(picks):
                out[pname] = None
                continue
            info = TensorsInfo(cfg.info[i].copy() for i in picks[int(idx)])
            out[pname] = Caps.from_config(TensorsConfig(
                info, cfg.format, cfg.rate_n, cfg.rate_d))
        return out


@register_element("tensor_split")
class TensorSplit(Element):
    """Slice one tensor into N along a dim. ``tensorseg`` gives per-pad
    slice sizes in reference dim-string form (e.g. "1:100:100,2:100:100"
    splits channels 1+2); ``tensorpick`` optionally reorders pads."""

    SINK_TEMPLATES = {"sink": "other/tensors"}
    SRC_TEMPLATES = {"src_%u": "other/tensors"}
    PROPS = {"tensorseg": "", "tensorpick": ""}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._segs: Optional[List[tuple]] = None
        self._axis: Optional[int] = None

    def _parse_segs(self, shape) -> None:
        if not self.tensorseg:
            raise ValueError(f"{self.name}: 'tensorseg' property is required")
        segs = [parse_dimension(s) for s in self.tensorseg.split(",")]
        ndim = len(shape)
        segs = [tuple([1] * (ndim - len(s)) + list(s)) if len(s) < ndim
                else s for s in segs]
        # find the split axis: the one where sizes differ/accumulate
        axis = None
        for d in range(ndim):
            if sum(s[d] for s in segs) == shape[d] and \
                    any(s[d] != shape[d] for s in segs):
                axis = d
                break
        if axis is None:
            # all dims equal across segs: split on outermost
            axis = 0
        if sum(s[axis] for s in segs) != shape[axis]:
            raise ValueError(
                f"{self.name}: tensorseg {self.tensorseg!r} does not tile "
                f"shape {shape}")
        self._segs, self._axis = segs, axis

    def _ensure_pads(self, n: int) -> List[Pad]:
        while len(self.src_pads) < n:
            self.request_pad(PadDirection.SRC)
        from .combiner import pad_sort_key
        return [p for _, p in sorted(self.src_pads.items(),
                                     key=lambda kv: pad_sort_key(kv[0]))]

    def on_sink_caps(self, pad: Pad, caps: Caps) -> None:
        cfg = caps.to_config()
        info = cfg.info[0]
        self._parse_segs(info.shape)
        pads = self._ensure_pads(len(self._segs))
        for p, seg in zip(pads, self._segs):
            shape = list(info.shape)
            shape[self._axis] = seg[self._axis]
            out = TensorsConfig(
                TensorsInfo([TensorInfo(info.name, info.type, tuple(shape))]),
                cfg.format, cfg.rate_n, cfg.rate_d)
            if p.is_linked:
                self.set_src_caps(Caps.from_config(out), pad=p)

    def static_transfer(self, in_caps):
        """Per-src-pad slice shapes from ``tensorseg`` (missing or
        non-tiling segs are provable errors)."""
        caps = in_caps.get("sink")
        cfg = caps.to_config() \
            if caps is not None and caps.is_fixed() else None
        if cfg is None or not len(cfg.info) or not cfg.info.is_valid():
            return {p: None for p in self.src_pads}
        info = cfg.info[0]
        self._parse_segs(info.shape)  # raises the runtime's ValueError
        out = {}
        for pname in self.src_pads:
            _, _, idx = pname.rpartition("_")
            if not idx.isdigit() or int(idx) >= len(self._segs):
                out[pname] = None
                continue
            shape = list(info.shape)
            shape[self._axis] = self._segs[int(idx)][self._axis]
            out[pname] = Caps.from_config(TensorsConfig(
                TensorsInfo([TensorInfo(info.name, info.type,
                                        tuple(shape))]),
                cfg.format, cfg.rate_n, cfg.rate_d))
        return out

    def do_chain(self, pad: Pad, buf: Buffer) -> None:
        arr = buf.chunks[0].host()
        if self._segs is None:
            self._parse_segs(arr.shape)
        pads = self._ensure_pads(len(self._segs))
        off = 0
        for p, seg in zip(pads, self._segs):
            size = seg[self._axis]
            sl = [slice(None)] * arr.ndim
            sl[self._axis] = slice(off, off + size)
            off += size
            if p.is_linked:
                p.push(buf.with_chunks(
                    [Chunk(np.ascontiguousarray(arr[tuple(sl)]))]))
