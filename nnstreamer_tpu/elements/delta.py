"""tensor_delta / tensor_delta_stitch — ROI-gated compute skip.

The wire half of the delta transport (edge/wire.py ``wire-codec=delta``)
stops re-shipping pixels that didn't change; this is the compute half:
stop re-*inferring* them.  ``tensor_delta`` compares each frame to the
previous one on a ``tile x tile`` grid and

- **mask** mode annotates the frame (``extras["delta_mask"]``) and
  passes it through — downstream ``tensor_if compared-value=CUSTOM
  compared-value-option=delta_changed`` gets frame-level gating for
  free (the custom condition is registered at import);
- **gate** mode drops unchanged frames outright (``transform() ->
  None``), so ``tensor_filter``/the serve batcher never see them;
- **roi** mode replaces the frame with the stack of *changed* tile
  crops — only those crops are admitted to inference, and
  ``tensor_delta_stitch`` downstream scatters the per-crop results
  back over a cached canvas so skipped regions reuse their last
  output.

The detector state is one reference frame; Segment/Flush events and a
caps/layout change reset it, and ``hold=N`` forces a full (keyframe)
frame every N frames so a downstream joining mid-stream converges.
Gating is lossy by construction — pipelint warns when a gated stream
feeds ``tensor_trainer`` (delta-lossy-gate-feeds-trainer).
"""
from __future__ import annotations

import math
from typing import Optional, Tuple

import numpy as np

from ..pipeline.element import TransformElement
from ..pipeline.events import FlushEvent, SegmentEvent
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer, Chunk
from .flowctl import register_if_condition

# frame-level custom condition for tensor_if: frames that never passed
# through tensor_delta count as changed (fail open, never drop blind)
register_if_condition(
    "delta_changed", lambda buf: bool(buf.extras.get("delta_changed", True)))


def _spatial(shape: Tuple[int, ...]) -> Tuple[int, int]:
    """First two dims are the spatial grid; 1-D tensors gate as (N, 1)."""
    if len(shape) == 1:
        return int(shape[0]), 1
    return int(shape[0]), int(shape[1])


def _collapse(arr: np.ndarray) -> np.ndarray:
    """(H, W, ...) -> (H, W) float32, trailing axes (channels) averaged
    out — change in any channel raises the tile's energy."""
    a = arr.astype(np.float32, copy=False).reshape(_spatial(arr.shape) + (-1,))
    return a.mean(axis=2)


def _tile_error_host(cur: np.ndarray, ref: np.ndarray,
                     tile: int) -> np.ndarray:
    """(gh, gw) mean-abs-diff per tile, zero-padding ragged edges (pads
    are identical in cur and ref so they contribute no energy)."""
    h, w = cur.shape
    gh, gw = math.ceil(h / tile), math.ceil(w / tile)
    d = np.zeros((gh * tile, gw * tile), np.float32)
    d[:h, :w] = np.abs(cur - ref)
    return d.reshape(gh, tile, gw, tile).mean(axis=(1, 3))


@register_element("tensor_delta")
class TensorDelta(TransformElement):
    SINK_TEMPLATES = {"sink": "other/tensors"}
    SRC_TEMPLATES = {"src": "other/tensors"}
    RESTART_SAFE = True  # worst case after restart: one extra keyframe
    PROPS = {
        "mode": "gate",     # mask | gate | roi
        "tile": 32,         # change-grid tile edge (pixels)
        "threshold": 0.0,   # mean-abs-diff above which a tile is "changed"
        "hold": 0,          # force a full frame every N frames (0 = never)
        "device": False,    # tile energies on device for device chunks
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        if str(self.mode) not in ("mask", "gate", "roi"):
            raise ValueError(f"tensor_delta: unknown mode {self.mode!r}")
        self._ref = None            # previous frame, collapsed (host or device)
        self._ref_key = None        # (shape, dtype) the reference was cut from
        self._since_full = 0        # frames since the last full frame
        self.stats.update({"delta_frames_skipped": 0, "delta_tiles_total": 0,
                           "delta_tiles_skipped": 0, "delta_keyframes": 0})

    def handle_event(self, pad, event) -> None:
        if isinstance(event, (SegmentEvent, FlushEvent)):
            self._ref = None  # racecheck: ok(events and chain are serialized per element)
            self._ref_key = None
            self._since_full = 0
        super().handle_event(pad, event)

    # -- detection ---------------------------------------------------

    def _energy(self, c: Chunk) -> Optional[np.ndarray]:
        """(gh, gw) tile energies vs the reference, or None when this
        frame must go out full (first frame / layout change / hold)."""
        tile = max(1, int(self.tile))
        key = (tuple(c.shape), str(c.dtype))
        hold = int(self.hold)
        if (self._ref is None or key != self._ref_key
                or (hold > 0 and self._since_full + 1 >= hold)):
            self._ref = None
            self._ref_key = key
            return None
        h, w = _spatial(c.shape)
        if (bool(self.device) and c.is_device
                and h % tile == 0 and w % tile == 0):
            import jax
            import jax.numpy as jnp

            from ..ops.delta import tile_error

            cur = jnp.mean(c.raw.astype(jnp.float32).reshape(h, w, -1),
                           axis=2)
            err = np.asarray(jax.device_get(
                tile_error(cur, self._ref, tile)))
            self._ref = cur
            return err
        cur = _collapse(c.host())
        err = _tile_error_host(cur, np.asarray(self._ref), tile)
        self._ref = cur
        return err

    def _remember(self, c: Chunk) -> None:
        """Seed the reference from a frame that went out full."""
        tile = max(1, int(self.tile))
        h, w = _spatial(c.shape)
        if (bool(self.device) and c.is_device
                and h % tile == 0 and w % tile == 0):
            import jax.numpy as jnp
            self._ref = jnp.mean(
                c.raw.astype(jnp.float32).reshape(h, w, -1), axis=2)
        else:
            self._ref = _collapse(c.host())
        self._since_full = 0

    # -- transform ---------------------------------------------------

    def transform(self, buf: Buffer) -> Optional[Buffer]:
        c = buf.chunks[0]
        err = self._energy(c)
        if err is None:  # full frame (keyframe-equivalent)
            self._remember(c)
            self.stats.inc("delta_keyframes")
            out = buf.with_chunks(buf.chunks)
            out.extras["delta_changed"] = True
            out.extras["delta_full"] = 1
            return out
        self._since_full += 1
        changed = err > float(self.threshold)
        gh, gw = changed.shape
        n_changed = int(changed.sum())
        self.stats.add(delta_tiles_total=gh * gw,
                       delta_tiles_skipped=gh * gw - n_changed)
        mode = str(self.mode)
        if mode == "mask":
            out = buf.with_chunks(buf.chunks)
            out.extras["delta_changed"] = n_changed > 0
            out.extras["delta_mask"] = changed
            out.extras["delta_grid"] = (gh, gw)
            return out
        if n_changed == 0:  # gate/roi: nothing moved, skip the frame
            self.stats.inc("delta_frames_skipped")
            return None
        if mode == "gate":
            out = buf.with_chunks(buf.chunks)
            out.extras["delta_changed"] = True
            out.extras["delta_mask"] = changed
            out.extras["delta_grid"] = (gh, gw)
            return out
        # roi: ship only the changed tile crops, zero-padded at ragged
        # edges so the stack is rectangular: (n, tile, tile, C)
        tile = max(1, int(self.tile))
        arr = c.host()
        h, w = _spatial(arr.shape)
        a3 = arr.reshape(h, w, -1)
        ch = a3.shape[2]
        rois = [(int(i), int(j)) for i, j in zip(*np.nonzero(changed))]
        crops = np.zeros((len(rois), tile, tile, ch), arr.dtype)
        for k, (i, j) in enumerate(rois):
            part = a3[i * tile:(i + 1) * tile, j * tile:(j + 1) * tile, :]
            crops[k, :part.shape[0], :part.shape[1], :] = part
        out = buf.with_chunks([Chunk(crops)])
        out.extras["delta_changed"] = True
        out.extras["delta_rois"] = rois
        out.extras["delta_grid"] = (gh, gw)
        out.extras["delta_tile"] = tile
        out.extras["delta_shape"] = tuple(arr.shape)
        return out


@register_element("tensor_delta_stitch")
class TensorDeltaStitch(TransformElement):
    """Decoder-side result reuse for ``tensor_delta mode=roi``: full
    frames refresh a cached canvas; ROI frames scatter the per-crop
    results back over it, so regions the gate skipped keep their last
    output.  Handles models that rescale the crop (e.g. a segmentation
    head emitting ``tile/2``-sized maps): the output tile edge is read
    from the crop stack and the canvas scales with it."""

    SINK_TEMPLATES = {"sink": "other/tensors"}
    SRC_TEMPLATES = {"src": "other/tensors"}
    RESTART_SAFE = True  # canvas rebuilds at the next full frame
    PROPS = {}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._canvas: Optional[np.ndarray] = None
        self.stats.update({"delta_stitched": 0, "delta_stitch_dropped": 0})

    def handle_event(self, pad, event) -> None:
        if isinstance(event, (SegmentEvent, FlushEvent)):
            self._canvas = None  # racecheck: ok(events and chain are serialized per element)
        super().handle_event(pad, event)

    def transform(self, buf: Buffer) -> Optional[Buffer]:
        rois = buf.extras.get("delta_rois")
        if rois is None:  # full frame: refresh the canvas, pass through
            self._canvas = buf.chunks[0].host().copy()
            return buf
        crops = buf.chunks[0].host()
        gh, gw = buf.extras["delta_grid"]
        h, w = _spatial(buf.extras["delta_shape"])
        in_tile = int(buf.extras.get("delta_tile") or math.ceil(h / gh))
        out_tile = int(crops.shape[1])
        scale = out_tile / in_tile
        oh, ow = max(1, round(h * scale)), max(1, round(w * scale))
        ch = int(np.prod(crops.shape[3:], dtype=np.int64)) if crops.ndim > 3 \
            else 1
        c3 = crops.reshape(len(rois), out_tile, out_tile, ch)
        if self._canvas is None or self._canvas.shape != (oh, ow, ch) \
                or self._canvas.dtype != crops.dtype:
            if self._canvas is not None:
                self.stats.inc("delta_stitch_dropped")
            self._canvas = np.zeros((oh, ow, ch), crops.dtype)
        for k, (i, j) in enumerate(rois):
            y, x = i * out_tile, j * out_tile
            ph, pw = min(out_tile, oh - y), min(out_tile, ow - x)
            if ph <= 0 or pw <= 0:
                continue
            self._canvas[y:y + ph, x:x + pw, :] = c3[k, :ph, :pw, :]
        self.stats.inc("delta_stitched")
        shape = (oh, ow) + tuple(crops.shape[3:]) if crops.ndim > 3 \
            else (oh, ow)
        out = buf.with_chunks([Chunk(self._canvas.copy().reshape(shape))])
        out.extras.pop("delta_rois", None)
        return out
