"""tensor_if / tensor_rate — data-dependent flow control & QoS.

≙ gst/nnstreamer/elements/gsttensor_if.c (condition on tensor values,
then/else actions, custom C callback via include/tensor_if.h) and
gsttensor_rate.c (framerate control + throttling).
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

import numpy as np

from ..pipeline.element import Element, TransformElement
from ..pipeline.events import EosEvent, QosEvent
from ..pipeline.pad import Pad, PadDirection
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer
from ..tensors.caps import Caps
from ..tensors.info import TensorsConfig, TensorsInfo

# runtime-registered custom conditions (≙ nnstreamer_if_custom_register)
_custom_conditions: Dict[str, Callable[[Buffer], bool]] = {}
_cc_lock = threading.Lock()


def register_if_condition(name: str, fn: Callable[[Buffer], bool]) -> None:
    with _cc_lock:
        _custom_conditions[name] = fn


def unregister_if_condition(name: str) -> None:
    with _cc_lock:
        _custom_conditions.pop(name, None)


_OPERATORS = {
    "EQ": lambda v, sv: v == sv[0],
    "NE": lambda v, sv: v != sv[0],
    "GT": lambda v, sv: v > sv[0],
    "GE": lambda v, sv: v >= sv[0],
    "LT": lambda v, sv: v < sv[0],
    "LE": lambda v, sv: v <= sv[0],
    "RANGE_INCLUSIVE": lambda v, sv: sv[0] <= v <= sv[1],
    "RANGE_EXCLUSIVE": lambda v, sv: sv[0] < v < sv[1],
    "NOT_IN_RANGE_INCLUSIVE": lambda v, sv: not (sv[0] <= v <= sv[1]),
    "NOT_IN_RANGE_EXCLUSIVE": lambda v, sv: not (sv[0] < v < sv[1]),
}


@register_element("tensor_if")
class TensorIf(Element):
    """Condition-gated routing: ``then`` branch on src_0, ``else`` branch
    on src_1 (each action PASSTHROUGH | SKIP | TENSORPICK)."""

    SINK_TEMPLATES = {"sink": "other/tensors"}
    SRC_TEMPLATES = {"src_%u": "other/tensors"}
    PROPS = {
        "compared-value": "A_VALUE",        # A_VALUE | TENSOR_AVERAGE_VALUE | CUSTOM
        "compared-value-option": "",        # "d0:d1:d2:d3,n" | "n" | custom name
        "operator": "EQ",
        "supplied-value": "",               # "v" or "v1:v2" for ranges
        "then": "PASSTHROUGH",
        "then-option": "",
        "else": "SKIP",
        "else-option": "",
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._then_pad: Optional[Pad] = None
        self._else_pad: Optional[Pad] = None

    def _pads(self):
        if self._then_pad is None:
            self._then_pad = self.get_static_or_request_pad(
                "src_0", PadDirection.SRC)
            self._else_pad = self.get_static_or_request_pad(
                "src_1", PadDirection.SRC)
        return self._then_pad, self._else_pad

    # -- negotiation ------------------------------------------------------
    def on_sink_caps(self, pad: Pad, caps: Caps) -> None:
        cfg = caps.to_config()
        then_pad, else_pad = self._pads()
        for p, action, option in ((then_pad, self.get_property("then"),
                                   self.then_option),
                                  (else_pad, self.get_property("else"),
                                   self.else_option)):
            if not p.is_linked or action == "SKIP":
                continue
            out = cfg
            if action == "TENSORPICK" and option:
                picks = [int(i) for i in option.split(",")]
                out = TensorsConfig(
                    TensorsInfo(cfg.info[i].copy() for i in picks),
                    cfg.format, cfg.rate_n, cfg.rate_d)
            self.set_src_caps(Caps.from_config(out), pad=p)

    def static_transfer(self, in_caps):
        """Per-branch config: passthrough, or the TENSORPICK selection;
        SKIP branches carry nothing."""
        caps = in_caps.get("sink")
        cfg = caps.to_config() \
            if caps is not None and caps.is_fixed() else None
        out: dict = {}
        for pname, action, option in (
                ("src_0", self.get_property("then"), self.then_option),
                ("src_1", self.get_property("else"), self.else_option)):
            if pname not in self.src_pads:
                continue
            if cfg is None or action == "SKIP":
                out[pname] = None
                continue
            sel = cfg
            if action == "TENSORPICK" and option:
                picks = [int(i) for i in option.split(",")]
                sel = TensorsConfig(
                    TensorsInfo(cfg.info[i].copy() for i in picks),
                    cfg.format, cfg.rate_n, cfg.rate_d)
            out[pname] = Caps.from_config(sel)
        for pname in self.src_pads:
            out.setdefault(pname, None)
        return out

    # -- condition --------------------------------------------------------
    def _compared_value(self, buf: Buffer) -> float:
        cv = self.compared_value
        opt = self.compared_value_option
        if cv == "A_VALUE":
            # "d0:d1:...,n" — innermost-first element index + tensor id
            idx_str, _, tid_str = opt.partition(",")
            tid = int(tid_str or 0)
            arr = buf.chunks[tid].host()
            ref_idx = [int(i) for i in idx_str.split(":")] if idx_str else []
            ref_idx += [0] * (arr.ndim - len(ref_idx))
            np_idx = tuple(reversed(ref_idx[:arr.ndim]))
            return float(arr[np_idx])
        if cv == "TENSOR_AVERAGE_VALUE":
            tid = int(opt or 0)
            return float(np.mean(buf.chunks[tid].host()))
        raise ValueError(f"{self.name}: unknown compared-value {cv!r}")

    def _evaluate(self, buf: Buffer) -> bool:
        if self.compared_value == "CUSTOM":
            with _cc_lock:
                fn = _custom_conditions.get(self.compared_value_option)
            if fn is None:
                raise ValueError(
                    f"{self.name}: no custom condition "
                    f"{self.compared_value_option!r} registered")
            return bool(fn(buf))
        v = self._compared_value(buf)
        sv = [float(x) for x in self.supplied_value.split(":") if x != ""]
        op = _OPERATORS.get(self.operator.upper())
        if op is None:
            raise ValueError(f"{self.name}: unknown operator {self.operator!r}")
        return op(v, sv)

    # -- dataflow ---------------------------------------------------------
    def do_chain(self, pad: Pad, buf: Buffer) -> None:
        result = self._evaluate(buf)
        then_pad, else_pad = self._pads()
        action = self.get_property("then") if result else self.get_property("else")
        option = self.then_option if result else self.else_option
        out_pad = then_pad if result else else_pad
        if action == "SKIP" or not out_pad.is_linked:
            return
        if action == "TENSORPICK" and option:
            picks = [int(i) for i in option.split(",")]
            buf = buf.with_chunks([buf.chunks[i] for i in picks])
        out_pad.push(buf)


@register_element("tensor_rate")
class TensorRate(TransformElement):
    """PTS-based framerate conversion: drop early frames, duplicate the
    previous frame to fill gaps; throttling QoS counters exposed as
    properties (≙ gsttensor_rate.c in/out/dup/drop)."""

    PROPS = {"framerate": "", "throttle": True, "silent": True}
    RESTART_SAFE = False  # restart loses the PTS schedule mid-stream

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._next_ts: Optional[int] = None
        self._prev: Optional[Buffer] = None
        self._throttling = False
        self._last_in_pts: Optional[int] = None
        self.stats.update({"in": 0, "out": 0, "dup": 0, "drop": 0})

    def _target(self):
        if not self.framerate:
            return None
        n, _, d = self.framerate.partition("/")
        return int(n), int(d or 1)

    # -- checkpoint/restore (checkpoint/) ---------------------------------
    CHECKPOINTABLE = "the PTS schedule (next emit slot + gap-fill frame)"

    def snapshot_state(self, snap_dir):
        if self._next_ts is None and self._prev is None:
            return None
        from ..checkpoint.state import dump_buffer
        return {"next_ts": self._next_ts,
                "last_in_pts": self._last_in_pts,
                "throttling": self._throttling,
                "prev": dump_buffer(self._prev)
                if self._prev is not None else None}

    def restore_state(self, state, snap_dir):
        from ..checkpoint.state import load_buffer
        self._next_ts = state["next_ts"]  # racecheck: ok(restore runs before start(): no chain thread exists yet)
        self._last_in_pts = state["last_in_pts"]  # racecheck: ok(restore runs before start())
        self._throttling = bool(state["throttling"])  # racecheck: ok(restore runs before start())
        self._prev = (load_buffer(state["prev"])  # racecheck: ok(restore runs before start())
                      if state.get("prev") is not None else None)

    def handle_event(self, pad, event) -> None:
        from ..pipeline.events import FlushEvent, SegmentEvent
        if isinstance(event, (SegmentEvent, FlushEvent)):
            # PTS discontinuity: mirror tensor_filter's reset — stale
            # _next_ts would drop every post-restart frame and a stuck
            # _throttling flag would suppress all future QoS events
            self._next_ts = None
            self._prev = None
            self._last_in_pts = None
            self._throttling = False
        super().handle_event(pad, event)

    def transform_caps(self, incaps: Caps) -> Optional[Caps]:
        tgt = self._target()
        if tgt is None:
            return incaps
        cfg = incaps.to_config()
        cfg = TensorsConfig(cfg.info, cfg.format, tgt[0], tgt[1])
        return Caps.from_config(cfg)

    def transform(self, buf: Buffer) -> Optional[Buffer]:
        tgt = self._target()
        self.stats.inc("in")
        if tgt is None or buf.pts is None:
            self.stats.inc("out")
            return buf
        period = int(1e9 * tgt[1] / tgt[0])
        if self._next_ts is None:
            self._next_ts = buf.pts
        in_delta = (buf.pts - self._last_in_pts
                    if self._last_in_pts is not None else None)
        self._last_in_pts = buf.pts
        if buf.pts < self._next_ts:
            self.stats.inc("drop")
            self._prev = buf
            if self.throttle and not self._throttling:
                # upstream is overproducing: ask producers (tensor_filter
                # consumes this) to space frames at our target period so
                # the dropped frames are never computed (≙ the QoS events
                # gsttensor_rate.c emits when throttle=true). Proportion =
                # target period / observed inter-arrival spacing (> 1 when
                # frames arrive faster than we can emit them); one event
                # per throttle episode, not per drop.
                self._throttling = True
                prop = (period / in_delta) if in_delta and in_delta > 0 else 2.0
                self.send_upstream_event(QosEvent(
                    proportion=max(prop, 1.01),
                    period_ns=period, timestamp=buf.pts))
            return None
        if self._throttling and self.throttle:
            # back under budget: clear the throttle
            self._throttling = False
            self.send_upstream_event(QosEvent(proportion=1.0, period_ns=0,
                                              timestamp=buf.pts))
        # duplicate previous frame into any gap
        while self._prev is not None and buf.pts >= self._next_ts + period:
            dup = self._prev.with_chunks(self._prev.chunks)
            dup.pts, dup.duration = self._next_ts, period
            self.stats.add(dup=1, out=1)
            self.push(dup)
            self._next_ts += period
        out = buf.with_chunks(buf.chunks)
        out.pts, out.duration = self._next_ts, period
        self._next_ts += period
        self._prev = buf
        self.stats.inc("out")
        return out
