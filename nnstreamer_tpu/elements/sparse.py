"""tensor_sparse_enc / tensor_sparse_dec — static<->sparse codec.

≙ gst/nnstreamer/elements/gsttensor_sparse{_enc,_dec,_util}.c: non-zero
elements encoded as (index, value) pairs behind a self-describing
TensorMetaInfo header (GstSparseTensorInfo.nnz, tensor_typedef.h:294-297).

Wire layout per chunk: 128-byte meta header | uint32 indices[nnz] |
values[nnz] (element dtype).
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np

from ..pipeline.element import TransformElement
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorsConfig, TensorsInfo
from ..tensors.meta import HEADER_SIZE, TensorMetaInfo
from ..tensors.types import TensorFormat, TensorType


def sparse_encode(arr: np.ndarray, ref: Optional[np.ndarray] = None) -> bytes:
    """Dense -> sparse wire bytes. Absolute mode (``ref=None``) encodes
    the non-zero elements; diff mode encodes the elements that differ
    from ``ref`` — compared bitwise, so NaN payloads and -0.0/+0.0 flips
    survive the round trip exactly. Decode diff-mode bytes with the same
    ``ref`` (the wire layout is identical; whose baseline the indices
    patch is the caller's contract — the delta wire codec keys it to the
    link's reference epoch)."""
    flat = np.ascontiguousarray(arr).reshape(-1)
    if ref is None:
        idx = np.flatnonzero(flat).astype(np.uint32)
    else:
        rflat = np.ascontiguousarray(ref).reshape(-1)
        if rflat.shape != flat.shape or rflat.dtype != flat.dtype:
            raise ValueError(
                f"sparse diff reference mismatch: {flat.dtype}{flat.shape} "
                f"vs {rflat.dtype}{rflat.shape}")
        itemsize = flat.dtype.itemsize
        if itemsize == 1:
            changed = flat.view(np.uint8) != rflat.view(np.uint8)
        else:
            changed = (flat.view(np.uint8).reshape(-1, itemsize) !=
                       rflat.view(np.uint8).reshape(-1, itemsize)).any(axis=1)
        idx = np.flatnonzero(changed).astype(np.uint32)
    vals = flat[idx]
    meta = TensorMetaInfo(
        type=TensorType.from_dtype(arr.dtype), format=TensorFormat.SPARSE,
        shape=tuple(arr.shape), nnz=len(idx))
    return meta.pack() + idx.tobytes() + vals.tobytes()


def _parse_sparse(data: bytes):
    """Wire -> (meta, uint32 indices, values); single source of truth
    for the layout, shared by the host and device decode paths."""
    meta = TensorMetaInfo.unpack(data[:HEADER_SIZE])
    if meta.format != TensorFormat.SPARSE:
        raise ValueError("chunk is not sparse-encoded")
    nnz = meta.nnz
    off = HEADER_SIZE
    idx = np.frombuffer(data[off:off + 4 * nnz], np.uint32)
    off += 4 * nnz
    dt = np.dtype(meta.type.np_dtype)
    vals = np.frombuffer(data[off:off + nnz * dt.itemsize], dt)
    return meta, idx, vals


def sparse_decode(data: bytes, ref: Optional[np.ndarray] = None) -> np.ndarray:
    """Inverse of :func:`sparse_encode`. With ``ref`` the output starts
    from a copy of the reference (diff mode) instead of zeros; the
    returned array never aliases ``ref``."""
    meta, idx, vals = _parse_sparse(data)
    size = math.prod(meta.shape)
    if ref is None:
        out = np.zeros(size, vals.dtype)
    else:
        rflat = np.ascontiguousarray(ref).reshape(-1)
        if rflat.size != size or rflat.dtype != vals.dtype:
            raise ValueError(
                f"sparse diff reference mismatch: {vals.dtype}[{size}] "
                f"vs {rflat.dtype}[{rflat.size}]")
        out = rflat.copy()
    out[idx] = vals
    return out.reshape(meta.shape)


@register_element("tensor_sparse_enc")
class TensorSparseEnc(TransformElement):
    SINK_TEMPLATES = {"sink": "other/tensors"}
    SRC_TEMPLATES = {"src": "other/tensors"}
    # density < 1.0 turns on the DEVICE pack path for device-resident
    # chunks: non-zeros are packed in HBM (ops/sparse.py) and only
    # ceil(size*density) (index, value) pairs cross the host link,
    # not the dense tensor. If a frame's nnz overflows the capacity it
    # falls back to the host path — never truncates.
    PROPS = {"density": 1.0}

    def transform_caps(self, incaps: Caps) -> Optional[Caps]:
        cfg = incaps.to_config()
        return Caps.from_config(TensorsConfig(
            TensorsInfo(), TensorFormat.SPARSE, cfg.rate_n, cfg.rate_d))

    def _encode_device(self, c: Chunk) -> Optional[bytes]:
        """Pack on device; None -> caller falls back to the host path."""
        import jax

        from ..ops.sparse import pack

        dev = c.raw
        size = int(np.prod(c.shape))
        capacity = max(1, min(size, math.ceil(size * float(self.density))))
        idx, vals, nnz = pack(dev.reshape(-1), capacity)
        idx, vals, nnz = jax.device_get([idx, vals, nnz])
        nnz = int(nnz)
        if nnz > capacity:
            return None  # denser than promised: host path has no limit
        meta = TensorMetaInfo(
            type=TensorType.from_dtype(c.dtype), format=TensorFormat.SPARSE,
            shape=tuple(c.shape), nnz=nnz)
        return meta.pack() + idx[:nnz].tobytes() + vals[:nnz].tobytes()

    def transform(self, buf: Buffer) -> Optional[Buffer]:
        chunks = []
        for c in buf.chunks:
            wire = None
            if float(self.density) < 1.0 and c.is_device:
                wire = self._encode_device(c)
            if wire is None:
                wire = sparse_encode(c.host())
            data = np.frombuffer(wire, np.uint8)
            meta = TensorMetaInfo.unpack(data[:HEADER_SIZE].tobytes())
            chunks.append(Chunk(data, meta=meta))
        return buf.with_chunks(chunks)


@register_element("tensor_sparse_dec")
class TensorSparseDec(TransformElement):
    SINK_TEMPLATES = {"sink": "other/tensors"}
    SRC_TEMPLATES = {"src": "other/tensors"}
    # device=true scatters (idx, vals) to a dense tensor IN HBM
    # (ops/sparse.py unpack): the small pair is what crosses H2D, and a
    # downstream tensor_filter finds its input already device-resident.
    PROPS = {"device": False}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._out_cfg: Optional[TensorsConfig] = None

    def _decode_device(self, data: bytes) -> Chunk:
        import jax

        from ..ops.sparse import unpack

        meta, idx, vals = _parse_sparse(data)
        size = math.prod(meta.shape)
        # pad to a power-of-two bucket: per-frame nnz varies, and a raw
        # nnz-shaped input would recompile the jitted scatter every
        # frame; pads are (idx 0, val 0), which unpack masks out
        cap = 1
        while cap < max(len(vals), 1):
            cap *= 2
        cap = min(cap, max(size, 1))
        pad = cap - len(vals)
        if pad > 0:
            idx = np.concatenate([idx, np.zeros(pad, np.uint32)])
            vals = np.concatenate([vals, np.zeros(pad, vals.dtype)])
        dense = unpack(jax.device_put(idx), jax.device_put(vals), size)
        return Chunk(dense.reshape(meta.shape))

    def transform_caps(self, incaps: Caps) -> Optional[Caps]:
        cfg = incaps.to_config()
        # dims are locked from the first decoded buffer (sparse streams are
        # self-describing); until then advertise flexible
        self._rate = (cfg.rate_n, cfg.rate_d)
        return Caps.from_config(TensorsConfig(
            TensorsInfo(), TensorFormat.FLEXIBLE, cfg.rate_n, cfg.rate_d))

    def transform(self, buf: Buffer) -> Optional[Buffer]:
        if self.device:
            chunks = [self._decode_device(c.host().tobytes())
                      for c in buf.chunks]
        else:
            chunks = [Chunk(sparse_decode(c.host().tobytes()))
                      for c in buf.chunks]
        out = buf.with_chunks(chunks)
        if self._out_cfg is None:
            self._out_cfg = TensorsConfig(out.to_infos(), TensorFormat.STATIC,
                                          *self._rate)
            self.set_src_caps(Caps.from_config(self._out_cfg))
        return out
