"""tensor_sparse_enc / tensor_sparse_dec — static<->sparse codec.

≙ gst/nnstreamer/elements/gsttensor_sparse{_enc,_dec,_util}.c: non-zero
elements encoded as (index, value) pairs behind a self-describing
TensorMetaInfo header (GstSparseTensorInfo.nnz, tensor_typedef.h:294-297).

Wire layout per chunk: 128-byte meta header | uint32 indices[nnz] |
values[nnz] (element dtype).
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np

from ..pipeline.element import TransformElement
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorsConfig, TensorsInfo
from ..tensors.meta import HEADER_SIZE, TensorMetaInfo
from ..tensors.types import TensorFormat, TensorType


def sparse_encode(arr: np.ndarray) -> bytes:
    flat = arr.reshape(-1)
    idx = np.flatnonzero(flat).astype(np.uint32)
    vals = flat[idx]
    meta = TensorMetaInfo(
        type=TensorType.from_dtype(arr.dtype), format=TensorFormat.SPARSE,
        shape=tuple(arr.shape), nnz=len(idx))
    return meta.pack() + idx.tobytes() + vals.tobytes()


def sparse_decode(data: bytes) -> np.ndarray:
    meta = TensorMetaInfo.unpack(data[:HEADER_SIZE])
    if meta.format != TensorFormat.SPARSE:
        raise ValueError("chunk is not sparse-encoded")
    nnz = meta.nnz
    off = HEADER_SIZE
    idx = np.frombuffer(data[off:off + 4 * nnz], np.uint32)
    off += 4 * nnz
    dt = meta.type.np_dtype
    vals = np.frombuffer(
        data[off:off + nnz * np.dtype(dt).itemsize], dt)
    out = np.zeros(math.prod(meta.shape), dt)
    out[idx] = vals
    return out.reshape(meta.shape)


@register_element("tensor_sparse_enc")
class TensorSparseEnc(TransformElement):
    SINK_TEMPLATES = {"sink": "other/tensors"}
    SRC_TEMPLATES = {"src": "other/tensors"}

    def transform_caps(self, incaps: Caps) -> Optional[Caps]:
        cfg = incaps.to_config()
        return Caps.from_config(TensorsConfig(
            TensorsInfo(), TensorFormat.SPARSE, cfg.rate_n, cfg.rate_d))

    def transform(self, buf: Buffer) -> Optional[Buffer]:
        chunks = []
        for c in buf.chunks:
            data = np.frombuffer(sparse_encode(c.host()), np.uint8)
            meta = TensorMetaInfo.unpack(data[:HEADER_SIZE].tobytes())
            chunks.append(Chunk(data, meta=meta))
        return buf.with_chunks(chunks)


@register_element("tensor_sparse_dec")
class TensorSparseDec(TransformElement):
    SINK_TEMPLATES = {"sink": "other/tensors"}
    SRC_TEMPLATES = {"src": "other/tensors"}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._out_cfg: Optional[TensorsConfig] = None

    def transform_caps(self, incaps: Caps) -> Optional[Caps]:
        cfg = incaps.to_config()
        # dims are locked from the first decoded buffer (sparse streams are
        # self-describing); until then advertise flexible
        self._rate = (cfg.rate_n, cfg.rate_d)
        return Caps.from_config(TensorsConfig(
            TensorsInfo(), TensorFormat.FLEXIBLE, cfg.rate_n, cfg.rate_d))

    def transform(self, buf: Buffer) -> Optional[Buffer]:
        chunks = [Chunk(sparse_decode(c.host().tobytes())) for c in buf.chunks]
        out = buf.with_chunks(chunks)
        if self._out_cfg is None:
            self._out_cfg = TensorsConfig(out.to_infos(), TensorFormat.STATIC,
                                          *self._rate)
            self.set_src_caps(Caps.from_config(self._out_cfg))
        return out
