"""Media-side elements: videotestsrc/audiotestsrc analogs and file IO.

The reference's pipelines are fed by GStreamer core elements
(videotestsrc, filesrc, multifilesink — e.g. tests/nnstreamer_converter/
runTest.sh uses videotestsrc ! tensor_converter; golden tests diff
multifilesink dumps). These are their tensor-framework counterparts: media
buffers are single-chunk host ndarrays whose caps use media mimetypes
(video/x-raw, audio/x-raw, text/x-raw, application/octet-stream).
"""
from __future__ import annotations

import glob
import os
from typing import List, Optional

import numpy as np

from ..pipeline.element import SinkElement, SrcElement, TransformElement
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps

_VIDEO_CHANNELS = {"RGB": 3, "BGR": 3, "RGBA": 4, "BGRx": 4, "GRAY8": 1}


def video_frame_shape(caps: Caps):
    s = caps.structures[0]
    fmt = str(s.fields.get("format", "RGB"))
    h, w = int(s.fields["height"]), int(s.fields["width"])
    c = _VIDEO_CHANNELS.get(fmt)
    if c is None:
        raise ValueError(f"unsupported video format {fmt!r}")
    return (h, w, c), fmt


@register_element("videotestsrc")
class VideoTestSrc(SrcElement):
    """Synthetic video frames (≙ videotestsrc). Patterns: smpte (color
    bars), ball (moving dot), counter, random."""

    PROPS = {"caps": "video/x-raw,format=RGB,width=640,height=480,"
                     "framerate=30/1",
             "pattern": "smpte", "is-live": False, "seed": 0}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._shape = None
        self._count = 0
        self._dur = None
        self._rng = None

    def negotiate_src_caps(self) -> Optional[Caps]:
        caps = Caps(self.caps).fixate()
        self._shape, _ = video_frame_shape(caps)
        cfg_rate = caps.structures[0].fields.get("framerate")
        if cfg_rate is not None and getattr(cfg_rate, "numerator", 0):
            self._dur = int(1e9 * cfg_rate.denominator / cfg_rate.numerator)
        return caps

    def create(self) -> Optional[Buffer]:
        h, w, c = self._shape
        if self._rng is None:
            self._rng = np.random.default_rng(self.seed)
        if self.pattern == "random":
            frame = self._rng.integers(0, 256, self._shape, np.uint8)
        elif self.pattern == "ball":
            frame = np.zeros(self._shape, np.uint8)
            cy = int((np.sin(self._count / 10.0) * 0.4 + 0.5) * h)
            cx = int((np.cos(self._count / 10.0) * 0.4 + 0.5) * w)
            frame[max(0, cy - 5):cy + 5, max(0, cx - 5):cx + 5] = 255
        elif self.pattern == "counter":
            frame = np.full(self._shape, self._count % 256, np.uint8)
        else:  # smpte-ish vertical bars
            bars = np.array([[255, 255, 255], [255, 255, 0], [0, 255, 255],
                             [0, 255, 0], [255, 0, 255], [255, 0, 0],
                             [0, 0, 255]], np.uint8)
            cols = bars[(np.arange(w) * 7 // max(w, 1)) % 7]
            frame = np.broadcast_to(cols[None, :, :c], (h, w, c)).copy()
        pts = self._count * self._dur if self._dur else self._count
        self._count += 1
        if self.is_live and self._dur:
            import time
            time.sleep(self._dur / 1e9)
        return Buffer([Chunk(frame)], pts=pts, duration=self._dur)


@register_element("audiotestsrc")
class AudioTestSrc(SrcElement):
    """Sine-wave audio frames (≙ audiotestsrc). One buffer =
    ``samplesperbuffer`` frames."""

    PROPS = {"caps": "audio/x-raw,format=S16LE,channels=1,rate=16000",
             "samplesperbuffer": 1024, "freq": 440.0}

    _FORMATS = {"S16LE": np.int16, "U8": np.uint8, "S8": np.int8,
                "F32LE": np.float32}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._count = 0

    def negotiate_src_caps(self) -> Optional[Caps]:
        return Caps(self.caps).fixate()

    def create(self) -> Optional[Buffer]:
        s = self.srcpad.caps.structures[0]
        rate = int(s.fields.get("rate", 16000))
        ch = int(s.fields.get("channels", 1))
        dt = self._FORMATS[str(s.fields.get("format", "S16LE"))]
        n = self.samplesperbuffer
        t = (np.arange(n) + self._count * n) / rate
        wave = np.sin(2 * np.pi * self.freq * t)
        if np.issubdtype(dt, np.integer):
            info = np.iinfo(dt)
            mid = (info.max + info.min + 1) / 2
            data = (mid + wave * (info.max - mid)).astype(dt)
        else:
            data = wave.astype(dt)
        frame = np.repeat(data[:, None], ch, axis=1)
        pts = int(self._count * n * 1e9 / rate)
        self._count += 1
        return Buffer([Chunk(frame)], pts=pts,
                      duration=int(n * 1e9 / rate))


@register_element("filesrc")
class FileSrc(SrcElement):
    """Whole-file reader: one buffer containing the file bytes
    (``blocksize=-1``) or fixed-size blocks."""

    PROPS = {"location": "", "blocksize": -1, "caps": ""}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._fp = None

    def negotiate_src_caps(self) -> Optional[Caps]:
        return Caps(self.caps) if self.caps else Caps(
            "application/octet-stream")

    def create(self) -> Optional[Buffer]:
        if self._fp is None:
            self._fp = open(self.location, "rb")
        data = self._fp.read() if self.blocksize < 0 else \
            self._fp.read(self.blocksize)
        if not data:
            self._fp.close()
            self._fp = None
            return None
        return Buffer([Chunk(np.frombuffer(data, np.uint8))])


@register_element("multifilesrc")
class MultiFileSrc(SrcElement):
    """Reads ``location`` as a printf pattern (frame.%03d.raw) or glob."""

    PROPS = {"location": "", "caps": "", "start-index": 0, "stop-index": -1}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._files: Optional[List[str]] = None
        self._idx = 0

    def negotiate_src_caps(self) -> Optional[Caps]:
        return Caps(self.caps) if self.caps else Caps(
            "application/octet-stream")

    def _resolve(self) -> List[str]:
        if "%" in self.location:
            out, i = [], self.start_index
            while self.stop_index < 0 or i <= self.stop_index:
                path = self.location % i
                if not os.path.exists(path):
                    break
                out.append(path)
                i += 1
            return out
        return sorted(glob.glob(self.location))

    def create(self) -> Optional[Buffer]:
        if self._files is None:
            self._files = self._resolve()
        if self._idx >= len(self._files):
            return None
        with open(self._files[self._idx], "rb") as f:
            data = f.read()
        self._idx += 1
        return Buffer([Chunk(np.frombuffer(data, np.uint8))])


@register_element("pngdec")
class PngDec(TransformElement):
    """Decode PNG (or JPEG — ``jpegdec`` is an alias) buffers into
    video/x-raw RGB frames (≙ gst pngdec in the reference's golden
    pipelines, tests/nnstreamer_filter_tensorflow2_lite/runTest.sh:77).
    Output caps are fixed from the first decoded frame."""

    SINK_TEMPLATES = {"sink": None}
    SRC_TEMPLATES = {"src": "video/x-raw"}

    def on_sink_caps(self, pad, caps) -> None:
        pass  # frame size unknown until the first buffer decodes

    def static_transfer(self, in_caps):
        """Unknown output: frame dims come from the decoded file."""
        return {"src": None}

    def transform(self, buf: Buffer) -> Optional[Buffer]:
        import io

        from PIL import Image
        img = Image.open(io.BytesIO(buf.chunks[0].host().tobytes()))
        frame = np.asarray(img.convert("RGB"))
        if self.srcpad.caps is None:
            h, w = frame.shape[:2]
            self.set_src_caps(Caps(
                f"video/x-raw,format=RGB,width={w},height={h},"
                "framerate=0/1"))
        return Buffer([Chunk(frame)], pts=buf.pts, duration=buf.duration)


register_element("jpegdec")(PngDec)


@register_element("videoscale")
class VideoScale(TransformElement):
    """Scale video frames to ``width`` x ``height`` (bilinear). The gst
    videoscale negotiates its target size with a downstream capsfilter;
    this runtime's negotiation is push-based, so the target is given as
    properties instead."""

    SINK_TEMPLATES = {"sink": "video/x-raw"}
    SRC_TEMPLATES = {"src": "video/x-raw"}
    PROPS = {"width": 0, "height": 0}

    def _out_caps(self, caps: Caps) -> Caps:
        (h, w, _), fmt = video_frame_shape(caps)
        out_w = self.width or w
        out_h = self.height or h
        s = caps.structures[0]
        rate = s.fields.get("framerate", "0/1")
        return Caps(
            f"video/x-raw,format={fmt},width={out_w},height={out_h},"
            f"framerate={rate}")

    def on_sink_caps(self, pad, caps) -> None:
        self.set_src_caps(self._out_caps(caps))

    def static_transfer(self, in_caps):
        """Scaled width/height on the declared video caps."""
        caps = in_caps.get("sink")
        if caps is None or not caps.is_fixed():
            return {"src": None}
        return {"src": self._out_caps(caps)}

    def transform(self, buf: Buffer) -> Optional[Buffer]:
        frame = buf.chunks[0].host()
        (h, w, _), _ = video_frame_shape(self.srcpad.caps)
        if frame.shape[0] == h and frame.shape[1] == w:
            return buf
        from PIL import Image
        gray = frame.ndim == 3 and frame.shape[-1] == 1
        img = Image.fromarray(frame[..., 0] if gray else frame)
        out = np.asarray(img.resize((w, h), Image.BILINEAR))
        if gray:
            out = out[..., None]
        return Buffer([Chunk(out)], pts=buf.pts, duration=buf.duration)


@register_element("videoconvert")
class VideoConvert(TransformElement):
    """Colorspace conversion between the supported raw formats (RGB/BGR/
    RGBA/BGRx/GRAY8). Target format via the ``format`` property (gst
    negotiates with a capsfilter instead)."""

    SINK_TEMPLATES = {"sink": "video/x-raw"}
    SRC_TEMPLATES = {"src": "video/x-raw"}
    PROPS = {"format": ""}

    def _out_caps(self, caps: Caps) -> Caps:
        (h, w, _), fmt = video_frame_shape(caps)
        out_fmt = self.format or fmt
        s = caps.structures[0]
        rate = s.fields.get("framerate", "0/1")
        return Caps(
            f"video/x-raw,format={out_fmt},width={w},height={h},"
            f"framerate={rate}")

    def on_sink_caps(self, pad, caps) -> None:
        self.set_src_caps(self._out_caps(caps))

    def static_transfer(self, in_caps):
        """Converted colorspace format on the declared video caps."""
        caps = in_caps.get("sink")
        if caps is None or not caps.is_fixed():
            return {"src": None}
        return {"src": self._out_caps(caps)}

    def transform(self, buf: Buffer) -> Optional[Buffer]:
        _, in_fmt = video_frame_shape(self.sinkpad.caps)
        _, out_fmt = video_frame_shape(self.srcpad.caps)
        if in_fmt == out_fmt:
            return buf
        frame = buf.chunks[0].host()
        rgb = self._to_rgb(frame, in_fmt)
        out = self._from_rgb(rgb, out_fmt)
        return Buffer([Chunk(out)], pts=buf.pts, duration=buf.duration)

    @staticmethod
    def _to_rgb(frame: np.ndarray, fmt: str) -> np.ndarray:
        if fmt == "RGB":
            return frame
        if fmt == "BGR":
            return frame[..., ::-1]
        if fmt == "RGBA":
            return frame[..., :3]
        if fmt == "BGRx":
            return frame[..., 2::-1]
        if fmt == "GRAY8":
            return np.repeat(frame, 3, axis=-1) if frame.shape[-1] == 1 \
                else np.repeat(frame[..., None], 3, axis=-1)
        raise ValueError(f"unsupported video format {fmt!r}")

    @staticmethod
    def _from_rgb(rgb: np.ndarray, fmt: str) -> np.ndarray:
        if fmt == "RGB":
            return np.ascontiguousarray(rgb)
        if fmt == "BGR":
            return np.ascontiguousarray(rgb[..., ::-1])
        if fmt == "RGBA":
            return np.concatenate(
                [rgb, np.full(rgb.shape[:2] + (1,), 255, np.uint8)], -1)
        if fmt == "BGRx":
            return np.concatenate(
                [rgb[..., ::-1],
                 np.full(rgb.shape[:2] + (1,), 255, np.uint8)], -1)
        if fmt == "GRAY8":
            return np.round(
                rgb @ np.array([0.299, 0.587, 0.114])).astype(
                    np.uint8)[..., None]
        raise ValueError(f"unsupported video format {fmt!r}")


@register_element("filesink")
class FileSink(SinkElement):
    PROPS = {"location": ""}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._fp = None

    def render(self, buf: Buffer) -> None:
        if self._fp is None:
            self._fp = open(self.location, "wb")
        for c in buf.chunks:
            self._fp.write(c.host().tobytes())

    def stop(self) -> None:
        if self._fp is not None:
            self._fp.close()
            self._fp = None
        super().stop()


@register_element("multifilesink")
class MultiFileSink(SinkElement):
    """One file per buffer: location is a printf pattern (out.%03d.raw) —
    the golden-test workhorse (≙ multifilesink in SSAT runTest.sh dumps)."""

    PROPS = {"location": "out.%03d.raw"}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._idx = 0

    def render(self, buf: Buffer) -> None:
        with open(self.location % self._idx, "wb") as f:
            for c in buf.chunks:
                f.write(c.host().tobytes())
        self._idx += 1
