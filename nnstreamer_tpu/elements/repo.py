"""tensor_repo_sink / tensor_repo_src — in-process repository enabling
pipeline cycles (recurrent topologies).

≙ gst/nnstreamer/elements/gsttensor_repo{,sink,src}.c: a global slot
table keyed by ``slot-index`` lets the back of a pipeline feed the front
without a pad link (LSTM/RNN scaffolds, tests/nnstreamer_repo_lstm).
"""
from __future__ import annotations

import collections
import threading
from typing import Deque, Dict, Optional

from ..pipeline.element import SinkElement, SrcElement
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer
from ..tensors.caps import Caps


class _Slot:
    def __init__(self, capacity: int = 2):
        self.queue: Deque[Buffer] = collections.deque()
        self.cond = threading.Condition()
        self.capacity = capacity
        self.eos = False


class TensorRepo:
    """Global slot table (≙ GstTensorRepo hash + cond-vars)."""

    def __init__(self):
        self._slots: Dict[int, _Slot] = {}
        self._lock = threading.Lock()

    def slot(self, index: int) -> _Slot:
        with self._lock:
            if index not in self._slots:
                self._slots[index] = _Slot()
            return self._slots[index]

    def push(self, index: int, buf: Buffer) -> None:
        s = self.slot(index)
        with s.cond:
            while len(s.queue) >= s.capacity and not s.eos:
                s.cond.wait(timeout=0.1)
            s.queue.append(buf)
            s.cond.notify_all()

    def pop(self, index: int, timeout: Optional[float] = None) -> Optional[Buffer]:
        s = self.slot(index)
        with s.cond:
            deadline = None
            while not s.queue:
                if s.eos:
                    return None
                if not s.cond.wait(timeout=timeout or 0.1) and timeout:
                    return None
            buf = s.queue.popleft()
            s.cond.notify_all()
            return buf

    def set_eos(self, index: int) -> None:
        s = self.slot(index)
        with s.cond:
            s.eos = True
            s.cond.notify_all()

    def reset(self) -> None:
        with self._lock:
            self._slots.clear()

    def snapshot_slot(self, index: int):
        """Coherent (queued buffers, eos) view of one slot for the
        checkpoint path (tensor_reposink's snapshot_state)."""
        s = self.slot(index)
        with s.cond:
            return list(s.queue), s.eos

    def restore_slot(self, index: int, bufs, eos: bool) -> None:
        s = self.slot(index)
        with s.cond:
            s.queue = collections.deque(bufs)
            s.eos = bool(eos)
            s.cond.notify_all()


GLOBAL_REPO = TensorRepo()


@register_element("tensor_reposink")
class TensorRepoSink(SinkElement):
    PROPS = {"slot-index": 0, "silent": True}
    # the writer owns the slot: one snapshot/restore site per cycle
    CHECKPOINTABLE = "the repo slot's queued frames + EOS flag"

    def render(self, buf: Buffer) -> None:
        GLOBAL_REPO.push(self.slot_index, buf)

    def on_eos(self) -> None:
        GLOBAL_REPO.set_eos(self.slot_index)
        super().on_eos()

    def snapshot_state(self, snap_dir):
        from ..checkpoint.state import dump_buffers
        bufs, eos = GLOBAL_REPO.snapshot_slot(self.slot_index)
        if not bufs and not eos:
            return None
        return {"queue": dump_buffers(bufs), "eos": eos}

    def restore_state(self, state, snap_dir):
        from ..checkpoint.state import load_buffers
        GLOBAL_REPO.restore_slot(self.slot_index,
                                 load_buffers(state["queue"]),
                                 state.get("eos", False))


@register_element("tensor_reposrc")
class TensorRepoSrc(SrcElement):
    PROPS = {"slot-index": 0, "caps": "", "silent": True}

    def negotiate_src_caps(self) -> Optional[Caps]:
        if not self.caps:
            raise ValueError(f"{self.name}: 'caps' property is required")
        return Caps(self.caps).fixate()

    def create(self) -> Optional[Buffer]:
        while not self._stop_evt.is_set():
            buf = GLOBAL_REPO.pop(self.slot_index, timeout=0.1)
            if buf is not None:
                return buf
            s = GLOBAL_REPO.slot(self.slot_index)
            if s.eos and not s.queue:
                return None
        return None
