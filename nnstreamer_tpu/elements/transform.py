"""tensor_transform — element-wise ops on tensor streams.

≙ gst/nnstreamer/elements/gsttensor_transform.c: modes typecast /
arithmetic / transpose / dimchg / stand / clamp / padding with the
reference's option-string grammar (e.g.
``mode=arithmetic option=typecast:float32,add:-127.5,div:127.5``).

Where the reference reaches for Orc SIMD (gsttensor_transform.c:56,
HAVE_ORC), this element computes with the array's own namespace: host
chunks via NumPy, device-resident chunks via jnp inside a cached jax.jit —
the op fuses into one XLA kernel and stays in HBM.
"""
from __future__ import annotations

import functools
from typing import Any, List, Optional, Tuple

import numpy as np

from ..pipeline.element import TransformElement
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorInfo, TensorsConfig, TensorsInfo
from ..tensors.types import TensorType

_ARITH_OPS = ("typecast", "add", "mul", "div")


def _parse_arith(option: str) -> List[Tuple[str, Any]]:
    """"typecast:float32,add:-127.5,div:127.5,add:1:2:3" ->
    [(op, scalar-or-vector)] applied in order. Multi-value operands are
    per-channel (innermost dim), ref per-channel option strings."""
    ops: List[Tuple[str, Any]] = []
    for part in option.split(","):
        part = part.strip()
        if not part:
            continue
        op, _, operand = part.partition(":")
        op = op.strip().lower()
        if op not in _ARITH_OPS:
            raise ValueError(f"unknown arithmetic op {op!r}")
        if op == "typecast":
            ops.append((op, TensorType.from_string(operand.strip())))
        else:
            vals = [float(v) for v in operand.split(":")]
            ops.append((op, vals[0] if len(vals) == 1 else np.array(vals)))
    return ops


def _apply_arith(arr, ops, xp):
    for op, operand in ops:
        if op == "typecast":
            arr = arr.astype(operand.np_dtype)
        elif op == "add":
            arr = arr + operand
        elif op == "mul":
            arr = arr * operand
        elif op == "div":
            arr = arr / operand
    return arr


def _ref_axes_to_np(axes_str: str, ndim: int) -> Tuple[int, ...]:
    """Reference transpose option is innermost-first dim indices
    ("1:0:2:3" swaps the two innermost). Convert to NumPy-order axes."""
    ref_axes = [int(a) for a in axes_str.split(":")]
    if len(ref_axes) < ndim:
        ref_axes += list(range(len(ref_axes), ndim))
    ref_axes = ref_axes[:ndim]
    # ref index i = numpy axis (ndim-1-i)
    np_axes = [0] * ndim
    for out_ref, in_ref in enumerate(ref_axes):
        np_axes[ndim - 1 - out_ref] = ndim - 1 - in_ref
    return tuple(np_axes)


@register_element("tensor_transform")
class TensorTransform(TransformElement):
    SINK_TEMPLATES = {"sink": "other/tensors"}
    SRC_TEMPLATES = {"src": "other/tensors"}
    PROPS = {"mode": "", "option": "", "acceleration": True,
             "transpose-rank-limit": 4}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._arith = None
        self._jit_cache = {}

    def start(self) -> None:
        super().start()
        if self.mode == "arithmetic":
            self._arith = _parse_arith(self.option)
        elif self.mode == "typecast":
            self._arith = [("typecast", TensorType.from_string(self.option))]

    # -- negotiation ------------------------------------------------------
    def transform_caps(self, incaps: Caps) -> Optional[Caps]:
        cfg = incaps.to_config()
        if not len(cfg.info):
            return incaps
        out = TensorsInfo()
        for info in cfg.info:
            out.append(self._transform_info(info))
        return Caps.from_config(TensorsConfig(out, cfg.format,
                                              cfg.rate_n, cfg.rate_d))

    def _transform_info(self, info: TensorInfo) -> TensorInfo:
        mode, opt = self.mode, self.option
        shape, ttype = tuple(info.shape), info.type
        if mode in ("typecast", "arithmetic"):
            ops = self._arith if self._arith is not None else (
                _parse_arith(opt) if mode == "arithmetic"
                else [("typecast", TensorType.from_string(opt))])
            for op, operand in ops:
                if op == "typecast":
                    ttype = operand
        elif mode == "transpose":
            axes = _ref_axes_to_np(opt, len(shape))
            shape = tuple(shape[a] for a in axes)
        elif mode == "dimchg":
            frm, to = (int(x) for x in opt.split(":"))
            nd = len(shape)
            np_from, np_to = nd - 1 - frm, nd - 1 - to
            dims = list(shape)
            d = dims.pop(np_from)
            dims.insert(np_to, d)
            shape = tuple(dims)
        elif mode == "clamp":
            pass
        elif mode == "stand":
            parts = opt.split(":")
            if len(parts) > 1:
                ttype = TensorType.from_string(parts[1])
            elif ttype not in (TensorType.FLOAT32, TensorType.FLOAT64):
                ttype = TensorType.FLOAT32
        elif mode == "padding":
            pads = self._parse_padding(opt, len(shape))
            shape = tuple(s + lo + hi for s, (lo, hi) in zip(shape, pads))
        elif mode == "":
            raise ValueError(f"{self.name}: 'mode' property is required")
        return TensorInfo(info.name, ttype, shape)

    @staticmethod
    def _parse_padding(opt: str, ndim: int) -> List[Tuple[int, int]]:
        """Option "left,right,dim[,left,right,dim...]" with reference
        innermost-first dim indices -> numpy pad widths."""
        toks = [int(t) for t in opt.replace(":", ",").split(",") if t != ""]
        pads = [(0, 0)] * ndim
        for i in range(0, len(toks), 3):
            left, right, ref_dim = toks[i:i + 3]
            pads[ndim - 1 - ref_dim] = (left, right)
        return pads

    # -- device placement (fusion compiler) --------------------------------
    DEVICE_FUSIBLE = ("typecast/arithmetic/transpose/dimchg/padding (dtype-"
                      "stable configs); clamp on float32; stand stays host")

    def device_veto(self) -> Optional[str]:
        if not self.mode:
            return "mode not set"
        if not self.acceleration:
            return "acceleration=false"
        if self.mode == "stand":
            return ("stand: float reductions (mean/std) are not byte-"
                    "stable between host numpy and XLA")
        return None

    def device_fn(self, ctx=None):
        if self.device_veto() is not None:
            return None
        if self._arith is None and self.mode in ("arithmetic", "typecast"):
            # mirror start(): device_fn may run before the element starts
            if self.mode == "arithmetic":
                self._arith = _parse_arith(self.option)
            else:
                self._arith = [("typecast",
                                TensorType.from_string(self.option))]
        cfg = getattr(ctx, "in_config", None) if ctx is not None else None
        if not self._dtype_stable(cfg):
            return None
        import jax.numpy as jnp
        op = self._op

        def fn(arrays):
            return [op(a, jnp) for a in arrays]

        return fn

    def _dtype_stable(self, cfg) -> bool:
        """Byte-parity guard for the fused path: numpy promotes
        (int array, float scalar) to float64 where jnp stays float32,
        and float->int casts truncate differently under numpy and XLA —
        only fuse configs where every step computes at an exactly
        matching dtype on both backends."""
        if cfg is None:
            return False
        if self.mode in ("transpose", "dimchg", "padding"):
            return True  # dtype-preserving data movement, any dtype
        floats = (TensorType.FLOAT32, TensorType.FLOAT64,
                  TensorType.FLOAT16, TensorType.BFLOAT16)
        for i in range(len(cfg.info)):
            dt = cfg.info[i].type
            if self.mode == "clamp":
                if dt != TensorType.FLOAT32:
                    return False
                continue
            for op, operand in (self._arith or ()):
                if op == "typecast":
                    if dt in floats and operand not in floats:
                        return False  # float->int casts truncate differently
                    dt = operand
                    if dt in (TensorType.FLOAT64, TensorType.INT64,
                              TensorType.UINT64):
                        return False
                    continue
                if isinstance(operand, np.ndarray):
                    return False  # float64 vector operand promotes differently
                if dt != TensorType.FLOAT32:
                    return False
        return True

    # -- dataflow ---------------------------------------------------------
    def transform(self, buf: Buffer) -> Optional[Buffer]:
        chunks = []
        for c in buf.chunks:
            if c.is_device and self.acceleration:
                chunks.append(Chunk(self._device_op(c.raw)))
            else:
                chunks.append(Chunk(self._host_op(c.host())))
        return buf.with_chunks(chunks)

    def _host_op(self, arr: np.ndarray) -> np.ndarray:
        return self._op(arr, np)

    def _device_op(self, arr):
        import jax
        sig = (self.mode, self.option, tuple(arr.shape), str(arr.dtype))
        fn = self._jit_cache.get(sig)
        if fn is None:
            import jax.numpy as jnp
            fn = jax.jit(functools.partial(self._op, xp=jnp))
            self._jit_cache[sig] = fn
        return fn(arr)

    def _op(self, arr, xp):
        mode, opt = self.mode, self.option
        if mode in ("typecast", "arithmetic"):
            ops = self._arith if self._arith is not None else _parse_arith(opt)
            return _apply_arith(arr, ops, xp)
        if mode == "transpose":
            return xp.transpose(arr, _ref_axes_to_np(opt, arr.ndim))
        if mode == "dimchg":
            frm, to = (int(x) for x in opt.split(":"))
            nd = arr.ndim
            return xp.moveaxis(arr, nd - 1 - frm, nd - 1 - to)
        if mode == "clamp":
            lo, hi = (float(x) for x in opt.split(":"))
            return xp.clip(arr, lo, hi)
        if mode == "stand":
            parts = opt.split(":")
            out_dt = np.dtype(TensorType.from_string(parts[1]).np_dtype) \
                if len(parts) > 1 else (arr.dtype if arr.dtype in
                                        (np.float32, np.float64) else np.float32)
            x = arr.astype(out_dt)
            if parts[0] == "dc-average":
                return x - xp.mean(x)
            std = xp.std(x)
            return (x - xp.mean(x)) / (std + 1e-10)
        if mode == "padding":
            pads = self._parse_padding(opt, arr.ndim)
            return xp.pad(arr, pads)
        raise ValueError(f"{self.name}: unknown mode {mode!r}")
