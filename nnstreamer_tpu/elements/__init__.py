"""Tensor pipeline elements (L3)."""
from . import filter  # noqa: F401  (registers tensor_filter)

__all__: list = []
