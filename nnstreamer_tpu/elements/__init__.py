"""Tensor pipeline elements (L3) — importing this package registers every
element with the factory (≙ registerer/nnstreamer.c GST_PLUGIN_DEFINE)."""
from . import filter  # noqa: F401  (tensor_filter)
from . import media  # noqa: F401  (videotestsrc/audiotestsrc/file IO)
from . import converter  # noqa: F401  (tensor_converter)
from . import transform  # noqa: F401  (tensor_transform)
from . import decoder  # noqa: F401  (tensor_decoder)
from . import combiner  # noqa: F401  (tensor_mux/tensor_merge/join)
from . import splitter  # noqa: F401  (tensor_demux/tensor_split)
from . import aggregator  # noqa: F401  (tensor_aggregator)
from . import flowctl  # noqa: F401  (tensor_if/tensor_rate)
from . import crop  # noqa: F401  (tensor_crop)
from . import repo  # noqa: F401  (tensor_reposink/tensor_reposrc)
from . import sparse  # noqa: F401  (tensor_sparse_enc/dec)
from . import sinks  # noqa: F401  (tensor_sink/tensor_debug)
from . import trainer  # noqa: F401  (tensor_trainer)
from . import datarepo  # noqa: F401  (datareposrc/datareposink)
from . import query  # noqa: F401  (tensor_query_client/serversrc/serversink)
from . import edge  # noqa: F401  (edgesrc/edgesink)
from . import mqtt  # noqa: F401  (mqttsrc/mqttsink)
from . import grpc  # noqa: F401  (tensor_src_grpc/tensor_sink_grpc)
from . import iio  # noqa: F401  (tensor_src_iio)

__all__: list = []
