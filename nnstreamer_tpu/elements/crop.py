"""tensor_crop — crop a tensor stream using crop-info arriving on a
second *stream* (not properties).

≙ gst/nnstreamer/elements/gsttensor_crop.c: ``raw`` pad carries frames,
``info`` pad carries regions (e.g. from the tensor_region decoder);
output is a flexible stream of cropped tensors (one chunk per region).
Region tensor: [N, 4] uint32 (x, y, w, h) in pixels of the raw frame.
"""
from __future__ import annotations

import collections
import threading
from typing import Deque, Optional

import numpy as np

from ..pipeline.element import Element
from ..pipeline.events import CapsEvent, EosEvent, Event
from ..pipeline.pad import Pad
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorsConfig, TensorsInfo
from ..tensors.meta import TensorMetaInfo
from ..tensors.types import TensorFormat


@register_element("tensor_crop")
class TensorCrop(Element):
    SINK_TEMPLATES = {"raw": "other/tensors", "info": "other/tensors"}
    SRC_TEMPLATES = {"src": "other/tensors"}
    PROPS = {"lateness": -1, "silent": True}

    # -- device placement (fusion compiler) --------------------------------
    # deliberately None: crop pairs TWO streams under a lock (stateful
    # cross-buffer queues) and emits a data-dependent number of
    # variable-shaped chunks — none of which a static jit program can
    # express. The planner also rejects it structurally (two sink pads).
    DEVICE_FUSIBLE = None

    def device_veto(self) -> Optional[str]:
        return ("stateful two-stream pairing with data-dependent "
                "output shapes")

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._raw_q: Deque[Buffer] = collections.deque()
        self._info_q: Deque[Buffer] = collections.deque()
        self._lock = threading.Lock()
        self._eos = {"raw": False, "info": False}
        self._sent_eos = False

    def handle_event(self, pad: Pad, event: Event) -> None:
        if isinstance(event, CapsEvent):
            pad.set_caps(event.caps)
            if pad.name == "raw":
                cfg = event.caps.to_config()
                out = TensorsConfig(TensorsInfo(), TensorFormat.FLEXIBLE,
                                    cfg.rate_n, cfg.rate_d)
                self.set_src_caps(Caps.from_config(out))
            return
        if isinstance(event, EosEvent):
            fire = False
            with self._lock:
                self._eos[pad.name] = True
                if all(self._eos.values()) and not self._sent_eos:
                    self._sent_eos = True
                    fire = True
            if fire:
                self.forward_event(event)
            return
        if pad.name == "raw":
            self.forward_event(event)

    def static_transfer(self, in_caps):
        """Flexible output (per-region crops have data-dependent dims);
        the rate follows the raw pad."""
        raw = in_caps.get("raw")
        if raw is None or not raw.is_fixed():
            return {"src": None}
        cfg = raw.to_config()
        return {"src": Caps.from_config(TensorsConfig(
            TensorsInfo(), TensorFormat.FLEXIBLE, cfg.rate_n, cfg.rate_d))}

    def do_chain(self, pad: Pad, buf: Buffer) -> None:
        with self._lock:
            (self._raw_q if pad.name == "raw" else self._info_q).append(buf)
            ready = []
            while self._raw_q and self._info_q:
                ready.append((self._raw_q.popleft(), self._info_q.popleft()))
        for raw, info in ready:
            out = self._crop(raw, info)
            if out is not None:
                self.srcpad.push(out)

    def _crop(self, raw: Buffer, info: Buffer) -> Optional[Buffer]:
        frame = raw.chunks[0].host()
        regions = info.chunks[0].host().reshape(-1, 4).astype(np.int64)
        chunks = []
        h, w = frame.shape[0], frame.shape[1]
        for x, y, cw, ch in regions:
            if cw <= 0 or ch <= 0:
                continue
            x0, y0 = max(0, int(x)), max(0, int(y))
            x1, y1 = min(w, x0 + int(cw)), min(h, y0 + int(ch))
            if x1 <= x0 or y1 <= y0:
                continue
            patch = np.ascontiguousarray(frame[y0:y1, x0:x1])
            meta = TensorMetaInfo.from_info(
                Buffer.from_arrays([patch]).to_infos()[0],
                format=TensorFormat.FLEXIBLE)
            chunks.append(Chunk(patch, meta=meta))
        if not chunks:
            return None
        return raw.with_chunks(chunks)
