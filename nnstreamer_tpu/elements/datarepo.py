"""datareposrc / datareposink — MLOps data-repository reader/writer.

≙ gst/datarepo/gstdatarepo{src,sink}.c: raw fixed-size sample records in a
data file, described by a JSON index with the reference's exact schema
(tests/test_models/data/datarepo/mnist.json)::

    {"gst_caps": "...", "total_samples": N, "sample_size": BYTES}

Reader properties mirror gstdatareposrc.c:140-193: location / json /
start-sample-index / stop-sample-index / epochs / is-shuffle /
tensors-sequence.

Note: datarepo caps join multi-tensor dims/types with "." (not ","),
e.g. ``dimensions=(string)1:1:784:1.1:1:10:1`` — normalized on load.
"""
from __future__ import annotations

import json
import os
import re
from typing import List, Optional

import numpy as np

from ..pipeline.element import SinkElement, SrcElement
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorsConfig

_DOT_FIELDS = re.compile(r"(dimensions|types)=(\(string\))?([^,;]*)")


def _normalize_datarepo_caps(caps_str: str) -> str:
    """datarepo joins list values with '.'; our caps grammar uses ','."""
    def fix(m):
        val = m.group(3).strip().strip('"')
        return f"{m.group(1)}=(string)\"{val.replace('.', ',')}\""
    return _DOT_FIELDS.sub(fix, caps_str)


def _denormalize_datarepo_caps(caps: Caps) -> str:
    cfg = caps.to_config()
    dims = cfg.info.dims_string().replace(",", ".")
    types = cfg.info.types_string().replace(",", ".")
    return (f"other/tensors, format=(string)static, "
            f"framerate=(fraction){cfg.rate_n}/{cfg.rate_d}, "
            f"num_tensors=(int){len(cfg.info)}, "
            f"dimensions=(string){dims}, types=(string){types}")


@register_element("datareposrc")
class DataRepoSrc(SrcElement):
    PROPS = {
        "location": "",
        "json": "",
        "start-sample-index": 0,
        "stop-sample-index": -1,
        "epochs": 1,
        "is-shuffle": True,
        "tensors-sequence": "",   # e.g. "1,0" reorders tensors per sample
        "caps": "",
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._config: Optional[TensorsConfig] = None
        self._fp = None
        self._order: List[int] = []
        self._cursor = 0
        self._epoch = 0
        self._rng = np.random.default_rng(0)
        self._sample_size = 0

    def negotiate_src_caps(self) -> Optional[Caps]:
        with open(self.json) as f:
            index = json.load(f)
        caps = Caps(_normalize_datarepo_caps(index["gst_caps"]))
        self._config = caps.to_config()
        self._total = int(index["total_samples"])
        self._sample_size = int(index["sample_size"])
        expect = self._config.info.total_size_bytes()
        if expect and expect != self._sample_size:
            raise ValueError(
                f"{self.name}: sample_size {self._sample_size} != caps "
                f"total {expect}")
        stop = self.stop_sample_index
        if stop < 0 or stop >= self._total:
            stop = self._total - 1
        self._range = list(range(self.start_sample_index, stop + 1))
        self._new_epoch()
        return caps

    def _new_epoch(self) -> None:
        self._order = list(self._range)
        if self.is_shuffle:
            self._rng.shuffle(self._order)
        self._cursor = 0
        self._epoch += 1

    def create(self) -> Optional[Buffer]:
        if self._cursor >= len(self._order):
            if self._epoch >= self.epochs:
                return None
            self._new_epoch()
        idx = self._order[self._cursor]
        self._cursor += 1
        if self._fp is None:
            self._fp = open(self.location, "rb")
        self._fp.seek(idx * self._sample_size)
        raw = self._fp.read(self._sample_size)
        if len(raw) < self._sample_size:
            return None
        chunks, off = [], 0
        for info in self._config.info:
            nb = info.size_bytes
            arr = np.frombuffer(raw[off:off + nb],
                                info.type.np_dtype).reshape(info.shape)
            chunks.append(Chunk(arr))
            off += nb
        if self.tensors_sequence:
            order = [int(i) for i in self.tensors_sequence.split(",")]
            chunks = [chunks[i] for i in order]
        return Buffer(chunks)

    def stop(self) -> None:
        if self._fp is not None:
            self._fp.close()
            self._fp = None
        super().stop()


@register_element("datareposink")
class DataRepoSink(SinkElement):
    PROPS = {"location": "", "json": ""}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._fp = None
        self._count = 0
        self._sample_size = 0

    def render(self, buf: Buffer) -> None:
        if self._fp is None:
            self._fp = open(self.location, "wb")
        raw = b"".join(c.host().tobytes() for c in buf.chunks)
        if self._sample_size == 0:
            self._sample_size = len(raw)
        elif len(raw) != self._sample_size:
            raise ValueError(
                f"{self.name}: variable sample size "
                f"({len(raw)} != {self._sample_size})")
        self._fp.write(raw)
        self._count += 1

    def stop(self) -> None:
        if self._fp is not None:
            self._fp.close()
            self._fp = None
        super().stop()

    def on_eos(self) -> None:
        self._write_json()
        super().on_eos()

    def _write_json(self) -> None:
        if not self.get_property("json"):
            return
        caps = self.sinkpad.caps
        index = {
            "gst_caps": _denormalize_datarepo_caps(caps) if caps else "",
            "total_samples": self._count,
            "sample_size": self._sample_size,
        }
        with open(self.get_property("json"), "w") as f:
            json.dump(index, f, indent=2)
