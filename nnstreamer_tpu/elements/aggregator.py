"""tensor_aggregator — temporal batching of tensor frames.

≙ gst/nnstreamer/elements/gsttensor_aggregator.c: concatenate
``frames-out`` input frames into one output (on ``frames-dim``), advance
by ``frames-flush`` (sliding window when flush < out), adjust framerate.
``concat=false`` stacks on a new outermost dim instead.
"""
from __future__ import annotations

import collections
from typing import Deque, Optional

import numpy as np

from ..pipeline.element import TransformElement
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..tensors.info import TensorInfo, TensorsConfig, TensorsInfo


@register_element("tensor_aggregator")
class TensorAggregator(TransformElement):
    PROPS = {"frames-in": 1, "frames-out": 1, "frames-flush": 0,
             "frames-dim": 3, "concat": True, "silent": True}
    STRIPS_META = True  # output windows are fresh buffers, N inputs -> 1
    RESTART_SAFE = False  # a restart would drop the aggregation window
    CHECKPOINTABLE = "the partial aggregation window (frames + timing)"

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._window: Deque[Buffer] = collections.deque()

    def snapshot_state(self, snap_dir):
        if not self._window:
            return None
        from ..checkpoint.state import dump_buffers
        return {"window": dump_buffers(self._window)}

    def restore_state(self, state, snap_dir):
        from ..checkpoint.state import load_buffers
        self._window = collections.deque(load_buffers(state["window"]))  # racecheck: ok(restore runs before start(): no chain thread exists yet)

    def _np_axis(self, ndim: int) -> int:
        ref_dim = int(self.frames_dim)
        if ref_dim >= ndim:
            return 0
        return ndim - 1 - ref_dim

    def transform_caps(self, incaps: Caps) -> Optional[Caps]:
        cfg = incaps.to_config()
        if not len(cfg.info):
            return incaps
        out = TensorsInfo()
        if self.frames_in > self.frames_out:
            # splitting mode: one k-frame buffer -> k/out per-chunk buffers
            ratio = self.frames_in // max(1, self.frames_out)
            for info in cfg.info:
                shape = list(info.shape)
                axis = self._np_axis(len(shape))
                if shape[axis] % ratio:
                    raise ValueError(
                        f"{self.name}: dim {shape[axis]} not divisible by "
                        f"frames-in/frames-out ratio {ratio}")
                shape[axis] //= ratio
                out.append(TensorInfo(info.name, info.type, tuple(shape)))
            rate_n = cfg.rate_n * ratio if cfg.rate_n > 0 else cfg.rate_n
            return Caps.from_config(
                TensorsConfig(out, cfg.format, rate_n, cfg.rate_d))
        n = self.frames_out // max(1, self.frames_in)
        for info in cfg.info:
            shape = list(info.shape)
            if self.concat and shape:
                axis = self._np_axis(len(shape))
                shape[axis] *= n
            else:
                shape = [n] + shape
            out.append(TensorInfo(info.name, info.type, tuple(shape)))
        flush = self.frames_flush or self.frames_out
        rate_n, rate_d = cfg.rate_n, cfg.rate_d
        if cfg.rate_n > 0:
            rate_d = cfg.rate_d * max(1, flush)
        return Caps.from_config(TensorsConfig(out, cfg.format, rate_n, rate_d))

    def transform(self, buf: Buffer) -> Optional[Buffer]:
        if self.frames_in > self.frames_out:
            return self._split(buf)
        n = self.frames_out // max(1, self.frames_in)
        if n <= 1:
            return buf
        self._window.append(buf)
        if len(self._window) < n:
            return None
        frames = list(self._window)
        flush = self.frames_flush or n
        for _ in range(min(flush, len(self._window))):
            self._window.popleft()
        chunks = []
        for i in range(len(frames[0].chunks)):
            arrs = [f.chunks[i].host() for f in frames]
            if self.concat:
                axis = self._np_axis(arrs[0].ndim)
                chunks.append(Chunk(np.concatenate(arrs, axis=axis)))
            else:
                chunks.append(Chunk(np.stack(arrs)))
        out = Buffer(chunks, pts=frames[0].pts)
        if frames[0].pts is not None and frames[-1].pts is not None:
            out.duration = (frames[-1].pts - frames[0].pts +
                            (frames[-1].duration or 0))
        return out

    def _split(self, buf: Buffer) -> None:
        """Splitting mode: emit ratio buffers per input, slicing each chunk
        along frames-dim (≙ gsttensor_aggregator.c frames-in > frames-out)."""
        ratio = self.frames_in // max(1, self.frames_out)
        arrs = [c.host() for c in buf.chunks]
        step_ns = (buf.duration // ratio) if buf.duration else None
        for i in range(ratio):
            chunks = []
            for a in arrs:
                axis = self._np_axis(a.ndim)
                size = a.shape[axis] // ratio
                sl = [slice(None)] * a.ndim
                sl[axis] = slice(i * size, (i + 1) * size)
                chunks.append(Chunk(np.ascontiguousarray(a[tuple(sl)])))
            pts = (buf.pts + i * step_ns) if (buf.pts is not None and
                                             step_ns) else buf.pts
            self.push(Buffer(chunks, pts=pts, duration=step_ns))
        return None

    def on_eos(self) -> None:
        self._window.clear()
