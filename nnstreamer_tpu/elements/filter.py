"""tensor_filter — the inference element.

≙ gst/nnstreamer/tensor_filter/tensor_filter.c (+ tensor_filter_common.c):
property parsing, framework auto-detection, model-vs-caps verification,
invoke dispatch, rolling latency/throughput statistics, input/output
combination, async generative output, suspend watchdog, shared-model key.

TPU-native specifics: chunks handed to the backend may already be
device-resident (HBM); outputs stay device-resident until a host boundary.
The hot path is one cached-executable dispatch (SURVEY.md §3.2 analog).
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Any, List, Optional

import numpy as np

from ..filters.base import (Accelerator, FilterEvent, FilterProperties,
                            InvokeDrop)
from ..filters.registry import (detect_framework, find_filter,
                                shared_model_get, shared_model_insert,
                                shared_model_release)
from ..tensors.buffer import Buffer, Chunk
# module scope, not per-frame: submit_fetch runs on every prefetch-host
# frame on the hot path
from ..tensors.transfer import submit_fetch
from ..tensors.caps import Caps
from ..tensors.info import TensorInfo, TensorsConfig, TensorsInfo
from ..tensors.types import TensorFormat
from ..obs import events as _obs_events
from ..pipeline.element import Element, TransferError
from ..pipeline.events import Event, QosEvent
from ..pipeline.pad import Pad
from ..pipeline.registry import register_element
from ..utils.log import logger
from ..utils.watchdog import Watchdog

# rolling window for the latency property
# (≙ GST_TF_STAT_MAX_RECENT, tensor_filter.c)
_MAX_RECENT = 10

# latency re-report thresholds (≙ tensor_filter.c:106-118): re-post when
# the estimate grows past reported×(1+5%) or improves by more than 25%
_LATENCY_REPORT_HEADROOM = 1.05
_LATENCY_IMPROVE_THRESHOLD = 0.75


def infer_batch_dim(sel: TensorsInfo, model: TensorsInfo) -> Optional[int]:
    """The stream's uniform leading batch dim over the model input, or
    None when the stream is not model-plus-one-leading-dim."""
    if len(sel) != len(model):
        return None
    b = None
    for s, m in zip(sel, model):
        if s.type != m.type or len(s.shape) != len(m.shape) + 1 \
                or tuple(s.shape[1:]) != tuple(m.shape):
            return None
        if b is None:
            b = int(s.shape[0])
        elif int(s.shape[0]) != b:
            return None
    return b


@register_element("tensor_filter")
class TensorFilter(Element):
    SINK_TEMPLATES = {"sink": "other/tensors"}
    SRC_TEMPLATES = {"src": "other/tensors"}
    # under overlap-depth>0 the executor adds dispatch/complete spans
    SPAN_POINTS = ("chain", "dispatch", "complete")
    PROPS = {
        "framework": "auto",
        "model": "",
        "input": "", "inputtype": "", "inputname": "",
        "output": "", "outputtype": "", "outputname": "",
        "accelerator": "",
        "custom": "",
        "latency": 0,            # 1 = enable latency property updates
        "throughput": 0,
        "invoke-dynamic": False,
        "invoke-async": False,
        "suspend": 0,            # idle ms before model unload; 0 = off
        "shared-tensor-filter-key": "",
        "input-combination": "",
        "output-combination": "",
        # start async device->host copies of outputs at invoke time, so
        # a downstream host boundary (decoder/serializer) finds the data
        # already in flight instead of paying the full D2H round-trip
        # latency per frame. Off by default: chained device-resident
        # elements should NOT force transfers.
        "prefetch-host": False,
        # circuit breaker on the backend path (fault/breaker.py):
        # breaker-threshold consecutive invoke failures open it — frames
        # are then SHED (serve rows answered with MsgKind.SHED +
        # retry-after, upstream throttled via QosEvent) instead of each
        # paying a doomed invoke; after breaker-reset-ms one probe
        # half-opens it. 0 = disabled (default).
        "breaker-threshold": 0,
        "breaker-reset-ms": 1000.0,
        "breaker-retry-after-ms": 50.0,
        # K-frame in-flight invoke window (elements/overlap.py): keep up
        # to K frames between dispatch and completion, completing each on
        # a dedicated completer thread instead of blocking the chain
        # thread — on a remote-attached chip this hides the link RTT
        # behind the compute (throughput ≈ min(K/RTT, chip ceiling)
        # instead of ≈ 1/RTT). 1 = synchronous (default). Requires a
        # backend with async dispatch (SUPPORTS_DISPATCH, e.g. jax);
        # otherwise the filter logs a notice and stays synchronous.
        "in-flight": 1,
        # restore PTS order before push() when in-flight > 1 (bounded
        # reorder buffer with a stall deadline). Disable only when every
        # downstream consumer is order-insensitive — pipelint WARNs if an
        # aggregator/trainer/rate sits downstream without it.
        "reorder": True,
        # how long the reorder buffer dams the pipeline waiting for a
        # missing frame before abandoning the gap
        "reorder-deadline-ms": 1000.0,
        # donate input device buffers to the dispatched executable
        # (XLA input/output aliasing): the H2D staging buffer is reused
        # for the outputs, halving HBM traffic per frame. Only honored
        # on device platforms that support donation (tpu/gpu) and only
        # for buffers this filter itself uploaded; device-resident
        # inputs owned by upstream elements are never donated.
        "donate-input": False,
        # run one zero-filled invoke at caps negotiation so the XLA
        # compile (tens of seconds for a big model) happens before the
        # first real frame instead of stalling it (no reference analog:
        # its backends don't JIT; on TPU cold-start hygiene is a
        # framework concern). Only effective for sync invokes on STATIC
        # caps: async/dynamic/flexible streams have no fixed invoke
        # signature to warm (async backends such as the LLM filter warm
        # through their own prefill path) — requesting it there logs a
        # notice and does nothing.
        "warmup": False,
    }

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.fw = None
        self._fw_owned = True
        self._in_info: Optional[TensorsInfo] = None
        self._out_info: Optional[TensorsInfo] = None
        self._recent_latency = collections.deque(maxlen=_MAX_RECENT)
        self._invoke_count = 0
        self._total_latency_ns = 0
        # dispatch-to-return timing, distinct from dispatch-to-completion
        # (_recent_latency): under an in-flight window the former is the
        # chain-thread cost (near-zero by design), the latter the real
        # model+link latency. Both are surfaced; QoS uses completion.
        self._recent_dispatch = collections.deque(maxlen=_MAX_RECENT)
        self._dispatch_count = 0
        self._total_dispatch_ns = 0
        # latency fields are written by the chain thread (sync path) AND
        # the completer thread (windowed path): one leaf lock covers the
        # deques/counters (racecheck: rmw from two roles needs it)
        self._stats_lock = threading.Lock()
        self._overlap = None               # OverlapExecutor when K > 1
        self._start_time = None
        self._watchdog: Optional[Watchdog] = None
        self._in_combi: Optional[List[int]] = None
        self._out_combi: Optional[List[str]] = None
        self._batch: Optional[int] = None  # batched-invoke leading dim
        self._reported_latency_us: Optional[float] = None
        self._throttle_period_ns = 0       # from downstream QoS events
        self._next_accept_ts: Optional[int] = None
        self._breaker = None
        # checkpoint/: framework state recovered by restore_state,
        # applied once the framework is open (start())
        self._fw_restore = None
        self.stats.update({"invoke_errors": 0, "frames_dropped": 0,
                           "qos_dropped": 0, "shed": 0,
                           "breaker_opened": 0})

    # -- framework lifecycle ---------------------------------------------
    def _open_fw(self) -> None:
        if self.fw is not None:
            return
        from ..utils.models import resolve
        # model:// and mlagent://model/ URIs resolve through the model
        # registry (≙ ml_agent.c URI resolution); plain paths untouched
        models = tuple(resolve(m) for m in self.model.split(",") if m) \
            if self.model else ()
        fw_name = self.framework
        if fw_name in ("auto", ""):
            fw_name = detect_framework(models)
        props = FilterProperties(
            framework=fw_name,
            model_files=models,
            # empty accelerator property = framework default (TPU), like the
            # reference's auto mode; an explicit "false"/"cpu" opts out
            accelerators=(tuple(Accelerator.parse(self.accelerator))
                          if self.accelerator else (Accelerator.DEFAULT,)),
            custom_properties=self.custom,
            invoke_dynamic=self.invoke_dynamic,
            invoke_async=self.invoke_async,
            shared_key=self.shared_tensor_filter_key or None,
            latency_report=bool(self.latency),
        )
        if self.input and self.inputtype:
            props.input_info = TensorsInfo.make(self.inputtype, self.input)
        if self.output and self.outputtype:
            props.output_info = TensorsInfo.make(self.outputtype, self.output)

        fw = None
        if props.shared_key:
            # consult the registry BEFORE loading: one HBM copy of the weights
            fw = shared_model_get(props.shared_key)
            self._fw_owned = False
        if fw is None:
            fw = find_filter(fw_name)()
            fw.open(props)
            if props.shared_key:
                fw = shared_model_insert(props.shared_key, fw)
        self.fw = fw
        self._fw_props = props
        mi_in, mi_out = fw.get_model_info()
        self._in_info = props.input_info or mi_in
        self._out_info = props.output_info or mi_out
        if self.invoke_async:
            fw.set_async_dispatcher(self._dispatch_async)
        if self.suspend > 0:
            self._watchdog = Watchdog(self.suspend / 1000.0, self._on_idle)
        if self._in_combi is None and self.input_combination:
            self._in_combi = [int(i) for i in self.input_combination.split(",")]
        if self._out_combi is None and self.output_combination:
            self._out_combi = [t.strip() for t in self.output_combination.split(",")]

    RESTART_SAFE = True  # stop/start re-opens the framework cleanly

    def start(self) -> None:
        super().start()
        self._open_fw()
        if self._fw_restore is not None:
            state, snap_dir = self._fw_restore
            if hasattr(self.fw, "restore_state"):
                self.fw.restore_state(state, snap_dir)
            self._fw_restore = None
        self._start_time = time.monotonic()
        if int(self.breaker_threshold) > 0:
            from ..fault.breaker import CircuitBreaker
            self._breaker = CircuitBreaker(
                threshold=int(self.breaker_threshold),
                reset_s=float(self.breaker_reset_ms) / 1e3,
                name=self.name, on_transition=self._on_breaker_transition)
        else:
            self._breaker = None
        self._overlap = None
        window = int(self.in_flight)
        if window > 1:
            if self.invoke_async:
                logger.info("%s: in-flight=%d ignored — invoke-async "
                            "backends manage their own in-flight frames",
                            self.name, window)
            elif not getattr(self.fw, "SUPPORTS_DISPATCH", False):
                logger.info("%s: in-flight=%d ignored — framework %s has "
                            "no async dispatch; staying synchronous",
                            self.name, window, self.fw.NAME)
            else:
                from .overlap import OverlapExecutor
                mesh = getattr(self.fw, "mesh", None)
                devices = len(mesh.devices.ravel()) if mesh is not None else 1
                self._overlap = OverlapExecutor(
                    window,
                    complete_cb=self._complete_frame,
                    error_cb=self._complete_error,
                    push_cb=self.push,
                    name=self.name,
                    reorder=bool(self.reorder),
                    reorder_deadline_s=float(self.reorder_deadline_ms) / 1e3,
                    devices=devices)

    def drain(self) -> None:
        """During a deliberate drain the filter may sit idle for longer
        than the suspend window while upstream flushes its queues —
        quiesce the idle watchdog so the model is not unloaded right
        before the flushed tail arrives and needs it. (The pipeline
        stops after the drain, so the quiesce is never resumed: destroy
        in stop() cleans up.)"""
        super().drain()
        if self._overlap is not None:
            self._overlap.flush()
        if self._watchdog is not None:
            self._watchdog.quiesce()

    # -- checkpoint/restore (checkpoint/) ---------------------------------
    CHECKPOINTABLE = ("whatever the loaded framework exposes (e.g. the "
                      "llm backend's continuous-batching streams)")

    def snapshot_state(self, snap_dir):
        # delegation, not ownership: the element is stateless between
        # frames, but a framework may carry cross-invoke state (llm
        # continuous batching) it knows how to snapshot
        if self.fw is not None and hasattr(self.fw, "snapshot_state"):
            return self.fw.snapshot_state(snap_dir)
        if self._fw_restore is not None:
            return self._fw_restore[0]  # restored, never started: re-emit
        return None

    def restore_state(self, state, snap_dir):
        self._fw_restore = (state, snap_dir)

    def stop(self) -> None:
        super().stop()
        if self._overlap is not None:
            # settle every in-flight frame before the framework closes;
            # the (stopped) executor is kept so post-run trace reports
            # still see the window/overlap numbers
            self._overlap.flush()
            self._overlap.stop()
        if self._watchdog is not None:
            self._watchdog.destroy()
        if self.fw is not None:
            key = self.shared_tensor_filter_key
            if key:
                shared_model_release(key)
            elif self._fw_owned:
                self.fw.close()
            self.fw = None

    # -- negotiation ------------------------------------------------------
    def _infer_batch(self, sel: TensorsInfo) -> Optional[int]:
        """If the stream is the model input plus one leading (outermost)
        batch dim on every tensor, return that batch size.

        TPU-first batched invoke: tensor_aggregator (or a batched source)
        stacks N frames; the whole stack goes through ONE executable
        dispatch, which is how the MXU earns its keep — the reference has
        no analog (its backends are handed exactly the model shape).
        Only backends declaring SUPPORTS_BATCH negotiate this; others keep
        the fail-fast caps mismatch error."""
        if not getattr(self.fw, "SUPPORTS_BATCH", False):
            return None
        if self._in_info is None:
            return None
        return infer_batch_dim(sel, self._in_info)

    def on_sink_caps(self, pad: Pad, caps: Caps) -> None:
        self._open_fw()
        cfg = caps.to_config()
        self._batch = None
        if self._in_info is not None and cfg.format == TensorFormat.STATIC:
            sel = cfg.info
            if self._in_combi:
                sel = TensorsInfo(cfg.info[i] for i in self._in_combi)
            if len(sel) and not sel.is_equal(self._in_info):
                self._batch = self._infer_batch(sel)
                if self._batch is None:
                    raise ValueError(
                        f"{self.name}: model input {self._in_info!r} does not match "
                        f"negotiated stream caps {sel!r}. Check tensor_converter/"
                        "tensor_transform output dims, or set input/inputtype "
                        "properties explicitly.")
        elif self._in_info is None:
            # push-path: derive model info from caps (SET_INPUT_INFO analog)
            self._in_info = cfg.info
            out = self.fw.set_input_info(cfg.info)
            if out is not None:
                self._out_info = out
        if self.invoke_dynamic or self._out_info is None:
            out_cfg = TensorsConfig(TensorsInfo(), TensorFormat.FLEXIBLE,
                                    cfg.rate_n, cfg.rate_d)
        else:
            out_info = self._out_info.copy()
            if self._batch is not None:
                out_info = TensorsInfo(
                    TensorInfo(i.name, i.type, (self._batch,) + tuple(i.shape))
                    for i in out_info)
            out_cfg = TensorsConfig(out_info, TensorFormat.STATIC,
                                    cfg.rate_n, cfg.rate_d)
        self.set_src_caps(Caps.from_config(out_cfg))
        if self.warmup:
            if self.invoke_async or self.invoke_dynamic \
                    or cfg.format != TensorFormat.STATIC:
                # not silently inert: tell the user WHY nothing warmed
                logger.info(
                    "%s: warmup requested but skipped (%s) — no fixed "
                    "invoke signature to warm; async filters warm via "
                    "their own prefill path", self.name,
                    "invoke-async" if self.invoke_async else
                    "invoke-dynamic" if self.invoke_dynamic else
                    "non-static stream format")
            else:
                # the same selection real frames will use (sel was
                # computed above for STATIC caps)
                sel = cfg.info
                if self._in_combi:
                    sel = TensorsInfo(cfg.info[i] for i in self._in_combi)
                if len(sel):
                    self._warmup_invoke(sel)

    def static_transfer(self, in_caps):
        """Model I/O from declared properties only (the framework is
        never opened): input/inputtype are checked against the stream
        with batch-dim tolerance; invoke-dynamic or output/outputtype
        give the out caps, otherwise the output is unknown."""
        incaps = in_caps.get("sink")
        cfg = None
        if incaps is not None and not incaps.any and incaps.structures \
                and incaps.is_fixed():
            try:
                cfg = incaps.to_config()
            except ValueError as exc:
                raise TransferError(f"{self.name}: {exc}", pad="sink")
        rate = (cfg.rate_n, cfg.rate_d) if cfg is not None else (0, 1)
        batch = None
        if self.input and self.inputtype and cfg is not None \
                and cfg.format == TensorFormat.STATIC and len(cfg.info):
            model_in = TensorsInfo.make(self.inputtype, self.input)
            sel = cfg.info
            if self.input_combination:
                idxs = [int(i) for i in self.input_combination.split(",")]
                sel = TensorsInfo(cfg.info[i] for i in idxs)
            if len(sel) and not sel.is_equal(model_in):
                # permissive on batching: SUPPORTS_BATCH is a backend
                # trait we cannot know without opening the framework
                batch = infer_batch_dim(sel, model_in)
                if batch is None:
                    raise TransferError(
                        f"{self.name}: model input {model_in!r} does not "
                        f"match stream caps {sel!r}. Check tensor_"
                        f"converter/tensor_transform output dims, or the "
                        f"input/inputtype properties.", pad="sink")
        if self.invoke_dynamic:
            out_cfg = TensorsConfig(TensorsInfo(), TensorFormat.FLEXIBLE,
                                    *rate)
        elif self.output and self.outputtype:
            out_info = TensorsInfo.make(self.outputtype, self.output)
            if batch is not None:
                out_info = TensorsInfo(
                    TensorInfo(i.name, i.type, (batch,) + tuple(i.shape))
                    for i in out_info)
            out_cfg = TensorsConfig(out_info, TensorFormat.STATIC, *rate)
        else:
            return {"src": None}  # model metadata needs the framework
        return {"src": Caps.from_config(out_cfg)}

    # -- device placement (fusion compiler) --------------------------------
    DEVICE_FUSIBLE = ("sync jax-backend invokes on static caps "
                      "(no invoke-async/dynamic; mesh-sharded members "
                      "fuse when the run shares one mesh spec)")

    _JAX_FRAMEWORKS = ("jax", "jax-tpu", "flax")

    def device_veto(self) -> Optional[str]:
        if self.invoke_async:
            return "invoke-async: output frames are decoupled from inputs"
        if self.invoke_dynamic:
            return "invoke-dynamic: per-frame output shapes (dynamic caps)"
        fw = (self.framework or "").lower()
        if fw in ("auto", ""):
            first = self.model.split(",")[0] if self.model else ""
            if not first.startswith("zoo://"):
                return (f"framework auto-detect on {first!r} cannot be "
                        f"proven to be the jax backend statically")
            return None  # zoo:// always resolves to the jax backend
        if fw not in self._JAX_FRAMEWORKS:
            return f"framework {fw!r} exposes no traceable invoke"
        return None

    def mesh_spec(self) -> str:
        """The declared ``mesh:`` custom option (e.g. ``"2x2x2"``,
        ``"auto"``), "" when unsharded. Static — readable before the
        framework opens; the fusion planner uses it to break runs at
        mesh-spec boundaries (one fused program, one mesh)."""
        for part in str(self.custom or "").split(","):
            part = part.strip()
            if part.startswith("mesh:"):
                return part[len("mesh:"):].strip()
        return ""

    def plan_out_caps(self, incaps: Caps) -> Optional[Caps]:
        """Plan-time refinement of :meth:`static_transfer`: opens the
        framework (the fusion planner runs after validation, before
        start — the one caller allowed to) and answers the same caps
        :meth:`on_sink_caps` would negotiate, without its side
        effects."""
        self._open_fw()
        cfg = incaps.to_config()
        if cfg.format != TensorFormat.STATIC or self._out_info is None:
            return None
        sel = cfg.info
        if self._in_combi:
            sel = TensorsInfo(cfg.info[i] for i in self._in_combi)
        batch = None
        if self._in_info is not None and len(sel) \
                and not sel.is_equal(self._in_info):
            batch = self._infer_batch(sel)
            if batch is None:
                return None
        out_info = self._out_info.copy()
        if batch is not None:
            out_info = TensorsInfo(
                TensorInfo(i.name, i.type, (batch,) + tuple(i.shape))
                for i in out_info)
        return Caps.from_config(TensorsConfig(
            out_info, TensorFormat.STATIC, cfg.rate_n, cfg.rate_d))

    def device_fn(self, ctx=None):
        """The backend's pure apply closure, wrapped with the filter's
        input/output-combination wiring. prefetch-host is ignored for
        MID-segment outputs (activations never leave the device, which
        is the point); the FusedSegment honors it for the segment's
        final outputs instead."""
        if self.device_veto() is not None:
            return None
        try:
            self._open_fw()
        except Exception:  # noqa: BLE001 -- decline, don't block launch
            logger.warning("%s: device_fn could not open the framework; "
                           "staying on the chain path", self.name,
                           exc_info=True)
            return None
        get = getattr(self.fw, "traceable_fn", None)
        tr = get() if callable(get) else None
        if tr is None:
            return None
        in_combi, out_combi = self._in_combi, self._out_combi

        def fn(arrays):
            xs = [arrays[i] for i in in_combi] if in_combi else list(arrays)
            outs = tr(*xs)
            if not isinstance(outs, (list, tuple)):
                outs = [outs]
            outs = list(outs)
            if out_combi:
                outs = [arrays[int(t[1:])] if t[0] == "i"
                        else outs[int(t[1:])] for t in out_combi]
            return outs

        return fn

    def _warmup_invoke(self, sel: TensorsInfo) -> None:
        """One zero-filled invoke with the NEGOTIATED stream shapes
        (incl. any batch dim), so the jit cache is hot for the exact
        signature real frames will hit. Failures are non-fatal: real
        frames will surface the same error through the normal path."""
        try:
            zeros = [np.zeros(tuple(i.shape), i.type.np_dtype)
                     for i in sel]
            self.fw.invoke(zeros)
            if self._watchdog is not None:
                # a long warmup compile must not be answered by an
                # immediate idle-suspend that clears the cache it built
                self._watchdog.feed()
            logger.info("%s: warmup invoke compiled %d input(s)",
                        self.name, len(zeros))
        except Exception as exc:  # noqa: BLE001
            logger.warning("%s: warmup invoke failed (ignored): %s",
                           self.name, exc)

    # -- hot path ---------------------------------------------------------
    def do_chain(self, pad: Pad, buf: Buffer) -> None:
        if self._qos_should_drop(buf):
            # downstream can't keep up: skip the invoke entirely so the
            # accelerator does no wasted work (≙ throttling check,
            # tensor_filter.c:532-584)
            self.stats.inc("qos_dropped")
            return
        if self._breaker is not None and not self._breaker.allow():
            # breaker OPEN: the backend is currently only producing
            # errors — shed without invoking (TF-Serving-style fail
            # fast) and tell upstream/clients when to come back
            self._shed_frame(buf)
            return
        inputs = [c.raw for c in buf.chunks]
        if self._in_combi:
            inputs = [inputs[i] for i in self._in_combi]
        if self._overlap is not None:
            self._dispatch_windowed(buf, inputs)
            return
        t0 = time.perf_counter_ns()
        c0 = getattr(self.fw, "compile_count", 0)
        try:
            if self.invoke_async:
                # ctx rides along with the invoke so each dispatched
                # output frame inherits ITS prompt's buffer (PTS et al.)
                # even with several invokes in flight; the template is a
                # fallback for backends that don't thread ctx through
                self._async_template = buf
                self.fw.invoke_async(inputs, ctx=buf)
                self._note_recompiles(c0)
                self._record_dispatch(time.perf_counter_ns() - t0)
                self._record_latency(time.perf_counter_ns() - t0)
                return
            outputs = self.fw.invoke(inputs)
        except InvokeDrop:
            # subplugin-signaled drop (≙ invoke result > 0): silent.
            # A deliberate drop is a WORKING backend for the breaker.
            if self._breaker is not None:
                self._breaker.record_success()
            self.stats.inc("frames_dropped")
            return
        except Exception as exc:  # noqa: BLE001
            self._account_invoke_error(exc)
            return
        if self._breaker is not None:
            self._breaker.record_success()
        self._note_recompiles(c0)
        # synchronous path: dispatch and completion are the same event
        dt = time.perf_counter_ns() - t0
        self._record_dispatch(dt)
        self._record_latency(dt)
        if self._watchdog is not None:
            self._watchdog.feed()
        outputs = self._trim_padded_rows(buf, outputs)
        if self.prefetch_host:
            # enqueue on the coalescing fetch service: the frame leaves
            # this element immediately carrying PendingHost handles, and
            # every frame queued while a fetch RPC is in flight shares
            # the next one. (copy_to_host_async does NOT hide the tunnel
            # RTT — measured worse than a plain blocking fetch.)
            outputs = submit_fetch(outputs)
        out_chunks = self._combine_outputs(buf, outputs)
        self.push(buf.with_chunks(out_chunks))

    # -- in-flight window (overlapped execution) ---------------------------
    def _dispatch_windowed(self, buf: Buffer, inputs: List[Any]) -> None:
        """DISPATCHER side of the overlap split: take a window slot
        (blocking here IS the backpressure — it propagates into the
        upstream queue exactly like a slow synchronous invoke), enqueue
        the device program, and hand completion to the completer
        thread. The chain thread never waits on the device."""
        t_disp = self._overlap.window.acquire()
        t0 = time.perf_counter_ns()
        c0 = getattr(self.fw, "compile_count", 0)
        try:
            handle = self.fw.dispatch(inputs,
                                      donate=bool(self.donate_input))
        except InvokeDrop:
            # release FIRST: the accounting below must not be able to
            # strand the slot (the completer never sees this frame)
            self._overlap.window.release(t_disp)
            if self._breaker is not None:
                self._breaker.record_success()
            self.stats.inc("frames_dropped")
            return
        except Exception as exc:  # noqa: BLE001
            self._overlap.window.release(t_disp)
            self._account_invoke_error(exc)
            self._settle_failed_rows(buf)
            return
        try:
            self._note_recompiles(c0)
            self._record_dispatch(time.perf_counter_ns() - t0)
            self._overlap.submit(buf, handle, t_disp)
        except BaseException:
            # a dispatch-side failure after acquire: the slot would
            # otherwise leak window depth permanently
            self._overlap.window.release(t_disp)
            raise

    def _complete_frame(self, entry) -> Buffer:
        """COMPLETER side: materialize one frame's results and run the
        per-frame accounting the sync path does inline. Raises on invoke
        failure — the executor routes that to :meth:`_complete_error`."""
        outputs = self.fw.complete(entry.payload)
        if self._breaker is not None:
            self._breaker.record_success()
        self._record_latency(time.perf_counter_ns() - entry.t_dispatch_ns)
        if self._watchdog is not None:
            self._watchdog.feed()
        buf = entry.buf
        outputs = self._trim_padded_rows(buf, outputs)
        if self.prefetch_host:
            outputs = submit_fetch(outputs)
        return buf.with_chunks(self._combine_outputs(buf, outputs))

    def _complete_error(self, entry, exc: BaseException) -> None:
        """A frame that failed at completion: same per-frame accounting
        as a sync invoke failure (invoke_errors / frames_dropped /
        breaker), even though the chain thread returned long ago."""
        self._account_invoke_error(exc)
        self._settle_failed_rows(entry.buf)

    def _settle_failed_rows(self, buf: Buffer) -> None:
        """Serve-batch rows of a failed frame get their on_shed callback
        (wire-level SHED + retry-after) instead of silently timing out
        at the client's deadline. Accounted under frames_dropped — not
        ``shed``, which counts breaker-open rejections."""
        rows = buf.extras.get("serve_rows")
        if not rows:
            return
        for req in rows:
            if req.on_shed is not None:
                try:
                    req.on_shed(req)
                except Exception:  # noqa: BLE001 — one dead client
                    logger.warning("%s: shed callback failed for "
                                   "stream %s", self.name,
                                   req.stream_id, exc_info=True)
        self._record_shed_failed(buf, len(rows))

    @staticmethod
    def _record_shed_failed(buf: Buffer, n: int) -> None:
        """Report rows settled by the filter's failure paths back to the
        scheduler: they left its batcher as ``submitted`` but no demuxed
        result ever returns, so without this terminal the serve
        settlement identity (requests == completed + shed_deadline +
        cancelled + shed_failed + pending) cannot balance."""
        sched = buf.extras.get("serve_sched")
        if sched is not None:
            sched.record_shed_failed(n)

    def _account_invoke_error(self, exc: BaseException) -> None:
        # invoke failure drops THIS frame but keeps the pipeline alive
        # (≙ tensor_filter.c:961-963); the error is surfaced on the
        # bus as a warning with an error counter, not a fatal error.
        # Warnings are rate-limited (1, 2, 4, 8, ... then every 64th)
        # so a permanently broken model can't flood an unread bus, and
        # carry the message string only — holding the exception object
        # would pin the traceback (and the input tensors) in memory.
        n = self.stats.inc("invoke_errors")
        self.stats.inc("frames_dropped")
        if self._breaker is not None:
            self._breaker.record_failure()
        logger.warning("%s: invoke failed (frame dropped, pipeline "
                       "kept): %s", self.name, exc)
        if n & (n - 1) == 0 or n % 64 == 0:
            self.post_message("warning", error=str(exc),
                              invoke_errors=n,
                              remedy="check the model's input "
                                     "dims/dtypes against the "
                                     "negotiated caps, or the "
                                     "subplugin's own logs")

    @staticmethod
    def _trim_padded_rows(buf: Buffer, outputs: List[Any]) -> List[Any]:
        nv = buf.extras.get("batch_valid_rows")
        if nv is None or not buf.chunks:
            return outputs
        # micro-batched upstream (e.g. query serversrc batch=K) padded
        # the stack to a fixed compile signature; drop padded rows of
        # HOST outputs (a free numpy view). Only outputs whose leading
        # dim IS the padded batch axis are touched — anything else
        # (flat vectors, [N,7] detection tables) passes through.
        # Device outputs ship padded: on the tunneled dev chip every
        # eager device op is an RPC costing more than the padded D2H
        # bytes save (measured: ~25% aggregate fan-out fps).
        pad = buf.chunks[0].shape[0] if buf.chunks[0].shape else None
        return [o[:nv] if isinstance(o, np.ndarray)
                and o.ndim >= 1 and pad is not None
                and o.shape[0] == pad and pad > nv else o
                for o in outputs]

    def transfer_report(self) -> dict:
        """Window occupancy / overlap stats for trace.report()'s
        ``transfer`` block; {} when running synchronously."""
        return self._overlap.report() if self._overlap is not None else {}

    # -- circuit breaker ---------------------------------------------------
    def _shed_frame(self, buf: Buffer) -> None:
        """Answer a frame while the breaker is open: serve-batch rows
        get their on_shed callback (the wire-level SHED + retry-after
        reply), and upstream gets a QosEvent spaced by the retry-after
        hint so sources stop producing doomed frames."""
        self.stats.inc("shed")
        self.stats.inc("dropped")
        _obs_events.emit("shed", source=self.name, element=self,
                         reason="breaker-open", pts=buf.pts)
        retry_after_ms = float(self.breaker_retry_after_ms)
        rows = buf.extras.get("serve_rows")
        if rows:
            for req in rows:
                if req.on_shed is not None:
                    try:
                        req.on_shed(req)
                    except Exception:  # noqa: BLE001 — one dead client
                        logger.warning("%s: shed callback failed for "
                                       "stream %s", self.name,
                                       req.stream_id, exc_info=True)
            self._record_shed_failed(buf, len(rows))
        self.send_upstream_event(QosEvent(
            proportion=2.0, period_ns=int(retry_after_ms * 1e6),
            timestamp=buf.pts))

    def _on_breaker_transition(self, old: str, new: str) -> None:
        from ..fault.breaker import OPEN
        if new == OPEN:
            self.stats.inc("breaker_opened")
        logger.warning("%s: circuit breaker %s -> %s", self.name, old, new)
        _obs_events.emit("breaker", source=self.name, element=self,
                         old=old, new=new)
        self.post_message("warning", breaker=new, breaker_from=old,
                          invoke_errors=self.stats["invoke_errors"],
                          retry_after_ms=float(self.breaker_retry_after_ms))

    # -- QoS throttling ----------------------------------------------------
    def handle_event(self, pad: Pad, event: Event) -> None:
        from ..pipeline.events import FlushEvent, SegmentEvent
        if self._overlap is not None:
            # serialized events (EOS, caps, segment) must not overtake
            # in-flight frames: barrier until the completer has settled
            # and pushed everything dispatched before this event
            self._overlap.flush()
        if isinstance(event, (SegmentEvent, FlushEvent)):
            # new segment / flush = PTS discontinuity: stale throttle state
            # would otherwise qos-drop every post-restart frame forever
            self._throttle_period_ns = 0
            self._next_accept_ts = None
        super().handle_event(pad, event)

    def _qos_should_drop(self, buf: Buffer) -> bool:
        if self._throttle_period_ns <= 0 or buf.pts is None:
            return False
        if self._next_accept_ts is not None and buf.pts < self._next_accept_ts:
            return True
        self._next_accept_ts = buf.pts + self._throttle_period_ns
        return False

    def handle_upstream_event(self, pad: Pad, event: Event) -> None:
        if isinstance(event, QosEvent):
            # keep the larger of the downstream-requested spacing and our
            # own sustainable cadence. Synchronously that cadence is the
            # invoke latency; under a K-frame window K completions are in
            # flight at once, so the sustainable period is latency/K —
            # throttling to full completion latency would forfeit the
            # overlap the window exists to win.
            window = self._overlap.window.limit \
                if self._overlap is not None else 1
            lat_ns = int(self.latency_average_us() * 1e3) // max(1, window)
            self._throttle_period_ns = max(event.period_ns, lat_ns) \
                if event.proportion > 1.0 else 0
            if self._throttle_period_ns == 0:
                self._next_accept_ts = None
            return  # consumed: the filter is the throttling point
        super().handle_upstream_event(pad, event)

    def _combine_outputs(self, inbuf: Buffer, outputs: List[Any]) -> List[Chunk]:
        if not self._out_combi:
            return [Chunk(o) for o in outputs]
        # output-combination: "i0,o1" mixes input passthrough and outputs
        # (≙ out-combination, tensor_filter.c:972-1076)
        chunks = []
        for tok in self._out_combi:
            kind, idx = tok[0], int(tok[1:])
            chunks.append(inbuf.chunks[idx] if kind == "i" else Chunk(outputs[idx]))
        return chunks

    def _dispatch_async(self, outputs: List[Any],
                        ctx: Optional[Buffer] = None) -> None:
        """Called by the backend once per generated output frame
        (≙ gst_tensor_filter_async_output_callback, tensor_filter.c:1099).
        ``ctx`` is the input buffer passed at invoke time — with two
        prompts in flight each token frame is stamped from its OWN
        prompt, not whichever arrived last."""
        template = ctx if ctx is not None \
            else getattr(self, "_async_template", None)
        buf = Buffer([Chunk(o) for o in outputs],
                     pts=template.pts if template else None)
        self.push(buf)

    # -- stats ------------------------------------------------------------
    def _note_recompiles(self, c0: int) -> None:
        """Frame-path compilations: the backend's jit cache missed
        DURING a frame invoke/dispatch (warmup and cache prewarm don't
        route through here, so they never count). A warmed process must
        hold this at zero — `make jit-stability` pins it, and
        /metrics exports it as nns_jit_recompiles_total."""
        d = getattr(self.fw, "compile_count", 0) - c0
        if d > 0:
            self.stats.add(jit_recompiles=d)

    def _record_latency(self, dt_ns: int) -> None:
        """Record one frame's dispatch-to-COMPLETION latency. Sync path:
        chain thread; windowed path: completer thread — every mutation
        sits under _stats_lock, and the bus post happens outside it
        (posting is I/O; never under a leaf lock)."""
        report_us = None
        with self._stats_lock:
            self._invoke_count += 1
            self._total_latency_ns += dt_ns
            self._recent_latency.append(dt_ns)
            if self.latency:
                est_us = (sum(self._recent_latency)
                          / len(self._recent_latency) / 1e3)
                self.latency_us = est_us
                # re-report when the rolling estimate drifts past the 5%
                # headroom or improves by more than 25%
                # (≙ tensor_filter.c:490-527 re-reporting thresholds)
                rep = self._reported_latency_us
                if rep is None or est_us > rep * _LATENCY_REPORT_HEADROOM \
                        or est_us < rep * _LATENCY_IMPROVE_THRESHOLD:
                    self._reported_latency_us = est_us
                    report_us = est_us
        if report_us is not None:
            self.post_message("latency", latency_us=report_us)

    def _record_dispatch(self, dt_ns: int) -> None:
        """Record one frame's dispatch-to-RETURN time (the chain-thread
        cost). Synchronously it equals the completion latency; under a
        window it is near-zero — surfacing both is what makes the
        overlap visible instead of silently misreported."""
        with self._stats_lock:
            self._dispatch_count += 1
            self._total_dispatch_ns += dt_ns
            self._recent_dispatch.append(dt_ns)

    def latency_average_us(self) -> float:
        """Rolling dispatch-to-completion average over the last 10
        frames, µs (≙ latency property, tensor_filter.c:408-448)."""
        with self._stats_lock:
            if not self._recent_latency:
                return 0.0
            return (sum(self._recent_latency)
                    / len(self._recent_latency) / 1e3)

    def dispatch_average_us(self) -> float:
        """Rolling dispatch-to-return average over the last 10 frames,
        µs — the chain-thread cost per frame under the window."""
        with self._stats_lock:
            if not self._recent_dispatch:
                return 0.0
            return (sum(self._recent_dispatch)
                    / len(self._recent_dispatch) / 1e3)

    def throughput_fps(self) -> float:
        """Invokes/sec since start (≙ throughput prop, tensor_filter.c:452)."""
        if self._start_time is None or self._invoke_count == 0:
            return 0.0
        dt = time.monotonic() - self._start_time
        return self._invoke_count / dt if dt > 0 else 0.0

    # -- suspend ----------------------------------------------------------
    def _on_idle(self) -> None:
        if self.fw is not None:
            logger.info("%s: idle %dms, suspending model", self.name, self.suspend)
            self.fw.handle_event(FilterEvent.SUSPEND)

    def reload_model(self, model: Optional[str] = None) -> bool:
        """Hot-swap the model (≙ RELOAD_MODEL / is-updatable path)."""
        if model:
            self.model = model
        data = {"model_files": tuple(self.model.split(","))} if model else None
        return self.fw.handle_event(FilterEvent.RELOAD_MODEL, data)
