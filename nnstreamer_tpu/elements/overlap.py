"""K-frame in-flight invoke window: dispatcher/completer split.

The synchronous chain path pays RTT + H2D + invoke + D2H serially per
frame, so a remote-attached chip caps the pipeline at ~1/RTT fps no
matter how fast the model runs. JAX dispatch is already asynchronous —
the fix is to stop blocking the chain thread on completion:

  * the **dispatcher** (the element's chain thread) acquires a slot in
    the per-link :class:`~..tensors.transfer.InFlightWindow` (blocking
    = backpressure into the upstream queue), dispatches the frame's
    device program, and hands the in-flight entry to the executor;
  * the **completer** (one daemon thread per element) materializes each
    frame's results in dispatch order, runs the element's completion
    callback (latency/breaker/watchdog accounting + downstream
    ``push``), and releases the window slot.

Ordering: the completer consumes the FIFO in dispatch order, so
completions are in-order by construction; the :class:`ReorderBuffer` it
feeds enforces the PTS contract anyway — it restores order if driven
out of order, advances past error gaps, and gives up on a missing frame
only after a bounded stall deadline (so one wedged completion cannot
dam the pipeline forever). PTS regressions at the release point are
counted, never silently passed through.

Error accounting under overlap: a frame that fails at completion is
settled by the element's error callback on the completer thread —
breaker failure, ``invoke_errors``, serve-row shedding — so the
zero-loss identity (frames in == pushed + dropped + shed) holds
per-frame even though the chain thread returned long ago.

Concurrency (racecheck: DISPATCHER submits, COMPLETER drains): every
mutable field is written only under ``_cv``; completion callbacks and
window release run outside it so the lock never covers a blocking
device wait.
"""
from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs import context as _obs_ctx
from ..obs import spans as _obs_spans
from ..tensors.transfer import InFlightWindow

log = logging.getLogger(__name__)

# sentinel for a sequence number that completed with no frame to emit
# (error path): the reorder buffer advances past it without releasing
_SKIP = object()


class _InFlight:
    """One dispatched frame awaiting completion."""

    __slots__ = ("seq", "buf", "payload", "t_dispatch_ns")

    def __init__(self, seq: int, buf, payload, t_dispatch_ns: int):
        self.seq = seq
        self.buf = buf
        self.payload = payload          # framework dispatch handle
        self.t_dispatch_ns = t_dispatch_ns


class ReorderBuffer:
    """Bounded PTS-order restorer with a stall deadline.

    Single-threaded by contract: only the completer touches it (the
    unit tests drive it directly, out of order, to pin the semantics).
    ``push``/``skip`` return the frames that became releasable, already
    in sequence order; ``poll`` handles the pathological case where a
    sequence number never arrives at all — after ``deadline_s`` of
    head-of-line blocking it abandons the missing frame (counted in
    ``stalls``) and releases what it holds.
    """

    def __init__(self, deadline_s: float = 1.0):
        self.deadline_s = max(0.0, float(deadline_s))
        self._next = 0                   # next seq eligible for release
        self._held: Dict[int, Tuple[Any, float]] = {}
        self._last_pts: Optional[int] = None
        self.released = 0
        self.skipped = 0
        self.stalls = 0
        self.pts_regressions = 0

    def __len__(self) -> int:
        return len(self._held)

    def push(self, seq: int, item: Any, now: Optional[float] = None
             ) -> List[Any]:
        self._held[seq] = (item, time.monotonic() if now is None else now)
        return self._drain()

    def skip(self, seq: int, now: Optional[float] = None) -> List[Any]:
        """Mark ``seq`` settled with nothing to emit (errored/dropped
        frame): later frames must not wait for it."""
        self._held[seq] = (_SKIP, time.monotonic() if now is None else now)
        return self._drain()

    def poll(self, now: Optional[float] = None) -> List[Any]:
        """Stall-deadline escape hatch: if the head-of-line seq is
        missing and the oldest held frame has waited past the deadline,
        abandon the gap and release from the oldest held seq on."""
        if not self._held or self._next in self._held:
            return self._drain()
        now = time.monotonic() if now is None else now
        oldest = min(self._held)
        if now - self._held[oldest][1] < self.deadline_s:
            return []
        self.stalls += 1
        log.warning("reorder stall: seq %d..%d never completed; "
                    "advancing past the gap", self._next, oldest - 1)
        self._next = oldest
        return self._drain()

    def flush(self) -> List[Any]:
        """Release everything held, in sequence order, gaps or not."""
        out: List[Any] = []
        for seq in sorted(self._held):
            if seq > self._next:
                self.stalls += 1
            item, _ = self._held.pop(seq)
            self._next = seq + 1
            if item is not _SKIP:
                out.append(self._release(item))
        return out

    def _drain(self) -> List[Any]:
        out: List[Any] = []
        while self._next in self._held:
            item, _ = self._held.pop(self._next)
            self._next += 1
            if item is _SKIP:
                self.skipped += 1
            else:
                out.append(self._release(item))
        return out

    def _release(self, item: Any) -> Any:
        pts = getattr(item, "pts", None)
        if pts is not None and self._last_pts is not None \
                and pts < self._last_pts:
            self.pts_regressions += 1
        if pts is not None:
            self._last_pts = pts
        self.released += 1
        return item


class OverlapExecutor:
    """The per-element dispatcher/completer pair around a window.

    ``submit`` runs on the element's chain thread (DISPATCHER role) and
    blocks only when the window is full; ``_complete_loop`` runs on a
    dedicated daemon thread (COMPLETER role), settles frames in FIFO
    order through ``complete_cb`` (success → buffer to push) or
    ``error_cb`` (frame accounted dropped), pushes releasable frames
    downstream via ``push_cb``, and frees the window slot.
    """

    def __init__(self, limit: int,
                 complete_cb: Callable[[_InFlight], Any],
                 error_cb: Callable[[_InFlight, BaseException], None],
                 push_cb: Callable[[Any], None],
                 name: str = "overlap",
                 reorder: bool = True,
                 reorder_deadline_s: float = 1.0,
                 devices: int = 1):
        # the window budget is per-MESH, not per-chip: one dispatched
        # frame occupies one slot even when its sharded program spans
        # ``devices`` chips (a sharded invoke is still a single XLA
        # dispatch with a single completion)
        self.window = InFlightWindow(limit, devices=devices)
        self._complete_cb = complete_cb
        self._error_cb = error_cb
        self._push_cb = push_cb
        self._name = name
        # completer-thread-only state: the FIFO entries move to the
        # reorder buffer under the completer role alone, so it needs no
        # lock of its own (pinned by the runtime lock validator test)
        self._reorder = ReorderBuffer(reorder_deadline_s) if reorder \
            else None
        self._cv = threading.Condition()
        self._q: "deque[_InFlight]" = deque()
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        self._seq = 0
        self._completed = 0
        self._errors = 0
        self._push_errors = 0

    # ---- dispatcher side (chain thread) --------------------------------

    def submit(self, buf, payload, t_dispatch_ns: int) -> None:
        """Hand a dispatched frame to the completer. The caller must
        already hold a window slot (``window.acquire()``) — the element
        acquires BEFORE dispatching so backpressure lands before device
        work is queued, and passes the returned timestamp here."""
        if _obs_spans.ENABLED:
            # harness stubs may hand the executor bare objects; only
            # real Buffers carry the extras dict a context rides in
            extras = getattr(buf, "extras", None)
            ctx = extras.get(_obs_ctx.CTX_KEY) if extras is not None \
                else None
            if ctx is not None:
                _obs_spans.record_span(f"{self._name}:dispatch", "dispatch",
                                       time.time_ns(), 0, ctx)
        with self._cv:
            self._ensure_thread()
            entry = _InFlight(self._seq, buf, payload, t_dispatch_ns)
            self._seq += 1
            self._q.append(entry)
            self._cv.notify_all()

    def flush(self, timeout: float = 30.0) -> bool:
        """Barrier: wait until every submitted frame has been settled
        and pushed. Events and EOS must not overtake in-flight frames —
        the element calls this before forwarding any serialized event."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._q:
                left = deadline - time.monotonic()
                if left <= 0 or not self._cv.wait(min(left, 1.0)):
                    if deadline - time.monotonic() <= 0:
                        log.warning("%s: flush timed out with %d frames "
                                    "queued", self._name, len(self._q))
                        return False
        ok = self.window.wait_idle(max(0.0, deadline - time.monotonic()))
        if not ok:
            log.warning("%s: flush timed out waiting for window idle",
                        self._name)
        return ok

    def stop(self) -> None:
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=10.0)

    # ---- completer side ------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stopping = False
            self._thread = threading.Thread(
                target=self._complete_loop,
                name=f"nns-complete-{self._name}", daemon=True)
            self._thread.start()

    def _complete_loop(self) -> None:
        while True:
            with self._cv:
                while not self._q and not self._stopping:
                    self._cv.wait(0.25)
                if not self._q:
                    if self._stopping:
                        return
                    continue
                entry = self._q.popleft()  # flow: owns(window-slot)
            # settle the frame OUTSIDE the lock: completion is a device
            # wait (racecheck: blocking call must not run under _cv)
            n_err = 0
            n_push_err = 0
            try:
                outbuf: Any = None
                err: Optional[BaseException] = None
                t_wall = time.time_ns() if _obs_spans.ENABLED else 0
                try:
                    outbuf = self._complete_cb(entry)
                except BaseException as exc:  # noqa: BLE001 — accounted
                    err = exc
                if t_wall:
                    extras = getattr(entry.buf, "extras", None)
                    ctx = extras.get(_obs_ctx.CTX_KEY) \
                        if extras is not None else None
                    if ctx is not None:
                        dur = time.time_ns() - t_wall
                        _obs_spans.record_span(f"{self._name}:complete",
                                               "complete", t_wall, dur,
                                               ctx)
                        ctx.c_ns += dur
                if err is None:
                    ready = ([outbuf] if self._reorder is None
                             else self._reorder.push(entry.seq, outbuf))
                else:
                    try:
                        self._error_cb(entry, err)
                    except Exception:  # noqa: BLE001 — never kill loop
                        log.exception("%s: error callback failed",
                                      self._name)
                    ready = ([] if self._reorder is None
                             else self._reorder.skip(entry.seq))
                if self._reorder is not None:
                    ready.extend(self._reorder.poll())
                n_err = 1 if err is not None else 0
                for out in ready:
                    try:
                        self._push_cb(out)
                    except Exception:  # noqa: BLE001 — downstream
                        # failure must not wedge the window: count and
                        # keep going
                        n_push_err += 1
                        log.exception("%s: downstream push failed for a "
                                      "completed frame", self._name)
            finally:
                # release in a finally: if the reorder buffer or an
                # error callback raises, a skipped release would strand
                # the slot and permanently shrink the window (the next
                # submit restarts the thread, but the depth is gone)
                self.window.release(entry.t_dispatch_ns)
            with self._cv:
                self._completed += 1 - n_err
                self._errors += n_err
                self._push_errors += n_push_err
                self._cv.notify_all()

    # ---- reporting -----------------------------------------------------

    def report(self) -> Dict[str, Any]:
        out = self.window.report()
        with self._cv:
            out.update(completed=self._completed, errors=self._errors,
                       queued=len(self._q))
            if self._push_errors:
                out["push_errors"] = self._push_errors
        rb = self._reorder
        if rb is not None:
            out["reorder"] = {"released": rb.released,
                              "skipped": rb.skipped,
                              "stalls": rb.stalls,
                              "pts_regressions": rb.pts_regressions,
                              "held": len(rb)}
        return out
