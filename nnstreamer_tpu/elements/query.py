"""tensor_query_client / tensor_query_serversrc / tensor_query_serversink
— remote-filter (RPC) stream offload.

≙ gst/nnstreamer/tensor_query/*: a client pipeline sends frames to a
server pipeline and receives results (tensor_query_client.c:676-712 send
path, :428-510 receive path); server entry/exit pads pair up through a
shared table keyed by ``id`` so answers return to the asking client
(tensor_query_server.c). Transport is the edge protocol (edge/protocol.py)
over TCP/DCN; caps are exchanged at connect like the reference's
edge-handle info "CAPS" (:537-562).
"""
from __future__ import annotations

import collections
import socket
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from ..edge import wire
from ..edge.protocol import MsgKind, recv_msg, send_msg, sever_socket as _sever
from ..pipeline.element import Element, SinkElement, SrcElement
from ..pipeline.events import QosEvent
from ..pipeline.pad import Pad
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..utils.log import logger


def _roi_meta(buf: Buffer) -> Optional[dict]:
    """The tensor_delta ROI side-band (which crops these are, cut from
    what) as a wire-meta block: buffer extras don't cross the link, so
    the client stamps this next to ``seq`` on DATA and the server
    echoes it on RESULT for the downstream tensor_delta_stitch."""
    rois = buf.extras.get("delta_rois")
    if rois is None:
        return None
    return {"rois": [list(r) for r in rois],
            "grid": list(buf.extras.get("delta_grid", ())),
            "tile": int(buf.extras.get("delta_tile", 0)),
            "shape": list(buf.extras.get("delta_shape", ()))}


def _roi_adopt(buf: Buffer, roi: Optional[dict]) -> Buffer:
    """Inverse of :func:`_roi_meta`: rebuild the stitch extras on a
    RESULT buffer from the echoed block."""
    if roi and roi.get("rois"):
        buf.extras["delta_rois"] = [tuple(r) for r in roi["rois"]]
        buf.extras["delta_grid"] = tuple(roi.get("grid", ()))
        buf.extras["delta_tile"] = int(roi.get("tile", 0))
        buf.extras["delta_shape"] = tuple(roi.get("shape", ()))
    return buf


class _ServerTable:
    """Pairs serversrc/serversink by id and routes client connections
    (≙ GstTensorQueryServerInfo table, tensor_query_server.c)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._conns: Dict[Tuple[int, int], socket.socket] = {}
        self._wire: Dict[Tuple[int, int], wire.WireConfig] = {}
        self._out_caps: Dict[int, str] = {}

    def add_conn(self, server_id: int, client_id: int,
                 sock: socket.socket) -> None:
        with self._lock:
            self._conns[(server_id, client_id)] = sock

    def remove_conn(self, server_id: int, client_id: int) -> None:
        with self._lock:
            self._conns.pop((server_id, client_id), None)
            self._wire.pop((server_id, client_id), None)

    def get_conn(self, server_id: int, client_id: int):
        with self._lock:
            return self._conns.get((server_id, client_id))

    def set_wire(self, server_id: int, client_id: int,
                 cfg: Optional[wire.WireConfig]) -> None:
        """Record the link config negotiated at the client's CAPS
        exchange; the serversink packs each RESULT under it."""
        with self._lock:
            if cfg is None:
                self._wire.pop((server_id, client_id), None)
            else:
                self._wire[(server_id, client_id)] = cfg

    def get_wire(self, server_id: int, client_id: int
                 ) -> Optional[wire.WireConfig]:
        with self._lock:
            return self._wire.get((server_id, client_id))

    def set_out_caps(self, server_id: int, caps: str) -> None:
        with self._lock:
            self._out_caps[server_id] = caps

    def get_out_caps(self, server_id: int) -> Optional[str]:
        with self._lock:
            return self._out_caps.get(server_id)

    def conns_of(self, server_id: int) -> list:
        """Live client sockets of one server (drain notification)."""
        with self._lock:
            return [s for k, s in self._conns.items() if k[0] == server_id]

    def close_server(self, server_id: int) -> None:
        """Close every client connection of a stopping server so clients
        see the death immediately and can fail over."""
        with self._lock:
            victims = [(k, s) for k, s in self._conns.items()
                       if k[0] == server_id]
            for k, _ in victims:
                del self._conns[k]
                self._wire.pop(k, None)
        for _, s in victims:
            _sever(s)


SERVER_TABLE = _ServerTable()
_FLEX_CAPS = "other/tensors,format=flexible"


@register_element("tensor_query_serversrc")
class TensorQueryServerSrc(SrcElement):
    """Server entry: listens for clients, pushes received frames into the
    server pipeline with the client id stamped in buffer extras."""

    PROPS = {"host": "localhost", "port": 3001, "id": 0, "timeout": 10.0,
             # HYBRID: advertise (topic -> host:port) on the discovery
             # broker at dest-host:dest-port (≙ connect-type enum,
             # tensor_query_common.c:30-40)
             "connect-type": "TCP", "topic": "",
             "dest-host": "localhost", "dest-port": 0,
             # batch>1 = server-side micro-batching: stack up to `batch`
             # in-flight frames (across ALL clients) into one buffer with
             # a leading batch dim, padded to a fixed size so the filter
             # compiles ONE executable; the serversink demuxes rows back
             # to their clients. BASELINE config 5's "batched invoke over
             # ICI": the MXU amortizes the dispatch, one D2H ships every
             # client's result.
             "batch": 0}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._listener: Optional[socket.socket] = None
        self._queue = []
        self._qlock = threading.Condition()
        self._next_client = [0]
        self._accept_thread: Optional[threading.Thread] = None
        self._broker_sock: Optional[socket.socket] = None
        self.stats["link_errors"] = 0

    @property
    def bound_port(self) -> int:
        return self._listener.getsockname()[1] if self._listener else self.port

    def negotiate_src_caps(self) -> Optional[Caps]:
        return Caps(_FLEX_CAPS)

    def static_src_caps(self) -> Optional[Caps]:
        """Flexible tensors (shapes arrive per request)."""
        return Caps(_FLEX_CAPS)

    def start(self) -> None:
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self._listener.listen(16)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"qsrc-accept:{self.name}",
            daemon=True)
        self._accept_thread.start()
        if self.connect_type.upper() == "HYBRID":
            # hold the registration connection open for our lifetime;
            # the broker drops the advertisement the moment it closes
            try:
                self._broker_sock = socket.create_connection(
                    (self.dest_host or "localhost", int(self.dest_port)),
                    timeout=self.timeout)
                send_msg(self._broker_sock, MsgKind.REGISTER,
                         {"topic": self.topic, "host": self.host,
                          "port": self.bound_port})
            except OSError:
                # don't leak a half-started server: closing the listener
                # also terminates the accept thread
                if self._broker_sock is not None:
                    try:
                        self._broker_sock.close()
                    except OSError:
                        pass
                    self._broker_sock = None
                try:
                    self._listener.close()
                except OSError:
                    pass
                self._listener = None
                raise
        super().start()

    def stop(self) -> None:
        super().stop()
        if self._broker_sock is not None:
            try:
                self._broker_sock.close()
            except OSError:
                pass
            self._broker_sock = None
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        # drop live client connections so clients detect the death at
        # once and fail over instead of timing out on a silent socket
        SERVER_TABLE.close_server(self.id)

    def _accept_loop(self) -> None:
        while not self._stop_evt.is_set():
            try:
                conn, addr = self._listener.accept()
            except OSError:
                return
            try:
                wire.tune_socket(conn)
            except OSError:
                # peer died between accept and setsockopt: close the
                # fd instead of leaking it
                conn.close()
                continue
            cid = self._next_client[0]
            self._next_client[0] += 1
            SERVER_TABLE.add_conn(self.id, cid, conn)
            threading.Thread(target=self._client_loop, args=(conn, cid),
                             name=f"qsrc-client{cid}:{self.name}",
                             daemon=True).start()

    def _client_loop(self, conn: socket.socket, cid: int) -> None:
        # per-op timeout: a half-open peer (died without FIN) must not
        # hold its recv thread — and its queued frames — forever; a
        # live-but-idle client just times out between messages and loops
        conn.settimeout(max(0.1, float(self.timeout)))
        try:
            while not self._stop_evt.is_set():
                try:
                    kind, meta, payloads = recv_msg(conn, stats=self.stats)
                except TimeoutError:
                    continue
                if kind == MsgKind.CAPS:
                    # wire v2: fold the client's advertisement into this
                    # link's config and echo the choice in the ack; a
                    # client without a "wire" block stays plain v1
                    cfg = wire.negotiate(meta.get("wire"))
                    SERVER_TABLE.set_wire(self.id, cid, cfg)
                    out_caps = SERVER_TABLE.get_out_caps(self.id) or _FLEX_CAPS
                    ack = {"caps": out_caps, "client_id": cid}
                    if cfg is not None:
                        ack["wire"] = cfg.to_meta()
                    send_msg(conn, MsgKind.CAPS_ACK, ack)
                elif kind == MsgKind.DATA:
                    self._enqueue(wire.unpack_buffer(meta, payloads,
                                                     stats=self.stats), cid)
                elif kind == MsgKind.DATA_BATCH:
                    for b in wire.unpack_batch(meta, payloads,
                                               stats=self.stats):
                        self._enqueue(b, cid)
                elif kind == MsgKind.EOS:
                    break
        except (ConnectionError, OSError, ValueError) as exc:
            # a dying client is routine, but never silent: the cause is
            # logged and counted so a flapping link is diagnosable from
            # stats() instead of invisible
            self.stats.inc("link_errors")
            logger.info("%s: client %d connection ended: %r",
                        self.name, cid, exc)
        finally:
            SERVER_TABLE.remove_conn(self.id, cid)
            # slot reclamation: frames this client queued but the
            # pipeline has not consumed would otherwise be invoked for a
            # dead peer (and their replies dropped at the sink)
            with self._qlock:
                self._queue = [b for b in self._queue
                               if b.extras.get("client_id") != cid]
            try:
                conn.close()
            except OSError:
                pass

    def drain(self) -> None:
        """Graceful teardown: stop admitting frames (late arrivals are
        shed + counted), tell every client DRAIN so it stops sending,
        and flush the queue through the pipeline behind the EOS barrier
        — every queued frame still gets its RESULT before close."""
        super().drain()
        for conn in SERVER_TABLE.conns_of(self.id):
            try:
                send_msg(conn, MsgKind.DRAIN, {"server_id": self.id})
            except (ConnectionError, OSError):
                pass
        with self._qlock:
            self._qlock.notify_all()

    def drain_flushed(self) -> bool:
        with self._qlock:
            return not self._queue

    def kill_link(self) -> int:
        """Chaos hook (tensor_fault mode=kill-link): force-close every
        live client connection mid-stream; clients reconnect and replay
        their unanswered frames."""
        victims = len(SERVER_TABLE.conns_of(self.id))
        SERVER_TABLE.close_server(self.id)
        self.stats.inc("link_kills", victims)
        return victims

    def _enqueue(self, buf: Buffer, cid: int) -> None:
        if self._drain_evt.is_set():
            # admission is closed: the frame is shed, visibly — the
            # client's pending entry settles via its own teardown path
            self.stats.inc("shed")
            return
        buf.extras["client_id"] = cid
        buf.extras["server_id"] = self.id
        with self._qlock:
            self._queue.append(buf)
            self._qlock.notify_all()

    def create(self) -> Optional[Buffer]:
        with self._qlock:
            while not self._queue:
                if self._stop_evt.is_set():
                    return None
                if self._drain_evt.is_set():
                    return None  # drained dry: the EOS barrier
                self._qlock.wait(timeout=0.1)
            k = int(self.batch)
            if k <= 1:
                return self._queue.pop(0)
            bufs = [self._queue.pop(0)]
            # stop at a shape mismatch: heterogeneous clients still work,
            # the mismatching frame just opens the next micro-batch
            while (self._queue and len(bufs) < k
                   and self._stackable(bufs[0], self._queue[0])):
                bufs.append(self._queue.pop(0))
        return self._stack(bufs, k)

    @staticmethod
    def _stackable(a: Buffer, b: Buffer) -> bool:
        return (len(a.chunks) == len(b.chunks)
                and all(x.shape == y.shape and x.dtype == y.dtype
                        for x, y in zip(a.chunks, b.chunks)))

    def _stack(self, bufs, k: int) -> Buffer:
        """Stack frames into one leading-dim-``k`` buffer (short batches
        pad by repeating the last frame — one compiled signature, and on
        the MXU a padded row is nearly free next to a second dispatch).
        ``batch_rows`` extras carry each real row's reply route."""
        rows = bufs + [bufs[-1]] * (k - len(bufs))
        chunks = []
        for j in range(len(bufs[0].chunks)):
            chunks.append(Chunk(np.stack([b.chunks[j].host()
                                          for b in rows])))
        out = Buffer(chunks, pts=bufs[0].pts)
        out.extras["server_id"] = self.id
        out.extras["batch_rows"] = [
            (b.extras.get("client_id"), b.extras.get("server_id", self.id),
             b.pts) for b in bufs]
        # downstream device elements slice padded rows off BEFORE any
        # D2H (tensor_filter honors this) — the tunnel's device->host
        # link is the scarce resource, don't spend it on padding
        out.extras["batch_valid_rows"] = len(bufs)
        return out


@register_element("tensor_query_serversink")
class TensorQueryServerSink(SinkElement):
    """Server exit: returns results to the client that asked."""

    PROPS = {"id": 0, "timeout": 10.0}

    def on_sink_caps(self, pad: Pad, caps: Caps) -> None:
        SERVER_TABLE.set_out_caps(self.id, str(caps))

    def handle_event(self, pad, event) -> None:
        from ..pipeline.events import CapsEvent
        if isinstance(event, CapsEvent):
            pad.set_caps(event.caps)
            self.on_sink_caps(pad, event.caps)
            return
        super().handle_event(pad, event)

    def render(self, buf: Buffer) -> None:
        rows = buf.extras.get("batch_rows")
        if rows is not None:
            # micro-batched frame: one D2H of the stacked outputs, then
            # row i goes back to the client that sent frame i (padded
            # rows have no entry and are simply dropped)
            hosts = [c.host() for c in buf.chunks]
            for i, (cid, sid, pts) in enumerate(rows):
                row = Buffer([Chunk(np.ascontiguousarray(h[i]))
                              for h in hosts], pts=pts)
                self._send_one(row, cid, sid)
            return
        self._send_one(buf, buf.extras.get("client_id"),
                       buf.extras.get("server_id", self.id))

    def _send_one(self, buf: Buffer, cid, sid) -> None:
        conn = SERVER_TABLE.get_conn(sid, cid) if cid is not None else None
        if conn is None:
            logger.warning("%s: no connection for client %s", self.name, cid)
            return
        # pack under whatever this client's link negotiated (None = v1)
        meta, payloads = wire.pack_buffer(
            buf, SERVER_TABLE.get_wire(sid, cid), stats=self.stats)
        meta["client_id"] = cid
        try:
            send_msg(conn, MsgKind.RESULT, meta, payloads, stats=self.stats)
        except (ConnectionError, OSError):
            SERVER_TABLE.remove_conn(sid, cid)


@register_element("tensor_query_client")
class TensorQueryClient(Element):
    """Client: sink-pad frames go to the server; results come back on the
    src pad. ``timeout`` guards the round trip (≙ timeout property +
    CONNECTION_CLOSED handling).

    Resilience (≙ tensor_query/README.md:79-80): on connection loss the
    client reconnects with backoff; in ``connect-type=HYBRID`` it
    re-queries the discovery broker at dest-host:dest-port for the
    ``topic`` each attempt, so it fails over to an alternative server
    when the one it was using dies. Unanswered frames are replayed on
    the new connection (at-least-once: a frame whose *result* died with
    the connection is recomputed, so a duplicate is possible; the
    reference simply loses such frames)."""

    SINK_TEMPLATES = {"sink": "other/tensors"}
    SRC_TEMPLATES = {"src": "other/tensors"}
    PROPS = {"host": "localhost", "port": 3001, "dest-host": "",
             "dest-port": 0, "timeout": 10.0, "max-request": 8,
             "connect-type": "TCP", "topic": "",
             # wire v2 link request: lossless payload codec
             # (raw|zlib|shuffle-zlib) and opt-in lossy fp32 downcast
             # (none|bf16|fp16); both silently fall back to raw/none
             # against a server that doesn't support them
             "wire-codec": "raw", "wire-precision": "none"}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._sock: Optional[socket.socket] = None
        self._recv_thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._inflight = threading.Semaphore(max(1, self.max_request))
        self._send_lock = threading.Lock()
        self._conn_lock = threading.Lock()
        self._connect_mutex = threading.Lock()  # one (re)connect at a time
        # unanswered requests, oldest first: replayed on reconnect so a
        # server death loses no frames (at-least-once; results map back
        # FIFO because the server pipeline preserves per-client order).
        # Each entry is [buffer, seq, sent_generation]; the BUFFER (not
        # serialized bytes) is held so a replay re-encodes under the NEW
        # connection's negotiated wire config — failing over from a
        # codec-speaking server to a v1 one must not replay stale-codec
        # payloads. Comparing the generation against _conn_gen under
        # _send_lock makes send and replay idempotent, so a frame is
        # sent at most once per connection no matter how sender and
        # reconnector interleave.
        self._pending: "collections.deque" = collections.deque()
        self._plock = threading.Lock()
        self._conn_gen = 0
        # negotiated per-connection wire config (None = v1 peer);
        # published under _conn_lock together with the socket it belongs
        # to, so a sender always packs for the link it sends on
        self._wire_cfg: Optional[wire.WireConfig] = None
        self._last_caps: Optional[Caps] = None
        self._server_caps = _FLEX_CAPS
        # per-request wire correlation: serving servers (tensor_serve_*)
        # echo it back on RESULT/SHED so out-of-order sheds settle the
        # RIGHT pending entry; plain query servers ignore it and the
        # client falls back to FIFO pairing
        self._seq = 0
        # exact request accounting (the satellite fix for swallowed
        # frames): every admitted frame ends in exactly one bucket, so
        #   session_requests == session_delivered + shed
        #                       + session_declared_lost + in-flight
        # always balances — a frame that dies between socket-error
        # detection and re-dial is DECLARED, never silently swallowed
        self.stats.update({"reconnects": 0, "shed": 0, "link_errors": 0,
                           "session_requests": 0, "session_delivered": 0,
                           "session_replayed": 0, "session_dup_drops": 0,
                           "session_declared_lost": 0})

    def static_transfer(self, in_caps):
        """Unknown output: result caps come from the remote server."""
        return {"src": None}

    def _endpoints(self, timeout: float) -> list:
        """Candidate servers, most preferred first. An EMPTY broker
        answer raises ConnectionError so :meth:`_connect`'s Backoff loop
        re-queries (with ``link_errors`` accounting) until a server
        registers or the timeout budget runs out — a momentarily-bare
        topic (fleet rolling, server restarting) must not fail the
        stream fast."""
        if self.connect_type.upper() == "HYBRID":
            from ..edge.broker import discover
            eps = discover(self.dest_host or self.host,
                           int(self.dest_port) or int(self.port),
                           self.topic, timeout=timeout)
            if eps:
                return eps
            raise ConnectionError(
                f"{self.name}: no server for topic {self.topic!r}")
        return [(self.dest_host or self.host,
                 int(self.dest_port) or int(self.port))]

    def start(self) -> None:
        super().start()
        self._stop_evt.clear()

    def _connect(self, caps: Optional[Caps]) -> None:
        """(Re)connect: discovery + handshake + pending replay, retried
        with backoff until ``timeout``. Each retry re-discovers, so a
        replacement server registered after a death is found."""
        # both the chain thread (do_chain -> _connect) and the background
        # reconnect thread write this; _conn_lock keeps the read-modify-
        # write whole
        with self._conn_lock:
            self._last_caps = caps or self._last_caps
        with self._connect_mutex:
            if self._sock is not None:
                return  # lost the race: another thread reconnected
            deadline = time.monotonic() + self.timeout
            # shared backoff discipline (fault/backoff.py): exponential
            # with jitter, so N clients orphaned by one server death
            # don't hammer the replacement in lockstep
            from ..fault.backoff import Backoff
            backoff = Backoff(base=0.05, multiplier=2.0, max_s=1.0)
            last_err: Optional[Exception] = None
            while time.monotonic() < deadline and not self._stop_evt.is_set():
                # every blocking step below is budgeted out of the SAME
                # deadline so do_chain never stalls longer than ~timeout
                remaining = deadline - time.monotonic()
                try:
                    for host, port in self._endpoints(remaining):
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        if self._try_endpoint(host, port, remaining):
                            return
                except (ConnectionError, OSError) as e:
                    # every failed round — unreachable broker, empty
                    # endpoint list, refused dial — is a counted link
                    # error, then the Backoff ladder re-queries
                    last_err = e
                    self.stats.inc("link_errors")
                # racecheck: ok(deliberate: reconnects are serialized under _connect_mutex, the sleep is stop-interruptible and deadline-budgeted)
                backoff.sleep(self._stop_evt)
            raise ConnectionError(
                f"{self.name}: cannot reach a query server: {last_err}")

    def _try_endpoint(self, host: str, port: int, timeout: float) -> bool:
        """One connect+handshake+replay attempt; False = try next."""
        try:
            sock = socket.create_connection((host, port), timeout=timeout)
        except OSError:
            return False
        wire.tune_socket(sock)
        try:
            send_msg(sock, MsgKind.CAPS,
                     {"caps": str(self._last_caps or ""),
                      "wire": wire.advertise(str(self.wire_codec),
                                             str(self.wire_precision))})
            kind, meta, _ = recv_msg(sock)
            if kind != MsgKind.CAPS_ACK:
                raise ConnectionError(f"{self.name}: bad handshake {kind}")
            # handshake done: blocking mode for the long-lived recv loop
            # (a lingering per-op timeout would kill idle connections),
            # and caps published BEFORE the socket so a racing _connect
            # caller never reads half-initialized state
            sock.settimeout(None)
            self._server_caps = meta.get("caps", _FLEX_CAPS)
            cfg = wire.accept(meta.get("wire"))
            with self._conn_lock:
                self._sock = sock
                self._wire_cfg = cfg
                self._conn_gen += 1
                gen = self._conn_gen
                self._inflight = threading.Semaphore(
                    max(1, self.max_request))
            self._recv_thread = threading.Thread(
                target=self._recv_loop, args=(sock, self._inflight),
                name=f"qclient-recv:{self.name}", daemon=True)
            self._recv_thread.start()
            # replay unanswered frames in order on the new connection —
            # re-encoded under THIS connection's negotiated config; the
            # send lock is held across the whole replay so a new frame
            # from the streaming thread cannot interleave and break the
            # FIFO request->result pairing; the generation mark skips
            # entries the streaming thread already sent on THIS connection
            with self._send_lock:
                with self._plock:
                    replay = list(self._pending)
                for entry in replay:
                    if entry[2] == gen:
                        continue
                    if not self._inflight.acquire(timeout=self.timeout):
                        raise ConnectionError(
                            f"{self.name}: replay stalled")
                    meta, payloads = wire.pack_buffer(entry[0], cfg,
                                                      stats=self.stats)
                    meta["seq"] = entry[1]
                    roi = _roi_meta(entry[0])
                    if roi is not None:
                        meta["delta_roi"] = roi
                    send_msg(sock, MsgKind.DATA, meta, payloads,
                             stats=self.stats)
                    entry[2] = gen
                    self.stats.inc("session_replayed")
            return True
        except (ConnectionError, OSError):
            self._handle_disconnect(sock)
            try:
                sock.close()
            except OSError:
                pass
            return False

    def _handle_disconnect(self, sock: Optional[socket.socket]) -> None:
        """Tear down a failed connection (idempotent; ignores stale
        sockets already replaced by a reconnect)."""
        with self._conn_lock:
            if sock is not None and sock is not self._sock:
                return
            old, self._sock = self._sock, None
            self._wire_cfg = None
            # fresh permit pool: replies owed on the dead connection will
            # never come, and blocked senders must not burn the timeout
            self._inflight = threading.Semaphore(max(1, self.max_request))
        _sever(old)

    def stop(self) -> None:
        self._stop_evt.set()
        self._handle_disconnect(None)
        super().stop()

    def on_sink_caps(self, pad: Pad, caps: Caps) -> None:
        if self._sock is None:
            self._connect(caps)
        self.set_src_caps(Caps(self._server_caps))

    def do_chain(self, pad: Pad, buf: Buffer) -> None:
        seq = self._seq = self._seq + 1
        self.stats.inc("session_requests")
        with self._conn_lock:
            self._last_caps = pad.caps or self._last_caps
        # the entry holds the BUFFER: it is packed at send time, under
        # the config of the connection it actually goes out on
        entry = [buf, seq, -1]  # -1 = not yet sent on any connection
        with self._plock:
            self._pending.append(entry)
        for attempt in (1, 2):
            sock = None
            try:
                if self._sock is None:
                    self._connect(pad.caps)
                    self.stats.inc("reconnects")
                    self.set_src_caps(Caps(self._server_caps))
                with self._conn_lock:
                    sock, gen = self._sock, self._conn_gen
                    inflight = self._inflight
                    cfg = self._wire_cfg
                if sock is None:
                    raise ConnectionError(f"{self.name}: not connected")
                if entry[2] == gen:
                    return  # a reconnect replay already sent our frame
                if not inflight.acquire(timeout=self.timeout):
                    raise TimeoutError(f"{self.name}: server not answering")
                with self._send_lock:
                    if entry[2] == gen:   # replay won the race meanwhile
                        inflight.release()
                    else:
                        meta, payloads = wire.pack_buffer(buf, cfg,
                                                          stats=self.stats)
                        meta["seq"] = seq
                        roi = _roi_meta(buf)
                        if roi is not None:
                            meta["delta_roi"] = roi
                        send_msg(sock, MsgKind.DATA, meta, payloads,
                                 stats=self.stats)
                        entry[2] = gen
                return
            except TimeoutError:
                # backpressure timeout, NOT a dead connection (it is an
                # OSError subclass, so re-raise before the handler below
                # tears down a healthy socket)
                self._declare_lost(entry)
                raise
            except (ConnectionError, OSError) as e:
                # tear down only the socket the failure happened on; a
                # racing reconnect may already have installed a fresh one
                if sock is not None:
                    self._handle_disconnect(sock)
                if attempt == 2:
                    self._declare_lost(entry)
                    raise ConnectionError(
                        f"{self.name}: send failed after reconnect: {e}") \
                        from e
                logger.warning("%s: connection lost, reconnecting (%s)",
                               self.name, e)

    def _declare_lost(self, entry) -> None:
        """Give up on one pending request and SAY SO: the frame is
        removed from the replay set and counted in
        ``session_declared_lost`` (plus a structured bus warning), so
        the accounting identity still balances — never a silent
        swallow between error detection and re-dial."""
        with self._plock:
            try:
                self._pending.remove(entry)
            except ValueError:
                return  # already settled/declared by another path
        self.stats.inc("session_declared_lost")
        self.post_message("warning", frames_lost=1, seq=entry[1],
                          detail="request abandoned after send/replay "
                                 "failure")

    def kill_link(self) -> int:
        """Chaos hook (tensor_fault mode=kill-link): force-close the
        live server connection mid-stream. The recv loop detects it,
        reconnects, and replays every unanswered frame."""
        with self._conn_lock:
            sock = self._sock
        if sock is None:
            return 0
        _sever(sock)
        self.stats.inc("link_kills")
        return 1

    def session_info(self) -> Dict:
        with self._plock:
            n = len(self._pending)
        return {"in_flight": n} if n else {}

    def _settle_pending(self, seq) -> None:
        """Mark the request a reply answers as no longer owed. Serving
        servers echo our ``seq`` (sheds can overtake results, so FIFO
        would settle the wrong entry); plain query servers don't, and
        order-preserving FIFO remains correct there."""
        with self._plock:
            if seq is not None:
                for i, entry in enumerate(self._pending):
                    if entry[1] == seq:
                        del self._pending[i]
                        return
            if self._pending:
                self._pending.popleft()

    def _recv_loop(self, sock: socket.socket,
                   inflight: threading.Semaphore) -> None:
        try:
            while not self._stop_evt.is_set():
                kind, meta, payloads = recv_msg(sock, stats=self.stats)
                if kind == MsgKind.DRAIN:
                    # the server is draining: it will settle what it
                    # already admitted and shed the rest. Back off new
                    # sends via upstream QoS with its retry-after hint.
                    self.stats.inc("server_drains")
                    retry_ns = int(
                        float(meta.get("retry_after_ms", 0.0)) * 1e6)
                    self.send_upstream_event(QosEvent(
                        proportion=2.0, period_ns=retry_ns))
                    continue
                if kind in (MsgKind.RESULT, MsgKind.SHED):
                    with self._conn_lock:
                        stale = sock is not self._sock
                    if stale:
                        # our connection was replaced under us: the replay
                        # on the new connection recomputes this frame, so
                        # forwarding would duplicate it — and releasing
                        # would inflate the NEW semaphore's permit pool.
                        # Counted: this is exactly a session dup-drop.
                        self.stats.inc("session_dup_drops")
                        continue
                    self._settle_pending(meta.get("seq"))
                    if kind == MsgKind.SHED:
                        # the server dropped this request (admission or
                        # deadline): no result will come. Surface the
                        # overload upstream as QoS with the server's
                        # retry-after as the sustainable spacing hint.
                        self.stats.inc("shed")
                        retry_ns = int(
                            float(meta.get("retry_after_ms", 0.0)) * 1e6)
                        self.send_upstream_event(QosEvent(
                            proportion=2.0, period_ns=retry_ns))
                        inflight.release()
                        continue
                    # push before releasing: on_eos drains by acquiring all
                    # permits, so releasing first would let EOS overtake
                    # (and drop) this final result downstream
                    self.srcpad.push(_roi_adopt(
                        wire.unpack_buffer(meta, payloads, stats=self.stats),
                        meta.get("delta_roi")))
                    self.stats.inc("session_delivered")
                    inflight.release()
                elif kind == MsgKind.EOS:
                    break
        except (ConnectionError, OSError):
            if not self._stop_evt.is_set():
                self.stats.inc("link_errors")
                logger.warning("%s: server connection closed", self.name)
                # unblock senders so the next frame triggers a reconnect
                self._handle_disconnect(sock)
                with self._plock:
                    owed = len(self._pending)
                if owed:
                    # answers are still owed: reconnect proactively so the
                    # replay happens even if no new frame ever arrives
                    threading.Thread(target=self._reconnect_bg,
                                     name=f"qclient-reconn:{self.name}",
                                     daemon=True).start()

    def _reconnect_bg(self) -> None:
        try:
            self._connect(self._last_caps)
            self.stats.inc("reconnects")
        except (ConnectionError, OSError) as e:
            logger.warning("%s: background reconnect failed: %s",
                           self.name, e)

    def on_eos(self) -> None:
        # drain in-flight requests before forwarding EOS
        deadline = time.monotonic() + self.timeout
        inflight = self._inflight
        for _ in range(max(1, self.max_request)):
            if not inflight.acquire(
                    timeout=max(0.0, deadline - time.monotonic())):
                break
        # anything still unanswered will never be: downstream is about
        # to see EOS. Declare the remainder so the accounting identity
        # (requests == delivered + shed + declared_lost) closes.
        with self._plock:
            leftovers = len(self._pending)
            self._pending.clear()
        if leftovers:
            self.stats.inc("session_declared_lost", leftovers)
            self.post_message("warning", frames_lost=leftovers,
                              detail="requests still unanswered at EOS")
        if self._sock is not None:
            try:
                send_msg(self._sock, MsgKind.EOS, {})
            except (ConnectionError, OSError):
                pass
