"""tensor_query_client / tensor_query_serversrc / tensor_query_serversink
— remote-filter (RPC) stream offload.

≙ gst/nnstreamer/tensor_query/*: a client pipeline sends frames to a
server pipeline and receives results (tensor_query_client.c:676-712 send
path, :428-510 receive path); server entry/exit pads pair up through a
shared table keyed by ``id`` so answers return to the asking client
(tensor_query_server.c). Transport is the edge protocol (edge/protocol.py)
over TCP/DCN; caps are exchanged at connect like the reference's
edge-handle info "CAPS" (:537-562).
"""
from __future__ import annotations

import socket
import threading
import time
from typing import Dict, Optional, Tuple

from ..edge.protocol import (MsgKind, buffer_to_wire, recv_msg, send_msg,
                             wire_to_buffer)
from ..pipeline.element import Element, SinkElement, SrcElement
from ..pipeline.pad import Pad
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer
from ..tensors.caps import Caps
from ..utils.log import logger


class _ServerTable:
    """Pairs serversrc/serversink by id and routes client connections
    (≙ GstTensorQueryServerInfo table, tensor_query_server.c)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._conns: Dict[Tuple[int, int], socket.socket] = {}
        self._out_caps: Dict[int, str] = {}

    def add_conn(self, server_id: int, client_id: int,
                 sock: socket.socket) -> None:
        with self._lock:
            self._conns[(server_id, client_id)] = sock

    def remove_conn(self, server_id: int, client_id: int) -> None:
        with self._lock:
            self._conns.pop((server_id, client_id), None)

    def get_conn(self, server_id: int, client_id: int):
        with self._lock:
            return self._conns.get((server_id, client_id))

    def set_out_caps(self, server_id: int, caps: str) -> None:
        with self._lock:
            self._out_caps[server_id] = caps

    def get_out_caps(self, server_id: int) -> Optional[str]:
        with self._lock:
            return self._out_caps.get(server_id)


SERVER_TABLE = _ServerTable()
_FLEX_CAPS = "other/tensors,format=flexible"


@register_element("tensor_query_serversrc")
class TensorQueryServerSrc(SrcElement):
    """Server entry: listens for clients, pushes received frames into the
    server pipeline with the client id stamped in buffer extras."""

    PROPS = {"host": "localhost", "port": 3001, "id": 0, "timeout": 10.0}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._listener: Optional[socket.socket] = None
        self._queue = []
        self._qlock = threading.Condition()
        self._next_client = [0]
        self._accept_thread: Optional[threading.Thread] = None

    @property
    def bound_port(self) -> int:
        return self._listener.getsockname()[1] if self._listener else self.port

    def negotiate_src_caps(self) -> Optional[Caps]:
        return Caps(_FLEX_CAPS)

    def start(self) -> None:
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self._listener.listen(16)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"qsrc-accept:{self.name}",
            daemon=True)
        self._accept_thread.start()
        super().start()

    def stop(self) -> None:
        super().stop()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None

    def _accept_loop(self) -> None:
        while not self._stop_evt.is_set():
            try:
                conn, addr = self._listener.accept()
            except OSError:
                return
            cid = self._next_client[0]
            self._next_client[0] += 1
            SERVER_TABLE.add_conn(self.id, cid, conn)
            threading.Thread(target=self._client_loop, args=(conn, cid),
                             name=f"qsrc-client{cid}:{self.name}",
                             daemon=True).start()

    def _client_loop(self, conn: socket.socket, cid: int) -> None:
        try:
            while not self._stop_evt.is_set():
                kind, meta, payloads = recv_msg(conn)
                if kind == MsgKind.CAPS:
                    out_caps = SERVER_TABLE.get_out_caps(self.id) or _FLEX_CAPS
                    send_msg(conn, MsgKind.CAPS_ACK,
                             {"caps": out_caps, "client_id": cid})
                elif kind == MsgKind.DATA:
                    buf = wire_to_buffer(meta, payloads)
                    buf.extras["client_id"] = cid
                    buf.extras["server_id"] = self.id
                    with self._qlock:
                        self._queue.append(buf)
                        self._qlock.notify_all()
                elif kind == MsgKind.EOS:
                    break
        except (ConnectionError, OSError):
            pass
        finally:
            SERVER_TABLE.remove_conn(self.id, cid)
            try:
                conn.close()
            except OSError:
                pass

    def create(self) -> Optional[Buffer]:
        with self._qlock:
            while not self._queue:
                if self._stop_evt.is_set():
                    return None
                self._qlock.wait(timeout=0.1)
            return self._queue.pop(0)


@register_element("tensor_query_serversink")
class TensorQueryServerSink(SinkElement):
    """Server exit: returns results to the client that asked."""

    PROPS = {"id": 0, "timeout": 10.0}

    def on_sink_caps(self, pad: Pad, caps: Caps) -> None:
        SERVER_TABLE.set_out_caps(self.id, str(caps))

    def handle_event(self, pad, event) -> None:
        from ..pipeline.events import CapsEvent
        if isinstance(event, CapsEvent):
            pad.set_caps(event.caps)
            self.on_sink_caps(pad, event.caps)
            return
        super().handle_event(pad, event)

    def render(self, buf: Buffer) -> None:
        cid = buf.extras.get("client_id")
        sid = buf.extras.get("server_id", self.id)
        conn = SERVER_TABLE.get_conn(sid, cid) if cid is not None else None
        if conn is None:
            logger.warning("%s: no connection for client %s", self.name, cid)
            return
        meta, payloads = buffer_to_wire(buf)
        meta["client_id"] = cid
        try:
            send_msg(conn, MsgKind.RESULT, meta, payloads)
        except (ConnectionError, OSError):
            SERVER_TABLE.remove_conn(sid, cid)


@register_element("tensor_query_client")
class TensorQueryClient(Element):
    """Client: sink-pad frames go to the server; results come back on the
    src pad. ``timeout`` guards the round trip (≙ timeout property +
    CONNECTION_CLOSED handling)."""

    SINK_TEMPLATES = {"sink": "other/tensors"}
    SRC_TEMPLATES = {"src": "other/tensors"}
    PROPS = {"host": "localhost", "port": 3001, "dest-host": "",
             "dest-port": 0, "timeout": 10.0, "max-request": 8}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._sock: Optional[socket.socket] = None
        self._recv_thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._inflight = threading.Semaphore(max(1, self.max_request))
        self._lock = threading.Lock()

    def _target(self) -> Tuple[str, int]:
        return (self.dest_host or self.host,
                int(self.dest_port) or int(self.port))

    def start(self) -> None:
        super().start()
        self._stop_evt.clear()

    def _connect(self, caps: Optional[Caps]) -> None:
        host, port = self._target()
        deadline = time.monotonic() + self.timeout
        last_err = None
        while time.monotonic() < deadline:
            try:
                self._sock = socket.create_connection((host, port),
                                                      timeout=self.timeout)
                break
            except OSError as e:
                last_err = e
                time.sleep(0.05)
        else:
            raise ConnectionError(
                f"{self.name}: cannot connect to {host}:{port}: {last_err}")
        send_msg(self._sock, MsgKind.CAPS, {"caps": str(caps or "")})
        kind, meta, _ = recv_msg(self._sock)
        if kind != MsgKind.CAPS_ACK:
            raise ConnectionError(f"{self.name}: bad handshake {kind}")
        self._server_caps = meta.get("caps", _FLEX_CAPS)
        self._recv_thread = threading.Thread(
            target=self._recv_loop, name=f"qclient-recv:{self.name}",
            daemon=True)
        self._recv_thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        super().stop()

    def on_sink_caps(self, pad: Pad, caps: Caps) -> None:
        if self._sock is None:
            self._connect(caps)
        self.set_src_caps(Caps(self._server_caps))

    def do_chain(self, pad: Pad, buf: Buffer) -> None:
        if self._sock is None:
            self._connect(pad.caps)
            self.set_src_caps(Caps(self._server_caps))
        if not self._inflight.acquire(timeout=self.timeout):
            raise TimeoutError(f"{self.name}: server not answering")
        meta, payloads = buffer_to_wire(buf)
        with self._lock:
            send_msg(self._sock, MsgKind.DATA, meta, payloads)

    def _recv_loop(self) -> None:
        try:
            while not self._stop_evt.is_set():
                kind, meta, payloads = recv_msg(self._sock)
                if kind == MsgKind.RESULT:
                    # push before releasing: on_eos drains by acquiring all
                    # permits, so releasing first would let EOS overtake
                    # (and drop) this final result downstream
                    self.srcpad.push(wire_to_buffer(meta, payloads))
                    self._inflight.release()
                elif kind == MsgKind.EOS:
                    break
        except (ConnectionError, OSError):
            if not self._stop_evt.is_set():
                logger.warning("%s: server connection closed", self.name)

    def on_eos(self) -> None:
        # drain in-flight requests before forwarding EOS
        deadline = time.monotonic() + self.timeout
        for _ in range(max(1, self.max_request)):
            if not self._inflight.acquire(
                    timeout=max(0.0, deadline - time.monotonic())):
                break
        if self._sock is not None:
            try:
                send_msg(self._sock, MsgKind.EOS, {})
            except (ConnectionError, OSError):
                pass
