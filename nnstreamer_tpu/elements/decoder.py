"""tensor_decoder — tensors -> media via decoder subplugins.

≙ gst/nnstreamer/elements/gsttensor_decoder.c + the GstTensorDecoderDef
subplugin ABI (include/nnstreamer_plugin_api_decoder.h:38-100 — init/exit/
setOption(9)/getOutCaps/decode), plus runtime custom-decoder registration
(include/tensor_decoder_custom.h).
"""
from __future__ import annotations

from typing import Optional

from ..decoders.registry import find_decoder
from ..pipeline.element import TransformElement
from ..pipeline.pad import Pad
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer
from ..tensors.caps import Caps


@register_element("tensor_decoder")
class TensorDecoder(TransformElement):
    SINK_TEMPLATES = {"sink": "other/tensors"}
    SRC_TEMPLATES = {"src": None}
    STRIPS_META = True  # decoded media buffers carry no tensor meta
    # mode + option1..option9, the reference's property surface
    PROPS = {"mode": "", **{f"option{i}": "" for i in range(1, 10)}}

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._decoder = None

    def _open(self) -> None:
        if self._decoder is None:
            if not self.mode:
                raise ValueError(f"{self.name}: 'mode' property is required")
            self._decoder = find_decoder(self.mode)()
            self._decoder.set_options(
                [getattr(self, f"option{i}") for i in range(1, 10)])

    def start(self) -> None:
        super().start()
        self._open()

    def on_sink_caps(self, pad: Pad, caps: Caps) -> None:
        self._open()
        out = self._decoder.get_out_caps(caps.to_config())
        self.set_src_caps(out)

    def static_transfer(self, in_caps):
        """The mode subplugin's get_out_caps on the declared config
        (subplugins declare out caps without touching data)."""
        if not self.mode:
            raise ValueError(f"{self.name}: 'mode' property is required")
        caps = in_caps.get("sink")
        if caps is None or not caps.is_fixed():
            return {"src": None}
        dec = find_decoder(self.mode)()
        dec.set_options(
            [getattr(self, f"option{i}") for i in range(1, 10)])
        return {"src": dec.get_out_caps(caps.to_config())}

    # -- device placement (fusion compiler) --------------------------------
    DEVICE_FUSIBLE = ("modes whose subplugin declares device_fn "
                      "(e.g. image_segment); others decode on the host")

    def device_veto(self) -> Optional[str]:
        if not self.mode:
            return "mode not set"
        try:
            dec_cls = find_decoder(self.mode)
        except ValueError:
            return f"unknown decoder mode {self.mode!r}"
        from ..decoders.registry import DecoderPlugin
        if dec_cls.device_fn is DecoderPlugin.device_fn:
            return f"decoder mode {self.mode!r} is host-only"
        return None

    def device_fn(self, ctx=None):
        if self.device_veto() is not None:
            return None
        try:
            self._open()
        except Exception:  # noqa: BLE001 -- decline, don't block launch
            return None
        cfg = getattr(ctx, "in_config", None) if ctx is not None else None
        return self._decoder.device_fn(cfg)

    def transform(self, buf: Buffer) -> Optional[Buffer]:
        out = self._decoder.decode(buf)
        if out is None:
            return None
        extras = dict(out.extras)  # decoder results survive the meta copy
        out.copy_meta_from(buf)
        out.extras.update(extras)
        return out
