"""Command-line launcher: the gst-launch-1.0 / gst-inspect-1.0 analog.

Run a pipeline description until EOS::

    python -m nnstreamer_tpu 'tensortestsrc caps="..." num-buffers=10 ! \
        tensor_filter framework=jax model=zoo://mobilenet_v2 ! fakesink'

Introspection (≙ gst-inspect)::

    python -m nnstreamer_tpu --inspect              # list all elements
    python -m nnstreamer_tpu --inspect tensor_filter  # one element's props
    python -m nnstreamer_tpu --inspect-filters      # filter backends

Static analysis (pipelint)::

    python -m nnstreamer_tpu lint 'tensortestsrc ... ! fakesink'
    python -m nnstreamer_tpu lint --json '<desc>'   # exit 0/1/2

Concurrency analysis (racecheck)::

    python -m nnstreamer_tpu racecheck nnstreamer_tpu/
    python -m nnstreamer_tpu racecheck --json -o build/racecheck.json

Settlement / conservation analysis (flowcheck)::

    python -m nnstreamer_tpu flowcheck nnstreamer_tpu/
    python -m nnstreamer_tpu flowcheck --json -o build/flowcheck.json

Compile/host-sync analysis (jitcheck)::

    python -m nnstreamer_tpu jitcheck nnstreamer_tpu/
    python -m nnstreamer_tpu jitcheck --json -o build/jitcheck.json

Fleet telemetry (scrapes obs metrics endpoints into one table)::

    python -m nnstreamer_tpu top --targets localhost:9100,localhost:9101
    python -m nnstreamer_tpu top --broker localhost:5000 --watch 2
"""
from __future__ import annotations

import argparse
import json
import sys


def _inspect(name: str | None) -> int:
    from .pipeline.registry import element_names, get_element_class
    if not name:
        for n in element_names():
            print(n)
        return 0
    try:
        cls = get_element_class(name)
    except KeyError:
        print(f"no such element {name!r}", file=sys.stderr)
        return 1
    print(f"{name} ({cls.__module__}.{cls.__name__})")
    doc = (cls.__doc__ or "").strip().splitlines()
    if doc:
        print(f"  {doc[0]}")
    props = {}
    for klass in reversed(cls.__mro__):
        props.update(getattr(klass, "PROPS", {}))
    if props:
        print("  properties:")
        for k, v in sorted(props.items()):
            print(f"    {k:24} default={v!r}")
    for attr, label in (("SINK_TEMPLATES", "sink pads"),
                        ("SRC_TEMPLATES", "src pads")):
        tmpl = getattr(cls, attr, {})
        if tmpl:
            print(f"  {label}:")
            for pname, caps in tmpl.items():
                print(f"    {pname:24} {caps or 'ANY'}")
    return 0


def _inspect_filters() -> int:
    from .filters.registry import _FRAMEWORKS
    for n in sorted(_FRAMEWORKS):
        cls = _FRAMEWORKS[n]
        exts = ",".join(getattr(cls, "EXTENSIONS", ()))
        avail = "" if getattr(cls, "AVAILABLE", True) else "  [unavailable]"
        print(f"{n:20} {exts}{avail}")
    return 0


def _run_broker(kind: str, port: int, timeout: float | None) -> int:
    """Run a standalone broker process (the SSAT cross-process pattern:
    tests launch brokers/servers as real processes, ref:
    tests/nnstreamer_edge/edge/runTest.sh)."""
    import time
    if kind == "mqtt":
        from .edge.mqtt import MqttBroker
        broker = MqttBroker(port=port).start()
    else:
        from .edge.broker import DiscoveryBroker
        broker = DiscoveryBroker(port=port).start()
    print(f"broker {kind} listening on {broker.bound_port}", flush=True)
    try:
        deadline = time.monotonic() + timeout if timeout else None
        while deadline is None or time.monotonic() < deadline:
            time.sleep(0.2)
    except KeyboardInterrupt:
        pass
    finally:
        broker.stop()
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    if argv and argv[0] == "lint":
        from .analysis.cli import main as lint_main
        return lint_main(argv[1:])
    if argv and argv[0] == "racecheck":
        from .analysis.concurrency.cli import main as racecheck_main
        return racecheck_main(argv[1:])
    if argv and argv[0] == "flowcheck":
        from .analysis.flow.cli import main as flowcheck_main
        return flowcheck_main(argv[1:])
    if argv and argv[0] == "jitcheck":
        from .analysis.jit.cli import main as jitcheck_main
        return jitcheck_main(argv[1:])
    if argv and argv[0] == "top":
        from .obs.top import main as top_main
        return top_main(argv[1:])
    ap = argparse.ArgumentParser(
        prog="python -m nnstreamer_tpu",
        description="Launch a tensor pipeline (gst-launch analog).")
    ap.add_argument("pipeline", nargs="?", help="pipeline description")
    ap.add_argument("--timeout", type=float, default=None,
                    help="seconds to wait for EOS (default: forever)")
    ap.add_argument("--trace", action="store_true",
                    help="print the tracing report at exit")
    ap.add_argument("--stats", action="store_true",
                    help="print per-element stats at exit")
    ap.add_argument("--inspect", nargs="?", const="", metavar="ELEMENT",
                    help="list elements, or one element's properties")
    ap.add_argument("--inspect-filters", action="store_true",
                    help="list filter backends")
    ap.add_argument("--broker", choices=("mqtt", "discovery"),
                    help="run a standalone broker instead of a pipeline "
                         "(mqtt = MQTT 3.1.1 data broker, discovery = "
                         "query HYBRID registry)")
    ap.add_argument("--port", type=int, default=0,
                    help="broker port (0 = ephemeral, printed to stdout)")
    args = ap.parse_args(argv)

    if args.inspect is not None:
        return _inspect(args.inspect or None)
    if args.inspect_filters:
        return _inspect_filters()
    if args.broker:
        return _run_broker(args.broker, args.port, args.timeout)
    if not args.pipeline:
        ap.print_usage()
        return 2

    from . import parse_launch
    pipe = parse_launch(args.pipeline)
    tracer = pipe.enable_tracing() if args.trace else None
    try:
        pipe.start()
        ok = pipe.wait_eos(args.timeout)
        if not ok:
            print("timeout waiting for EOS", file=sys.stderr)
    except KeyboardInterrupt:
        print("interrupted", file=sys.stderr)
    finally:
        pipe.stop()
    err = [m for m in pipe.bus.drain() if m.kind == "error"]
    for m in err:
        print(f"ERROR: {m.data.get('element')}: {m.data.get('error')}",
              file=sys.stderr)
    if args.stats:
        print(json.dumps(pipe.stats(), indent=2, default=str))
    if tracer is not None:
        print(json.dumps(tracer.report(pipe), indent=2, default=str))
    return 1 if err else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:   # e.g. `--inspect | head`
        sys.exit(0)
