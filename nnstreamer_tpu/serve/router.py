"""Fleet router: one front-end, N replica serve pipelines.

``tensor_serve_router`` accepts client streams on the exact query/serve
wire (CAPS/CAPS_ACK, DATA/DATA_BATCH -> RESULT/SHED/DRAIN) and fans each
request out to one of N replica ``tensor_serve_src`` pipelines, so the
single-pipeline serving stack (PR 1) stops being a single point of
failure. Robustness is the headline, composed from the existing layers:

* **consistent-hash session affinity** with a **least-loaded tiebreak**:
  a session's frames stick to one replica (its scheduler keeps the
  stream's arrival order and jit signatures warm); sessionless traffic
  and displaced sessions go to the replica with the smallest
  in-flight + reported-queue-depth load, fed by the occupancy reports
  replicas piggyback on PONG heartbeats and broker REGISTER metadata;
* a **per-replica health state machine** — connecting / healthy /
  suspect / down / draining — driven by PING/PONG heartbeats
  (edge/session.Heartbeat) and a per-link circuit breaker
  (fault/breaker.CircuitBreaker) that paces re-dials of a dead replica;
* **zero-loss failover**: every dispatched request sits in a pending
  table keyed by a router-minted seq until the replica answers. When a
  replica link dies, its unsettled requests are re-dispatched to a
  survivor (PR 7's replay/seq-dedup discipline: each settles exactly
  once — a late duplicate answer is counted in ``router_dup_drops``,
  never forwarded), and when no survivor exists they are SHED to the
  client with a retry-after, never silently dropped;
* **live membership** over the :class:`~..edge.broker.DiscoveryBroker`:
  replicas REGISTER with occupancy metadata, the router re-queries on a
  cadence and immediately after any replica death;
* **administrative drain**: :meth:`FleetRouter.drain_replica` (or a
  DRAIN the replica itself sends while its pipeline drains) marks one
  replica draining — its in-flight requests settle normally via the
  DRAIN/retry-after path while the ring steers its affinity sessions to
  the survivors.

The accounting identity clients rely on holds at any quiescent point::

    router_requests == router_delivered + router_shed + router_orphaned

(``router_orphaned`` counts answers owed to a client that disconnected
first — settled toward a peer that no longer exists).
"""
from __future__ import annotations

import bisect
import hashlib
import socket
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..edge import wire
from ..edge.listener import TcpListener
from ..edge.protocol import MsgKind, recv_msg, send_msg, sever_socket as _sever
from ..edge.session import Heartbeat
from ..fault.breaker import CircuitBreaker
from ..obs import events as _obs_events
from ..pipeline.element import Element
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer
from ..tensors.caps import Caps
from ..utils.atomic import Counters
from ..utils.log import logger

_FLEX_CAPS = "other/tensors,format=flexible"

# replica health states (report() vocabulary)
CONNECTING = "connecting"
HEALTHY = "healthy"
SUSPECT = "suspect"
DOWN = "down"
DRAINING = "draining"


def _hval(key: str) -> int:
    """Stable 64-bit hash (sha1 prefix): identical placement across
    processes and runs, unlike the salted builtin hash()."""
    return int.from_bytes(hashlib.sha1(key.encode()).digest()[:8], "big")


class HashRing:
    """Consistent-hash ring over replica keys with virtual nodes: a
    session key maps to the first vnode clockwise, so membership changes
    remap only the sessions of the replicas that actually joined/left
    (~1/N of sessions per event, not a full reshuffle)."""

    def __init__(self, vnodes: int = 64):
        self.vnodes = max(1, int(vnodes))
        self._ring: List[Tuple[int, str]] = []

    def rebuild(self, keys) -> None:
        ring = [(_hval(f"{k}#{i}"), k)
                for k in keys for i in range(self.vnodes)]
        ring.sort()
        self._ring = ring

    def lookup(self, session_key: str) -> Optional[str]:
        if not self._ring:
            return None
        i = bisect.bisect_right(self._ring, (_hval(session_key), ""))
        return self._ring[i % len(self._ring)][1]


class _Replica:
    """One replica link: socket + negotiated wire config + heartbeat +
    breaker. The socket/config/generation triple is published under the
    router's replica lock; the send lock keeps wire frames atomic
    between the dispatching client threads and the heartbeat timer."""

    __slots__ = ("key", "host", "port", "origin", "sock", "slock", "cfg",
                 "gen", "hb", "breaker", "draining", "load", "instance",
                 "restored_ad")

    def __init__(self, key: str, host: str, port: int, origin: str,
                 heartbeat_s: float, heartbeat_miss: int,
                 breaker_threshold: int, breaker_reset_s: float):
        self.key, self.host, self.port = key, host, int(port)
        self.origin = origin  # "static" (replicas= prop) or "broker"
        self.sock: Optional[socket.socket] = None
        self.slock = threading.Lock()
        self.cfg: Optional[wire.WireConfig] = None
        self.gen = 0
        self.hb = Heartbeat(heartbeat_s, heartbeat_miss)
        self.breaker = CircuitBreaker(threshold=breaker_threshold,
                                      reset_s=breaker_reset_s,
                                      name=f"replica:{key}")
        self.draining = False
        self.load: Dict = {}
        # the serve src's per-incarnation token (CAPS_ACK): a re-dial
        # that lands on the SAME process is a reconnect, not a rejoin —
        # it must not clear an administrative drain ("" = pre-token peer)
        self.instance = ""
        # whether the broker advert last seen for this endpoint carried
        # restored_sessions: resurrection counting is edge-triggered on
        # this, so a same-endpoint resurrect counts exactly once
        self.restored_ad = False

    @property
    def llm_role(self) -> str:
        """The replica's advertised LLM phase ("prefill" | "decode" |
        "both"; "" = not an LLM replica), carried by REGISTER metadata
        and refreshed by every PONG load report."""
        return str((self.load or {}).get("llm_role") or "")

    def state(self) -> str:
        if self.draining:
            return DRAINING
        if self.sock is None:
            return DOWN if self.gen else CONNECTING
        return SUSPECT if self.hb.outstanding > 0 else HEALTHY


def parse_replicas(spec: str) -> List[Tuple[str, int]]:
    """``host:port`` endpoints, comma or semicolon separated."""
    out = []
    for tok in str(spec or "").replace(";", ",").split(","):
        tok = tok.strip()
        if not tok:
            continue
        host, _, port = tok.rpartition(":")
        out.append((host or "localhost", int(port)))
    return out


class FleetRouter:
    """The embeddable core (the element below wraps it): accepts client
    streams, dispatches to replicas, fails over, drains."""

    def __init__(self, *, host: str = "localhost", port: int = 0,
                 replicas: str = "", topic: str = "",
                 broker_host: str = "localhost", broker_port: int = 0,
                 timeout: float = 10.0, affinity: bool = True,
                 session: bool = True, heartbeat_s: float = 0.25,
                 heartbeat_miss: int = 3, breaker_threshold: int = 3,
                 breaker_reset_s: float = 1.0, retry_after_ms: float = 50.0,
                 requery_s: float = 0.5, max_redispatch: int = 3,
                 name: str = "router", stats: Optional[Counters] = None):
        self.name = name
        self.timeout = max(0.1, float(timeout))
        self.affinity = bool(affinity)
        self.session = bool(session)
        self.heartbeat_s = max(0.01, float(heartbeat_s))
        self.heartbeat_miss = max(1, int(heartbeat_miss))
        self.breaker_threshold = max(1, int(breaker_threshold))
        self.breaker_reset_s = max(0.01, float(breaker_reset_s))
        self.retry_after_ms = float(retry_after_ms)
        self.requery_s = max(0.05, float(requery_s))
        self.max_redispatch = max(0, int(max_redispatch))
        self.topic = str(topic or "")
        self.broker_host = broker_host or "localhost"
        self.broker_port = int(broker_port)
        self.stats = Counters()
        if stats is not None:
            self.stats = stats  # share the owning element's counters
        self.stats.update({
            "router_requests": 0, "router_delivered": 0, "router_shed": 0,
            "router_redispatched": 0, "router_dup_drops": 0,
            "router_orphaned": 0, "router_orphan_drops": 0,
            "router_replica_deaths": 0,
            "router_replica_connects": 0, "router_replica_drains": 0,
            # pre-seeded (not lazily minted on first event) so report()
            # and /metrics expose them as 0 from the first scrape — a
            # dashboard watching for the first rejoin/resurrection must
            # not have to special-case a missing series
            "router_replica_rejoins": 0,
            "router_replica_resurrections": 0,
            "link_errors": 0})
        self._listener = TcpListener(host, port, self._client_conn,
                                     name=f"router-accept:{name}")
        self._stop_evt = threading.Event()
        self._wake = threading.Event()
        self._draining = False
        # cid -> [conn, send lock, wire cfg, session key]
        self._clients: Dict[int, list] = {}
        self._next_cid = 0
        self._clock = threading.Lock()
        # replica key -> _Replica, plus the affinity ring over the keys
        # currently eligible for NEW dispatches (live, not draining)
        self._replicas: Dict[str, _Replica] = {}
        self._ring = HashRing()
        # decode-home ring for disaggregated LLM fleets: consistent
        # hashing over the DECODE-capable replicas only, so a stream's
        # decode home survives prefill membership churn (and vice
        # versa). Mirrors _ring while no replica advertises an llm_role.
        self._dring = HashRing()
        self._rlock = threading.Lock()
        # rseq -> [cid, client seq, buffer, replica key, attempts,
        # llm phase]: every dispatched-but-unsettled request; the
        # failover unit
        self._pending: Dict[int, list] = {}
        # rseqs retired by _drop_client (their client died first): a late
        # replica answer for one is an orphan answer, not a failover
        # duplicate — the two causes are counted apart. Bounded FIFO;
        # guarded by _plock like the pending table it shadows.
        self._orphan_rseqs: "OrderedDict[int, bool]" = OrderedDict()
        self._rseq = 0
        self._plock = threading.Lock()
        self._maint_thread: Optional[threading.Thread] = None
        for h, p in parse_replicas(replicas):
            key = f"{h}:{p}"
            self._replicas[key] = _Replica(
                key, h, p, "static", self.heartbeat_s, self.heartbeat_miss,
                self.breaker_threshold, self.breaker_reset_s)

    # -- lifecycle ---------------------------------------------------------
    @property
    def bound_port(self) -> int:
        return self._listener.bound_port

    def start(self) -> "FleetRouter":
        self._stop_evt.clear()
        self._draining = False
        if self.topic and self.broker_port:
            self._requery_broker()  # initial membership, best-effort
        with self._rlock:
            # broker-discovered members were dialed by the requery; only
            # the static list (and any requery stragglers) remain down
            down = [r for r in self._replicas.values() if r.sock is None]
        for rep in down:
            self._connect_replica(rep)
        self._listener.start()
        self._maint_thread = threading.Thread(
            target=self._maintain, name=f"router-maint:{self.name}",
            daemon=True)
        self._maint_thread.start()
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        self._wake.set()
        self._listener.stop()
        with self._clock:
            clients = list(self._clients.values())
            self._clients.clear()
        for ent in clients:
            _sever(ent[0])
        with self._rlock:
            socks = [r.sock for r in self._replicas.values()
                     if r.sock is not None]
            for r in self._replicas.values():
                r.sock = None
                r.cfg = None
        for s in socks:
            _sever(s)

    # -- client side -------------------------------------------------------
    def _client_conn(self, conn: socket.socket) -> None:
        # per-op timeout: a half-open client must not hold its recv
        # thread forever; a live-but-idle one just times out and loops
        conn.settimeout(max(0.1, self.timeout))
        cid: Optional[int] = None
        skey: Optional[str] = None
        try:
            while not self._stop_evt.is_set():
                try:
                    kind, meta, payloads = recv_msg(conn, stats=self.stats)
                except TimeoutError:
                    continue
                if kind == MsgKind.CAPS:
                    cfg = wire.negotiate(meta.get("wire"))
                    if cid is None:
                        with self._clock:
                            cid = self._next_cid
                            self._next_cid += 1
                            # affinity key: the client's session id when
                            # it advertises one (survives its reconnects)
                            # else this connection's identity
                            if self.session:
                                sess = meta.get("session") or {}
                                skey = str(sess.get("sid") or f"c{cid}")
                            self._clients[cid] = [conn, threading.Lock(),
                                                  cfg, skey]
                    else:
                        with self._clock:
                            ent = self._clients.get(cid)
                            if ent is not None:
                                ent[2] = cfg
                    ack = {"caps": _FLEX_CAPS, "client_id": cid}
                    if cfg is not None:
                        ack["wire"] = cfg.to_meta()
                    send_msg(conn, MsgKind.CAPS_ACK, ack)
                elif kind == MsgKind.DATA:
                    if cid is None:
                        continue  # no handshake, no route
                    buf = wire.unpack_buffer(meta, payloads,
                                             stats=self.stats)
                    self._dispatch(cid, buf, meta.get("seq"), skey,
                                   phase=meta.get("llm_phase"))
                elif kind == MsgKind.DATA_BATCH:
                    if cid is None:
                        continue
                    for b in wire.unpack_batch(meta, payloads,
                                               stats=self.stats):
                        self._dispatch(cid, b, b.extras.get("seq"), skey)
                elif kind == MsgKind.PING:
                    self._send_client(cid, MsgKind.PONG,
                                      {"t": meta.get("t")})
                elif kind == MsgKind.EOS:
                    break
        except (ConnectionError, OSError, ValueError) as exc:
            self.stats.inc("link_errors")
            logger.info("%s: client %s connection ended: %r",
                        self.name, cid, exc)
        finally:
            if cid is not None:
                self._drop_client(cid)
            try:
                conn.close()
            except OSError:
                pass

    def _drop_client(self, cid: int) -> None:
        with self._clock:
            self._clients.pop(cid, None)
        # answers owed to a dead client are unroutable: retire their
        # pending entries VISIBLY so the accounting identity closes
        with self._plock:
            orphans = [r for r, e in self._pending.items() if e[0] == cid]
            for r in orphans:
                del self._pending[r]
                self._orphan_rseqs[r] = True
            while len(self._orphan_rseqs) > 4096:
                self._orphan_rseqs.popitem(last=False)
        if orphans:
            self.stats.inc("router_orphaned", len(orphans))

    def _skey_of(self, cid: int) -> Optional[str]:
        with self._clock:
            ent = self._clients.get(cid)
        return ent[3] if ent is not None else None

    def _send_client(self, cid, kind, meta, payloads=()) -> bool:
        with self._clock:
            ent = self._clients.get(cid)
        if ent is None:
            return False
        conn, lock = ent[0], ent[1]
        try:
            with lock:
                send_msg(conn, kind, meta, payloads, stats=self.stats)
            return True
        except (ConnectionError, OSError):
            self._drop_client(cid)
            return False

    def _client_cfg(self, cid) -> Optional[wire.WireConfig]:
        with self._clock:
            ent = self._clients.get(cid)
        return ent[2] if ent is not None else None

    # -- dispatch ----------------------------------------------------------
    def _dispatch(self, cid: int, buf: Buffer, cseq, skey: Optional[str],
                  attempts: int = 0, phase: Optional[str] = None) -> None:
        if attempts == 0:
            self.stats.inc("router_requests")
        if self._draining:
            self._shed_to_client(cid, cseq, buf)
            return
        tried: set = set()
        while True:
            # phase is only forwarded when present: _pick's 2-arg form
            # stays a stable seam (tests stub it for race injection)
            snap = (self._pick(skey, tried, phase) if phase
                    else self._pick(skey, tried))
            if snap is None or attempts > self.max_redispatch:
                # no dispatchable replica (or the request already
                # ping-ponged through max_redispatch deaths): settle it
                # as SHED with a retry-after — RESULT xor SHED, never
                # silence
                self._shed_to_client(cid, cseq, buf)
                return
            key, sock, slock, cfg = snap
            with self._plock:
                self._rseq += 1
                rseq = self._rseq
                self._pending[rseq] = [cid, cseq, buf, key, attempts,
                                       phase]
            meta, payloads = wire.pack_buffer(buf, cfg, stats=self.stats)
            meta["seq"] = rseq
            if phase:
                meta["llm_phase"] = phase
                if phase == "prompt" and skey is not None:
                    # pin the stream's decode home so the prefill
                    # replica ships its KV where every later frame of
                    # this session will also land
                    home = self.decode_home(skey)
                    if home is not None:
                        meta["decode_home"] = home
            try:
                with slock:
                    send_msg(sock, MsgKind.DATA, meta, payloads,
                             stats=self.stats)
                return
            except (ConnectionError, OSError):
                # the pending entry is reclaimed BEFORE the down-handler
                # runs so the failover sweep cannot double-dispatch it;
                # a miss means a concurrent _replica_down (which severed
                # this socket, making our send raise) swept the entry
                # first and already re-dispatched it — that path owns
                # the retry, looping here would mint a second pending
                # entry (duplicate settles) for one client request
                with self._plock:
                    owned = self._pending.pop(rseq, None) is not None
                self._replica_down(key, sock)
                if not owned:
                    return
                tried.add(key)
                attempts += 1

    def _pick(self, skey: Optional[str], exclude: set,
              phase: Optional[str] = None
              ) -> Optional[Tuple[str, socket.socket, threading.Lock,
                                  Optional[wire.WireConfig]]]:
        """Choose a replica: ring affinity first, least-loaded among the
        live ones otherwise. Returns a snapshot (key, sock, send lock,
        wire cfg) taken under the replica lock; None = nobody can serve.

        Disaggregated LLM fleets add a phase filter: ``phase="prompt"``
        frames go to prefill capacity (dedicated ``prefill`` replicas
        first, ``both`` as spillover) and skip the affinity ring —
        prompts are stateless, least-loaded wins; ``phase="decode"``
        frames pin to the stream's decode home on the decode ring. A
        fleet where nobody advertises a role ignores the phase."""
        with self._rlock:
            live = [r for r in self._replicas.values()
                    if r.sock is not None and not r.draining
                    and r.key not in exclude]
            if phase and any(r.llm_role for r in live):
                if phase == "prompt":
                    pref = [r for r in live if r.llm_role == "prefill"]
                    live = pref or [r for r in live
                                    if r.llm_role in ("prefill", "both")]
                elif phase == "decode":
                    live = [r for r in live
                            if r.llm_role in ("decode", "both")]
                    want = (self._dring.lookup(skey)
                            if skey is not None else None)
                    for r in live:
                        if r.key == want:
                            return (r.key, r.sock, r.slock, r.cfg)
            if not live:
                return None
            if self.affinity and skey is not None and phase != "prompt":
                want = self._ring.lookup(skey)
                for r in live:
                    if r.key == want:
                        return (r.key, r.sock, r.slock, r.cfg)
            cands = [(r.key, r.sock, r.slock, r.cfg,
                      int((r.load or {}).get("depth", 0))) for r in live]
        # least-loaded tiebreak: our own unsettled count per replica
        # (exact) plus the replica's last self-reported queue depth
        # (PONG/REGISTER occupancy metadata; possibly a beat stale)
        with self._plock:
            inflight: Dict[str, int] = {}
            for ent in self._pending.values():
                inflight[ent[3]] = inflight.get(ent[3], 0) + 1
        best = min(cands, key=lambda c: inflight.get(c[0], 0) + c[4])
        return best[:4]

    def _shed_to_client(self, cid: int, cseq, buf: Buffer) -> None:
        self.stats.inc("router_shed")
        _obs_events.emit("shed", source=self.name, element=self,
                         reason="no-replica", client=cid)
        self._send_client(cid, MsgKind.SHED,
                          {"seq": cseq, "pts": buf.pts, "client_id": cid,
                           "retry_after_ms": float(self.retry_after_ms)})

    def _settle(self, rseq) -> Optional[list]:
        """Pop one pending entry exactly once; None = already settled.
        A miss is classified before counting: an answer owed to a
        client that disconnected first (entry retired by _drop_client)
        is ``router_orphan_drops``; anything else is a duplicate after
        failover re-dispatch, ``router_dup_drops``. Either way it is
        dropped and counted, never forwarded twice."""
        with self._plock:
            ent = self._pending.pop(rseq, None)
            orphan = (ent is None
                      and self._orphan_rseqs.pop(rseq, False))
        if ent is None:
            self.stats.inc("router_orphan_drops" if orphan
                           else "router_dup_drops")
        return ent

    # -- replica side ------------------------------------------------------
    def _connect_replica(self, rep: _Replica) -> bool:
        """Dial + CAPS handshake one replica; on success publish the
        link and spawn its recv loop. Breaker outcomes are the caller's
        job (start() dials unconditionally, the maintainer is gated)."""
        try:
            sock = socket.create_connection((rep.host, rep.port),
                                            timeout=self.timeout)
        except OSError:
            return False
        wire.tune_socket(sock)
        try:
            sock.settimeout(self.timeout)
            send_msg(sock, MsgKind.CAPS,
                     {"caps": "", "wire": wire.advertise("raw", "none")})
            kind, meta, _ = recv_msg(sock)
            if kind != MsgKind.CAPS_ACK:
                raise ConnectionError(f"bad handshake {kind}")
            cfg = wire.accept(meta.get("wire"))
            # keep the per-op timeout for the link's lifetime: a wedged
            # replica whose TCP send buffer fills must make the blocked
            # send (PING under the send lock, or a dispatch) raise into
            # _replica_down, not hold the fleet-wide maintenance thread
            # hostage. Recv timeouts never fire on a healthy link —
            # PONGs arrive every heartbeat_s << timeout — so hitting
            # one means the heartbeat machinery itself is wedged and
            # declaring the link dead is the right backstop.
            sock.settimeout(self.timeout)
        except (ConnectionError, OSError, ValueError):
            try:
                sock.close()
            except OSError:
                pass
            return False
        rejoined = False
        inst = str(meta.get("instance") or "")
        with self._rlock:
            # the serve src mints a fresh instance token per start(): a
            # matching token means this re-dial reached the SAME process
            # life — a TCP blip, not a membership event
            same_proc = bool(inst) and inst == rep.instance
            rep.instance = inst
            rep.sock = sock
            rep.slock = threading.Lock()
            rep.cfg = cfg
            rep.gen += 1
            rep.hb = Heartbeat(self.heartbeat_s, self.heartbeat_miss)
            # a fresh link to a NEW process is a fresh replica: one
            # resurrected at the same host:port must not inherit the
            # corpse's DRAINING flag (it would be routable never again).
            # A reconnect to the same still-draining process keeps the
            # flag — clearing it would undo an administrative drain and
            # double-count the rejoin (the mid-drain counter drift this
            # guard exists for).
            if rep.draining and not same_proc:
                rep.draining = False
                rejoined = True
            self._rebuild_ring_locked()
        if rejoined:
            self.stats.inc("router_replica_rejoins")
            logger.info("%s: replica %s rejoined (draining flag cleared)",
                        self.name, rep.key)
        threading.Thread(target=self._replica_loop, args=(rep, sock),
                         name=f"router-replica:{rep.key}",
                         daemon=True).start()
        self.stats.inc("router_replica_connects")
        logger.info("%s: replica %s connected", self.name, rep.key)
        return True

    def _rebuild_ring_locked(self) -> None:
        live = [r for r in self._replicas.values()
                if r.sock is not None and not r.draining]
        self._ring.rebuild(sorted(r.key for r in live))
        # the decode ring only narrows once someone actually advertises
        # a phase; a role-free fleet keeps decode_home == assignment
        roled = [r for r in live if r.llm_role]
        decode = [r.key for r in roled
                  if r.llm_role in ("decode", "both")]
        self._dring.rebuild(sorted(decode) if roled
                            else sorted(r.key for r in live))

    def _replica_loop(self, rep: _Replica, sock: socket.socket) -> None:
        try:
            while not self._stop_evt.is_set():
                kind, meta, payloads = recv_msg(sock, stats=self.stats)
                if kind == MsgKind.RESULT:
                    rep.hb.heard()
                    ent = self._settle(meta.get("seq"))
                    if ent is None:
                        continue
                    buf = wire.unpack_buffer(meta, payloads,
                                             stats=self.stats)
                    out_meta, out_payloads = wire.pack_buffer(
                        buf, self._client_cfg(ent[0]), stats=self.stats)
                    out_meta["client_id"] = ent[0]
                    out_meta["seq"] = ent[1]
                    if self._send_client(ent[0], MsgKind.RESULT, out_meta,
                                         out_payloads):
                        self.stats.inc("router_delivered")
                    else:
                        self.stats.inc("router_orphaned")
                elif kind == MsgKind.SHED:
                    rep.hb.heard()
                    ent = self._settle(meta.get("seq"))
                    if ent is None:
                        continue
                    self.stats.inc("router_shed")
                    self._send_client(
                        ent[0], MsgKind.SHED,
                        {"seq": ent[1], "client_id": ent[0],
                         "retry_after_ms": float(meta.get(
                             "retry_after_ms", self.retry_after_ms))})
                elif kind == MsgKind.PONG:
                    rep.hb.pong(float(meta.get("t", 0.0)))
                    load = meta.get("load")
                    if isinstance(load, dict):
                        with self._rlock:
                            rechain = (str(load.get("llm_role") or "")
                                       != rep.llm_role)
                            rep.load = load
                            if rechain:
                                # a phase (dis)appeared: the decode-home
                                # ring membership just changed
                                self._rebuild_ring_locked()
                elif kind == MsgKind.DRAIN:
                    # the replica's pipeline is draining: it will settle
                    # what it admitted and shed the rest — steer new
                    # dispatches (and its affinity sessions) elsewhere
                    self._mark_draining(rep)
                elif kind == MsgKind.EOS:
                    break
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            if not self._stop_evt.is_set():
                self._replica_down(rep.key, sock)

    def _mark_draining(self, rep: _Replica) -> None:
        with self._rlock:
            fresh = not rep.draining
            rep.draining = True
            if fresh:
                self._rebuild_ring_locked()
        if fresh:
            self.stats.inc("router_replica_drains")
            logger.info("%s: replica %s draining; affinity sessions "
                        "steered to survivors", self.name, rep.key)

    def drain_replica(self, key: str) -> bool:
        """Administrative drain: quiesce one replica — new dispatches
        (and its affinity sessions) steer elsewhere, its in-flight
        requests settle normally. Pair with the replica pipeline's own
        ``drain()`` to flush and stop it."""
        with self._rlock:
            rep = self._replicas.get(key)
        if rep is None:
            return False
        self._mark_draining(rep)
        return True

    def _replica_down(self, key: str, sock: Optional[socket.socket]) -> None:
        """One replica link died: retire the socket (idempotent via
        identity), pace re-dials through its breaker, and fail its
        unsettled requests over to the survivors."""
        with self._rlock:
            rep = self._replicas.get(key)
            if rep is None or sock is None or rep.sock is not sock:
                return  # stale report: a newer link is already up
            rep.sock = None
            rep.cfg = None
            rep.gen += 1
            self._rebuild_ring_locked()
        rep.breaker.record_failure()
        self.stats.inc("router_replica_deaths")
        _sever(sock)
        logger.warning("%s: replica %s died; failing over", self.name, key)
        _obs_events.emit("failover", source=self.name, element=self,
                         replica=key)
        self._failover(key)
        self._wake.set()  # immediate re-dial attempt + membership requery

    def _failover(self, key: str) -> None:
        """Re-dispatch every unsettled request of a dead replica to a
        survivor. The dead link can no longer answer, so each entry
        settles exactly once on its new home (a wrongly-declared-dead
        replica's late answers hit the seq dedup in :meth:`_settle`)."""
        with self._plock:
            victims = [(r, e) for r, e in self._pending.items()
                       if e[3] == key]
            for r, _ in victims:
                del self._pending[r]
        for _, ent in victims:
            self.stats.inc("router_redispatched")
            self._dispatch(ent[0], ent[2], ent[1], self._skey_of(ent[0]),
                           attempts=ent[4] + 1,
                           phase=ent[5] if len(ent) > 5 else None)

    # -- maintenance: heartbeats, re-dials, membership ---------------------
    def _maintain(self) -> None:
        tick = min(self.heartbeat_s / 2.0, 0.1)
        next_query = 0.0
        while not self._stop_evt.is_set():
            # racecheck: ok(deliberate: the maintenance timer sleeps on its own wake event with no shared lock held)
            self._wake.wait(tick)
            self._wake.clear()
            if self._stop_evt.is_set():
                return
            now = time.monotonic()
            with self._rlock:
                live = [(r.key, r.sock, r.slock, r.hb)
                        for r in self._replicas.values()
                        if r.sock is not None]
                down = [r for r in self._replicas.values()
                        if r.sock is None]
            for key, sock, slock, hb in live:
                if hb.peer_dead:
                    # miss_limit unanswered pings: a half-open TCP link
                    # is declared dead instead of trusted forever
                    self._replica_down(key, sock)
                    continue
                if hb.due(now):
                    try:
                        with slock:
                            send_msg(sock, MsgKind.PING,
                                     {"t": time.monotonic()})
                        hb.sent()
                    except (ConnectionError, OSError):
                        self._replica_down(key, sock)
            for rep in down:
                # breaker-paced re-dial: CLOSED dials freely, OPEN
                # waits out reset_s, HALF_OPEN admits one probe
                if rep.breaker.allow():
                    if self._connect_replica(rep):
                        rep.breaker.record_success()
                    else:
                        rep.breaker.record_failure()
            if self.topic and self.broker_port and now >= next_query:
                next_query = now + self.requery_s
                self._requery_broker()

    def _requery_broker(self) -> None:
        from ..edge.broker import discover_meta
        try:
            eps = discover_meta(self.broker_host, self.broker_port,
                                self.topic, timeout=min(2.0, self.timeout))
        except (ConnectionError, OSError, ValueError):
            self.stats.inc("link_errors")
            return
        fresh: List[_Replica] = []
        seen = set()
        with self._rlock:
            for (host, port), info in eps:
                key = f"{host}:{port}"
                seen.add(key)
                rep = self._replicas.get(key)
                if rep is None:
                    rep = _Replica(key, host, port, "broker",
                                   self.heartbeat_s, self.heartbeat_miss,
                                   self.breaker_threshold,
                                   self.breaker_reset_s)
                    self._replicas[key] = rep
                    fresh.append(rep)
                if isinstance(info, dict) and (not rep.load
                                               or rep.sock is None):
                    # REGISTER occupancy seeds the load; a down replica's
                    # stale PONG load is replaced by the fresh advert
                    rep.load = info
                has_rs = (isinstance(info, dict)
                          and bool(info.get("restored_sessions")))
                if has_rs and not rep.restored_ad:
                    # the replica came back from a preemption snapshot
                    # carrying restored session ids. Edge-triggered on
                    # the advert (a registration's advert dies with its
                    # broker connection), so a resurrection counts once
                    # whether the process came back at a brand-new
                    # endpoint or at the SAME host:port — the latter
                    # was previously never counted
                    self.stats.inc("router_replica_resurrections")
                    logger.info("%s: replica %s resurrected with %d "
                                "restored session(s)", self.name, key,
                                len(info["restored_sessions"]))
                rep.restored_ad = has_rs
            for k, r in self._replicas.items():
                if k not in seen:
                    # its advert died with its registration connection;
                    # the next advert carrying restored_sessions is a
                    # fresh resurrection edge
                    r.restored_ad = False
            # a replica the broker no longer advertises AND whose link is
            # gone has left the fleet; a live link outranks a flapping
            # broker, so connected members are never evicted here
            gone = [k for k, r in self._replicas.items()
                    if r.origin == "broker" and k not in seen
                    and r.sock is None]
            for k in gone:
                del self._replicas[k]
            if gone:
                self._rebuild_ring_locked()
        for rep in fresh:
            self._connect_replica(rep)

    # -- drain / observability / chaos -------------------------------------
    def drain(self) -> None:
        """Router-wide quiesce: stop admitting (late DATA sheds with
        retry-after) and tell every client DRAIN; in-flight requests
        still settle through their replicas."""
        self._draining = True
        with self._clock:
            ents = list(self._clients.items())
        for cid, ent in ents:
            try:
                with ent[1]:
                    send_msg(ent[0], MsgKind.DRAIN,
                             {"client_id": cid,
                              "retry_after_ms": float(self.retry_after_ms)})
            except (ConnectionError, OSError):
                pass

    def pending(self) -> int:
        with self._plock:
            return len(self._pending)

    def assignment(self, skey: str) -> Optional[str]:
        """The replica a session's NEXT frame would go to (affinity
        view; observability + tests)."""
        with self._rlock:
            return self._ring.lookup(skey)

    def decode_home(self, skey: str) -> Optional[str]:
        """The decode-capable replica this session is pinned to
        (consistent hash over the decode ring) — where prompt-phase
        dispatches tell the prefill replica to ship its KV."""
        with self._rlock:
            return self._dring.lookup(skey)

    def replica_keys(self) -> List[str]:
        with self._rlock:
            return sorted(self._replicas)

    def report(self) -> Dict[str, Dict]:
        with self._plock:
            inflight: Dict[str, int] = {}
            for ent in self._pending.values():
                inflight[ent[3]] = inflight.get(ent[3], 0) + 1
        out: Dict[str, Dict] = {}
        with self._rlock:
            reps = list(self._replicas.values())
        for r in reps:
            hb = r.hb
            out[r.key] = {
                "state": r.state(),
                "origin": r.origin,
                "llm_role": r.llm_role,
                "in_flight": inflight.get(r.key, 0),
                "load": dict(r.load or {}),
                "breaker": r.breaker.state,
                "pongs": hb.pongs,
                "rtt_us_avg": (hb.rtt_ns / hb.pongs / 1e3
                               if hb.pongs else 0.0),
            }
        return out

    def kill_links(self) -> int:
        """Chaos hook: sever every live replica link (the client side of
        a partition between router and fleet); heartbeats/recv loops
        detect it and the failover path re-dispatches."""
        with self._rlock:
            socks = [(r.key, r.sock) for r in self._replicas.values()
                     if r.sock is not None]
        for _key, s in socks:
            _sever(s)
        return len(socks)


@register_element("tensor_serve_router")
class TensorServeRouter(Element):
    """Fleet front-end element: clients connect to it exactly as they
    would to a single ``tensor_serve_src``; it spreads their requests
    over the replica fleet with affinity, health-checked failover, and
    zero-loss re-dispatch (see :class:`FleetRouter`).

    Replicas come from the static ``replicas`` list (``host:port,...``)
    and/or the discovery broker at ``dest-host:dest-port`` under
    ``topic`` (replicas REGISTER there with occupancy metadata; the
    router re-queries every ``requery-ms`` and on any replica death).
    A router with neither is unroutable — the ``router-no-replicas``
    lint rule rejects it before launch."""

    PROPS = {"host": "localhost", "port": 3002, "timeout": 10.0,
             # static fleet membership: host:port, comma/semicolon list
             "replicas": "",
             # broker membership: topic + broker endpoint (HYBRID slot)
             "topic": "", "dest-host": "localhost", "dest-port": 0,
             # consistent-hash session affinity (least-loaded when off);
             # session=false disables per-connection session keys, so
             # affinity has nothing to key on (lint warns)
             "affinity": True, "session": True,
             # replica health: PING cadence + unanswered-ping budget
             "heartbeat-ms": 250.0, "heartbeat-miss": 3,
             # per-replica-link breaker pacing re-dials of a dead replica
             "breaker-threshold": 3, "breaker-reset-ms": 1000.0,
             # the retry-after hint on router-minted SHEDs
             "retry-after-ms": 50.0,
             # broker membership re-query cadence
             "requery-ms": 500.0,
             # failover budget per request before it sheds
             "max-redispatch": 3}

    # conservation identity flowcheck proves statically and
    # check_identities() asserts over live stats snapshots
    SETTLEMENT_IDENTITY = ("router-settlement",)

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self.router: Optional[FleetRouter] = None

    @property
    def bound_port(self) -> int:
        return self.router.bound_port if self.router else int(self.port)

    def negotiate_src_caps(self) -> Optional[Caps]:
        return Caps(_FLEX_CAPS)

    def start(self) -> None:
        self.router = FleetRouter(
            host=self.host, port=int(self.port),
            replicas=str(self.replicas), topic=str(self.topic),
            broker_host=str(self.dest_host), broker_port=int(self.dest_port),
            timeout=float(self.timeout), affinity=bool(self.affinity),
            session=bool(self.session),
            heartbeat_s=float(self.heartbeat_ms) / 1e3,
            heartbeat_miss=int(self.heartbeat_miss),
            breaker_threshold=int(self.breaker_threshold),
            breaker_reset_s=float(self.breaker_reset_ms) / 1e3,
            retry_after_ms=float(self.retry_after_ms),
            requery_s=float(self.requery_ms) / 1e3,
            max_redispatch=int(self.max_redispatch),
            name=self.name, stats=self.stats)
        self.router.start()
        super().start()

    def stop(self) -> None:
        if self.router is not None:
            self.router.stop()
        super().stop()

    def drain(self) -> None:
        super().drain()
        if self.router is not None:
            self.router.drain()

    def drain_flushed(self) -> bool:
        return self.router is None or self.router.pending() == 0

    def drain_replica(self, key: str) -> bool:
        return self.router is not None and self.router.drain_replica(key)

    def kill_link(self) -> int:
        return self.router.kill_links() if self.router is not None else 0

    def session_info(self) -> Dict:
        n = self.router.pending() if self.router is not None else 0
        return {"in_flight": n} if n else {}

    def router_report(self) -> Dict:
        return self.router.report() if self.router is not None else {}
