"""tensor_serve — the dynamic-batching serving stack (L4).

Sits between N concurrent client streams and one ``tensor_filter``:
per-stream admission control feeds a bucketed batcher whose padded
batches keep the filter's jit-executable cache hot (at most one compile
per bucket), and a demux routes each batch row's result back to the
stream that asked, by correlation id.

The reference's among-device layer (tensor_query_*) RPCs one frame per
connection straight into the filter; this package turns that into a
serving stack: ``tensor_serve_src ! tensor_filter ! tensor_serve_sink``
speaks the same wire protocol as ``tensor_query_client``, plus SHED
replies (retry-after backpressure) when admission or deadlines drop a
request.
"""
from .batcher import BucketBatcher, Request, stack_requests
from .router import FleetRouter, HashRing, parse_replicas
from .scheduler import SERVE_TABLE, ServeScheduler

__all__ = ["BucketBatcher", "Request", "ServeScheduler", "SERVE_TABLE",
           "stack_requests", "FleetRouter", "HashRing", "parse_replicas"]
