"""ServeScheduler: admission -> bucketed batch -> invoke -> demux.

The scheduler owns the three moving parts of the serving stack: a
:class:`~.batcher.BucketBatcher` (coalescing + admission + deadlines), a
demux that routes each batch row's result back to its originating
request by correlation, and per-batch metrics (occupancy, queue delay,
batch latency, shed counts) kept in O(1)-memory reservoirs and — when a
pipeline tracer is attached — mirrored into its report.

Two embeddings:

* **Pipeline elements** (``tensor_serve_src``/``tensor_serve_sink``):
  the src loop calls :meth:`next_batch`, the filter invokes, the sink
  calls :meth:`complete`. The pair find each other in :data:`SERVE_TABLE`
  keyed by their ``id`` property.
* **Standalone** (tests, embedding without a pipeline): construct with
  ``invoke_fn`` and :meth:`start` a worker thread that drives
  batch -> invoke -> demux itself.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from ..obs import events as _obs_events
from ..obs import spans as _obs_spans
from ..utils.atomic import Counters
from ..utils.log import logger
from ..utils.trace import Reservoir, WindowReservoir
from .batcher import BucketBatcher, Request, stack_requests

# serve_src/serve_sink pairing by id (≙ the query elements' SERVER_TABLE)
SERVE_TABLE: Dict[int, "ServeScheduler"] = {}
_TABLE_LOCK = threading.Lock()


def register_scheduler(sid: int, sched: "ServeScheduler") -> None:
    with _TABLE_LOCK:
        SERVE_TABLE[sid] = sched


def unregister_scheduler(sid: int) -> None:
    with _TABLE_LOCK:
        SERVE_TABLE.pop(sid, None)


def get_scheduler(sid: int) -> Optional["ServeScheduler"]:
    with _TABLE_LOCK:
        return SERVE_TABLE.get(sid)


class ServeScheduler:
    def __init__(self, buckets: Sequence[int] = (1, 2, 4, 8),
                 max_wait_s: float = 0.005, max_queue: int = 16,
                 deadline_s: float = 0.0,
                 invoke_fn: Optional[Callable] = None,
                 name: str = "serve", mesh_spec: str = ""):
        self.name = name
        # mesh-aware serving: the declared mesh's data-parallel degree
        # snaps the buckets (every stacked batch divides dp), and
        # place() lays each stacked batch out batch-major across the
        # mesh before the filter dispatches — one sharded invoke per
        # batch instead of one chip doing all rows
        self.mesh_spec = str(mesh_spec or "")
        snap = 1
        if self.mesh_spec:
            from ..parallel.mesh import spec_dp
            snap = spec_dp(self.mesh_spec)
        self.batcher = BucketBatcher(buckets, max_wait_s, max_queue,
                                     snap_multiple=snap)
        self._mesh = None          # built lazily on the first place()
        self._mesh_failed = False  # insufficient devices: degrade once
        self.deadline_s = max(0.0, float(deadline_s))
        self._invoke_fn = invoke_fn
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self.tracer = None  # optional utils.trace.Tracer (observe() sink)
        self._mlock = threading.Lock()
        # queue delay is the autoscaler's control signal: windowed, so
        # a drained backlog stops reading as pressure within seconds
        self._queue_delay = WindowReservoir(window_s=2.0)
        self._batch_latency = Reservoir()
        self.stats = Counters(completed=0, rows_padded=0, bucket_rows=0,
                              result_errors=0, invoke_errors=0,
                              shed_failed=0)
        # ledger recovered from a preemption snapshot (read under _mlock)
        self.recovered_ledger: List[Dict[str, Any]] = []

    # -- producers ---------------------------------------------------------
    def admit(self, stream_id: Any, arrays: Sequence[Any], *,
              seq: Optional[int] = None, pts: Optional[int] = None,
              on_result: Optional[Callable] = None,
              on_shed: Optional[Callable] = None,
              deadline_s: Optional[float] = None,
              ctx: Optional[Any] = None) -> Optional[Request]:
        """Admit one request and return its handle (None = shed at
        admission; ``on_shed`` has already been invoked). The handle is
        what :meth:`cancel_requests` cancels — callers that may shed a
        composite (e.g. every sibling crop of an ROI frame) keep it."""
        dl = self.deadline_s if deadline_s is None else deadline_s
        req = Request(stream_id, arrays, seq=seq, pts=pts,
                      deadline=(time.monotonic() + dl) if dl > 0 else None,
                      on_result=on_result, on_shed=on_shed, ctx=ctx)
        if self.batcher.submit(req):
            return req
        _obs_events.emit("shed", source=self.name, reason="admission",
                         stream=str(stream_id))
        if on_shed is not None:
            on_shed(req)
        return None

    def submit(self, stream_id: Any, arrays: Sequence[Any], *,
               seq: Optional[int] = None, pts: Optional[int] = None,
               on_result: Optional[Callable] = None,
               on_shed: Optional[Callable] = None,
               deadline_s: Optional[float] = None,
               ctx: Optional[Any] = None) -> bool:
        """Admit one request. False = shed at admission; the ``on_shed``
        callback has already been invoked (retry-after is the caller's
        wire-level answer)."""
        return self.admit(stream_id, arrays, seq=seq, pts=pts,
                          on_result=on_result, on_shed=on_shed,
                          deadline_s=deadline_s, ctx=ctx) is not None

    def cancel_stream(self, stream_id: Any) -> int:
        return self.batcher.cancel_stream(stream_id)

    def cancel_requests(self, reqs: Sequence[Request]) -> int:
        """Cancel specific still-queued requests (ROI sibling-crop
        cleanup on a shed frame). Returns how many were removed; each
        counts as ``cancelled`` in the settlement identity. Requests
        already batched are past cancellation and settle normally."""
        return self.batcher.cancel_requests(reqs)

    def record_shed_failed(self, n: int = 1) -> None:
        """Terminal accounting for batched-but-failed rows: an invoke
        failure sheds the whole batch via per-request ``on_shed``, and
        this counter is what keeps ``requests == completed +
        shed_deadline + cancelled + shed_failed + pending`` balanced.
        The pipeline embedding (tensor_filter) calls this from its
        invoke-failure and breaker-open paths."""
        if n > 0:
            with self._mlock:
                self.stats.inc("shed_failed", n)

    def drain(self) -> None:
        """Graceful teardown: close admission (late submits shed with
        retry-after), flush every queued request through the invoke
        path, and let :meth:`next_batch` return None once the queue is
        dry — the serving loop's EOS barrier. Pending correlations
        settle through :meth:`complete` as usual."""
        self.batcher.drain()

    @property
    def draining(self) -> bool:
        return self.batcher.draining

    def pending(self) -> int:
        """Requests admitted but not yet batched (the drain barrier
        watches this reach zero)."""
        return self.batcher.depth()

    # -- checkpoint/restore (checkpoint/) ----------------------------------
    def pending_ledger(self) -> List[Dict[str, Any]]:
        """The admitted-but-unsettled ledger a preemption snapshot
        records: per-request (stream, seq, pts) identity. Reply routes
        (sockets, callbacks) do not survive process death, so the ledger
        declares — it does not replay; the fleet router's failover owns
        re-dispatch, and a late duplicate settles as an orphan, keeping
        ``router_requests == delivered + shed + orphaned``."""
        return self.batcher.ledger()

    def record_recovered(self, ledger: List[Dict[str, Any]]) -> None:
        """Note a restored ledger on this (fresh) scheduler: counted and
        kept for observability/chaos assertions; nothing is re-queued
        here (see :meth:`pending_ledger`)."""
        with self._mlock:
            self.recovered_ledger = list(ledger or [])
        if ledger:
            self.stats.inc("recovered_pending", len(ledger))
            logger.info("%s: restored with %d declared in-flight "
                        "requests (router failover re-dispatches them)",
                        self.name, len(ledger))

    # -- the batch side ----------------------------------------------------
    def next_batch(self, stop: Optional[threading.Event] = None):
        """Block for the next batch; returns (requests, bucket, stacked
        arrays) or None when ``stop`` fires. Queue-delay and occupancy
        metrics are recorded here (the batch is formed NOW)."""
        batch = self.batcher.next_batch(stop)
        if batch is None:
            return None
        bucket = self.batcher.bucket_for(len(batch))
        now = time.monotonic()
        with self._mlock:
            for r in batch:
                self._queue_delay.add((now - r.t_arrival) * 1e9)
            self.stats.add(bucket_rows=bucket, rows_padded=bucket - len(batch))
        if self.tracer is not None:
            for r in batch:
                self.tracer.observe(f"{self.name}:queue_delay",
                                    (now - r.t_arrival) * 1e9)
        if _obs_spans.ENABLED:
            t_wall = time.time_ns()
            for r in batch:
                if r.ctx is not None:
                    wait = int((now - r.t_arrival) * 1e9)
                    _obs_spans.record_span(f"{self.name}:queue_wait",
                                           "queue", t_wall - wait, wait,
                                           r.ctx)
                    r.ctx.q_ns += wait
        return batch, bucket, self.place(stack_requests(batch, bucket))

    def place(self, stacked):
        """Lay a stacked batch out across the declared mesh with a
        batch-major NamedSharding device_put — BEFORE dispatch, so the
        downstream filter finds every input already committed and its
        own placement is a no-op. Degrades to host arrays (logged once)
        when the mesh cannot be built, e.g. fewer devices than the spec
        asks for: bucket snapping still applies, sharding does not."""
        mesh = self._mesh_for_place()
        if mesh is None:
            return stacked
        from ..parallel.sharding import place_batch
        placed = place_batch(stacked, mesh)
        self.stats.inc("placed_batches")
        return placed

    def _mesh_for_place(self):
        if not self.mesh_spec or self._mesh_failed:
            return self._mesh
        if self._mesh is None:
            try:
                from ..parallel.mesh import mesh_from_spec
                self._mesh = mesh_from_spec(self.mesh_spec)
            except Exception as exc:  # noqa: BLE001 — degrade, keep serving
                self._mesh_failed = True
                logger.warning(
                    "%s: mesh %s unavailable (%s); buckets stay snapped "
                    "but batches are not mesh-placed", self.name,
                    self.mesh_spec, exc)
        return self._mesh

    def complete(self, batch: List[Request], outputs: Sequence[Any]) -> None:
        """Demux: row ``i`` of every output tensor goes back to the
        request that contributed input row ``i`` (padded rows have no
        request and are dropped). A failing per-row callback (its client
        died mid-reply) must not starve the other rows of the batch."""
        now = time.monotonic()
        import jax
        # ONE batched D2H transfer for every device output (host arrays
        # pass through device_get untouched) — a per-array np.asarray
        # here is an implicit __array__ sync per tensor per batch
        hosts = [np.asarray(o) for o in jax.device_get(list(outputs))]
        for i, req in enumerate(batch):
            row = [np.ascontiguousarray(h[i]) if h.ndim >= 1
                   and h.shape[0] >= len(batch) else h for h in hosts]
            if req.t_batched is not None:
                lat_ns = (now - req.t_batched) * 1e9
                with self._mlock:
                    self._batch_latency.add(lat_ns)
                if self.tracer is not None:
                    self.tracer.observe(f"{self.name}:batch_latency", lat_ns)
                if _obs_spans.ENABLED and req.ctx is not None:
                    dur = int(lat_ns)
                    _obs_spans.record_span(f"{self.name}:batch", "compute",
                                           time.time_ns() - dur, dur, req.ctx)
                    req.ctx.c_ns += dur
            if req.on_result is None:
                continue
            try:
                req.on_result(req, row)
            except Exception:  # noqa: BLE001 — one dead client, not a batch
                with self._mlock:
                    self.stats.inc("result_errors")
                logger.warning("%s: result callback failed for stream %s",
                               self.name, req.stream_id, exc_info=True)
        with self._mlock:
            self.stats.inc("completed", len(batch))

    # -- metrics -----------------------------------------------------------
    def occupancy(self) -> Dict[str, Any]:
        """O(1) load snapshot for fleet routing: queue depth + active
        streams (batcher), rolling bucket occupancy, and the queue-delay
        p50. Cheap enough to piggyback on every PONG heartbeat reply
        and on the broker REGISTER advertisement."""
        b = self.batcher.occupancy()
        with self._mlock:
            s = self.stats.snapshot()
            qd = self._queue_delay.percentiles()
        filled = s["bucket_rows"] - s["rows_padded"]
        return {"depth": b["depth"], "streams": b["streams"],
                "occupancy_avg": round(filled / s["bucket_rows"], 4)
                if s["bucket_rows"] else 0.0,
                "queue_delay_us_p50": round(qd["p50"] / 1e3, 1),
                # the tail the autoscaler's control law acts on (its
                # target is a p95, not a median)
                "queue_delay_us_p95": round(qd["p95"] / 1e3, 1)}

    def report(self) -> Dict[str, Any]:
        """Occupancy, queue delay and batch latency percentiles, shed
        counts — the per-batch observability the ISSUE's serving stack
        promises (also mirrored into an attached Tracer)."""
        b = self.batcher.stats.snapshot()
        with self._mlock:
            s = self.stats.snapshot()
            qd = self._queue_delay.percentiles()
            bl = self._batch_latency.percentiles()
        filled = s["bucket_rows"] - s["rows_padded"]
        mesh_info = {}
        if self.mesh_spec:
            mesh_info = {"mesh": self.mesh_spec,
                         "buckets": list(self.batcher.buckets),
                         "devices": len(self._mesh.devices.ravel())
                         if self._mesh is not None else 0,
                         "placed_batches": s.get("placed_batches", 0)}
        return {
            **mesh_info,
            "batches": b["batches"],
            "requests": b["submitted"],
            "completed": s["completed"],
            "shed_admission": b["shed_admission"],
            "shed_deadline": b["shed_deadline"],
            "cancelled": b["cancelled"],
            "shed_failed": s["shed_failed"],
            "result_errors": s["result_errors"],
            "invoke_errors": s["invoke_errors"],
            "occupancy_avg": (filled / s["bucket_rows"]
                              if s["bucket_rows"] else 0.0),
            "queue_delay_us": {k: v / 1e3 for k, v in qd.items()},
            "batch_latency_us": {k: v / 1e3 for k, v in bl.items()},
        }

    # -- standalone worker mode --------------------------------------------
    def start(self) -> None:
        """Spawn the worker loop (standalone embedding only: requires
        ``invoke_fn``). Pipeline elements drive next_batch/complete
        themselves and never call this."""
        if self._invoke_fn is None:
            raise ValueError(f"{self.name}: start() needs an invoke_fn")
        self._stop_evt.clear()
        self._thread = threading.Thread(target=self._worker,
                                        name=f"serve:{self.name}",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None \
                and self._thread is not threading.current_thread():
            self._thread.join(timeout=5.0)
            self._thread = None

    def _worker(self) -> None:
        while not self._stop_evt.is_set():
            nb = self.next_batch(self._stop_evt)
            if nb is None:
                return
            batch, _bucket, stacked = nb
            try:
                outputs = self._invoke_fn(stacked)
            except Exception as exc:  # noqa: BLE001 — shed the batch, keep serving
                with self._mlock:
                    self.stats.inc("invoke_errors")
                    # the batch's rows left the queue but will never
                    # complete(): count their terminal event so the
                    # settlement identity balances
                    self.stats.inc("shed_failed", len(batch))
                logger.warning("%s: invoke failed (%r), batch of %d shed",
                               self.name, exc, len(batch), exc_info=True)
                _obs_events.emit("shed", source=self.name, reason="invoke",
                                 frames=len(batch))
                for r in batch:
                    if r.on_shed is not None:
                        r.on_shed(r)
                continue
            self.complete(batch, outputs)
