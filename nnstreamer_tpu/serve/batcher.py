"""Dynamic-batching core: coalesce concurrent request streams into
TPU-shaped batches.

Three invariants drive the design:

* **Bucketed sizes.** A batch is always padded up to one of a small set
  of ``buckets`` (e.g. 1/2/4/8), so the downstream filter's jit cache
  sees at most ``len(buckets)`` input signatures instead of one per
  occupancy — on XLA a new signature is a multi-second compile, a padded
  row is nearly free.
* **Max-wait deadline.** A lone request never stalls waiting for
  companions: the oldest queued request bounds how long a partial batch
  may wait before it flushes at whatever occupancy it reached.
* **Bounded admission.** Each stream owns a bounded queue slot budget;
  a stream that outruns the TPU is shed at submit time (retry-after
  backpressure) instead of growing an unbounded backlog, and a request
  whose deadline expired before batching is shed rather than invoked.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Sequence

import numpy as np

from ..obs import events as _obs_events
from ..utils.atomic import Counters


class Request:
    """One in-flight inference request with its reply route.

    The object itself is the correlation id inside the process (batch
    rows carry the ``Request``); ``stream_id``/``seq`` are the wire-level
    correlation echoed back to remote clients.
    """

    __slots__ = ("stream_id", "seq", "arrays", "pts", "deadline",
                 "on_result", "on_shed", "t_arrival", "t_batched", "ctx")

    def __init__(self, stream_id: Any, arrays: Sequence[Any], *,
                 seq: Optional[int] = None, pts: Optional[int] = None,
                 deadline: Optional[float] = None,
                 on_result: Optional[Callable] = None,
                 on_shed: Optional[Callable] = None,
                 ctx: Optional[Any] = None):
        self.stream_id = stream_id
        self.arrays = [np.asarray(a) for a in arrays]
        self.seq = seq
        self.pts = pts
        self.deadline = deadline          # absolute monotonic, None = none
        self.on_result = on_result        # (request, [row arrays]) -> None
        self.on_shed = on_shed            # (request) -> None
        self.t_arrival = time.monotonic()
        self.t_batched: Optional[float] = None
        self.ctx = ctx                    # obs TraceContext riding the frame

    def signature(self):
        return tuple((a.shape, a.dtype.str) for a in self.arrays)


def stack_requests(requests: List[Request], bucket: int) -> List[np.ndarray]:
    """Stack request tensors into leading-dim-``bucket`` arrays, padding
    short batches by repeating the last row (one compiled signature per
    bucket; a padded MXU row is nearly free next to a recompile)."""
    rows = requests + [requests[-1]] * (bucket - len(requests))
    return [np.stack([r.arrays[j] for r in rows])
            for j in range(len(requests[0].arrays))]


class BucketBatcher:
    """Coalesces submitted requests into stackable, bucketed batches.

    Thread-safe: any number of producers call :meth:`submit`; one
    consumer (the serving loop) calls :meth:`next_batch`. Shed callbacks
    fire outside the lock.
    """

    def __init__(self, buckets: Sequence[int] = (1, 2, 4, 8),
                 max_wait_s: float = 0.005, max_queue: int = 16,
                 snap_multiple: int = 1):
        # mesh-aware bucket policy: every bucket snaps UP to a multiple
        # of the data-parallel degree, so a stacked batch always lays
        # out batch-major across the mesh (dim 0 divisible by dp) and
        # the jit cache still sees one signature per bucket. Snapping
        # can only merge buckets (1,2,4,8 @ dp=4 -> 4,8); padded rows
        # are accounted exactly as before (bucket - len(batch)).
        snap = max(1, int(snap_multiple))
        buckets = sorted({-(-int(b) // snap) * snap
                          for b in buckets if int(b) > 0})
        if not buckets:
            raise ValueError("buckets must name at least one positive size")
        self.buckets = buckets
        self.snap_multiple = snap
        self.max_wait_s = max(0.0, float(max_wait_s))
        self.max_queue = max(1, int(max_queue))
        self._cond = threading.Condition()
        self._fifo: Deque[Request] = deque()
        self._per_stream: Dict[Any, int] = {}
        self._draining = False
        self.stats = Counters(submitted=0, batches=0, shed_admission=0,
                              shed_deadline=0, cancelled=0)

    # -- producers ---------------------------------------------------------
    def submit(self, req: Request) -> bool:
        """Admit a request; False = shed at admission (the stream's queue
        budget is exhausted — backpressure, the caller owes the client a
        retry-after). The shed callback is NOT invoked here so the caller
        can decide how to answer."""
        with self._cond:
            if self._draining:
                # admission is closed: everything already queued will
                # flush, but new work is shed (retry elsewhere/later)
                self.stats.inc("shed_admission")
                return False
            n = self._per_stream.get(req.stream_id, 0)
            if n >= self.max_queue:
                self.stats.inc("shed_admission")
                return False
            self._per_stream[req.stream_id] = n + 1
            self._fifo.append(req)
            self.stats.inc("submitted")
            self._cond.notify_all()
        return True

    def drain(self) -> None:
        """Enter drain: stop admitting, flush what is queued. From here
        :meth:`submit` sheds everything, partial batches flush without
        waiting out max-wait, and :meth:`next_batch` returns None once
        the FIFO is empty — the consumer's EOS barrier."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()

    @property
    def draining(self) -> bool:
        with self._cond:
            return self._draining

    def cancel_stream(self, stream_id: Any) -> int:
        """Reclaim every queued slot of a dead stream (client disconnect
        mid-request must not wedge the batcher or leak its slots)."""
        with self._cond:
            kept = [r for r in self._fifo if r.stream_id != stream_id]
            n = len(self._fifo) - len(kept)
            self._fifo = deque(kept)
            self._per_stream.pop(stream_id, None)
            self.stats.inc("cancelled", n)
        return n

    def cancel_requests(self, reqs: Sequence[Request]) -> int:
        """Remove specific still-queued requests (identity match — the
        Request object IS the in-process correlation id). Used by the
        ROI gate to reclaim a shed frame's sibling crops; each removal
        counts as ``cancelled`` so the frame's settlement stays exact.
        Requests already popped into a batch are not cancellable."""
        with self._cond:
            drop = {id(r) for r in reqs}
            removed = [r for r in self._fifo if id(r) in drop]
            if not removed:
                return 0
            self._fifo = deque(r for r in self._fifo
                               if id(r) not in drop)
            for r in removed:
                n = self._per_stream.get(r.stream_id, 1) - 1
                if n <= 0:
                    self._per_stream.pop(r.stream_id, None)
                else:
                    self._per_stream[r.stream_id] = n
            self.stats.inc("cancelled", len(removed))
        return len(removed)

    def depth(self, stream_id: Any = None) -> int:
        with self._cond:
            if stream_id is None:
                return len(self._fifo)
            return self._per_stream.get(stream_id, 0)

    def occupancy(self) -> Dict[str, int]:
        """One consistent (depth, active streams) snapshot — the
        batcher's half of the fleet router's load report (two depth()
        calls could tear across a batch pop)."""
        with self._cond:
            return {"depth": len(self._fifo),
                    "streams": len(self._per_stream)}

    def ledger(self) -> List[Dict[str, Any]]:
        """Identity of every admitted-but-unbatched request — the
        pending ledger a preemption snapshot records so a restarted
        replica can DECLARE what was in flight (the router's failover
        re-dispatches them; a late duplicate settles as an orphan)."""
        with self._cond:
            return [{"stream": r.stream_id, "seq": r.seq, "pts": r.pts}
                    for r in self._fifo]

    # -- the consumer ------------------------------------------------------
    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n (the largest bucket caps a run)."""
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def next_batch(self, stop: Optional[threading.Event] = None,
                   poll_s: float = 0.05) -> Optional[List[Request]]:
        """Block until a batch is ready: the largest bucket fills with
        stackable requests, or the oldest request's max-wait expires.
        Expired-deadline requests are shed here (callbacks fire after the
        lock drops). Returns None when ``stop`` is set."""
        shed: List[Request] = []
        try:
            with self._cond:
                while True:
                    if stop is not None and stop.is_set():
                        return None
                    now = time.monotonic()
                    self._shed_expired_locked(now, shed)
                    if not self._fifo:
                        if self._draining:
                            return None  # drained dry: the EOS barrier
                        self._cond.wait(timeout=poll_s)
                        continue
                    head = self._fifo[0]
                    run = self._stackable_run(self.buckets[-1])
                    flush_at = head.t_arrival + self.max_wait_s
                    if run >= self.buckets[-1] or now >= flush_at \
                            or self._draining:
                        batch = [self._fifo.popleft() for _ in range(run)]
                        for r in batch:
                            n = self._per_stream.get(r.stream_id, 1) - 1
                            if n <= 0:
                                self._per_stream.pop(r.stream_id, None)
                            else:
                                self._per_stream[r.stream_id] = n
                            r.t_batched = now
                        self.stats.inc("batches")
                        return batch
                    timeout = flush_at - now
                    nearest = min((r.deadline for r in self._fifo
                                   if r.deadline is not None), default=None)
                    if nearest is not None:
                        timeout = min(timeout, nearest - now)
                    self._cond.wait(timeout=max(0.0, min(timeout, poll_s)))
        finally:
            if shed:
                _obs_events.emit("shed", source="batcher",
                                 reason="deadline", frames=len(shed))
            for r in shed:
                if r.on_shed is not None:
                    r.on_shed(r)

    def _shed_expired_locked(self, now: float, out: List[Request]) -> None:
        if not any(r.deadline is not None and now >= r.deadline
                   for r in self._fifo):
            return
        kept: List[Request] = []
        for r in self._fifo:
            if r.deadline is not None and now >= r.deadline:
                out.append(r)
                n = self._per_stream.get(r.stream_id, 1) - 1
                if n <= 0:
                    self._per_stream.pop(r.stream_id, None)
                else:
                    self._per_stream[r.stream_id] = n
            else:
                kept.append(r)
        self._fifo = deque(kept)
        self.stats.inc("shed_deadline", len(out))

    def _stackable_run(self, cap: int) -> int:
        """Length of the stackable run at the head of the FIFO: requests
        with a different tensor signature stay queued and open the NEXT
        batch (heterogeneous clients work, they just don't share one)."""
        head_sig = self._fifo[0].signature()
        run = 1
        for r in itertools.islice(self._fifo, 1, None):
            if run >= cap or r.signature() != head_sig:
                break
            run += 1
        return run
