"""tensor_serve_src / tensor_serve_sink — the serving-stack edge.

``tensor_serve_src ! tensor_filter ... ! tensor_serve_sink`` is the
server pipeline: the src accepts N concurrent clients speaking the same
wire protocol as ``tensor_query_client``, admits each frame through the
ServeScheduler (bounded per-stream queues), coalesces admitted requests
into bucketed padded batches, and the sink demuxes each batch row's
result back to the client that asked. Shed requests (admission or
deadline) are answered immediately with a SHED message carrying a
retry-after hint, which the query client surfaces as an upstream
QosEvent.

Against the per-request ``tensor_query_serversrc`` path this is the
"serving stack": the jit cache sees at most ``len(buckets)`` signatures,
a lone request flushes after ``max-wait-ms``, and a client that outruns
the TPU is shed instead of growing an unbounded backlog.
"""
from __future__ import annotations

import socket
import threading
import uuid
from typing import Dict, Optional, Tuple

import numpy as np

from ..edge import wire
from ..edge.protocol import MsgKind, recv_msg, send_msg, sever_socket as _sever
from ..obs import context as _obs_ctx
from ..obs import events as _obs_events
from ..pipeline.element import SinkElement, SrcElement
from ..pipeline.pad import Pad
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer, Chunk
from ..tensors.caps import Caps
from ..utils.log import logger
from .batcher import Request
from .scheduler import (ServeScheduler, get_scheduler, register_scheduler,
                        unregister_scheduler)

_FLEX_CAPS = "other/tensors,format=flexible"


@register_element("tensor_serve_src")
class TensorServeSrc(SrcElement):
    """Serving entry: N client connections -> one bucketed batch stream.

    Each created buffer is one padded batch: chunks carry the stacked
    request tensors, ``serve_rows`` extras carry the originating
    requests (the demux correlation), and ``batch_valid_rows`` tells the
    filter how many rows are real (padded host rows are sliced off
    before D2H, exactly like the query micro-batch path).

    ``mesh=DxSxT`` makes the serve path mesh-aware: buckets snap up to
    multiples of the spec's data-parallel degree and every stacked
    batch is laid out batch-major across the mesh BEFORE dispatch, so
    a downstream ``custom=mesh:...`` filter runs one sharded invoke
    per bucket (see Documentation/parallel.md).
    """

    PROPS = {"host": "localhost", "port": 3001, "id": 0, "timeout": 10.0,
             # HYBRID: advertise (topic -> host:port) on the discovery
             # broker at dest-host:dest-port, with occupancy metadata so
             # a fleet router can seed its least-loaded dispatch
             "connect-type": "TCP", "topic": "",
             "dest-host": "localhost", "dest-port": 0,
             # bucketed batch sizes, ascending; one jit signature each
             "buckets": "1,2,4,8",
             # a partial batch flushes when its oldest request has
             # waited this long (a lone request never stalls)
             "max-wait-ms": 5.0,
             # bounded per-stream queue: admission control / backpressure
             "max-queue": 16,
             # 0 = no deadline; else queued requests older than this are
             # shed with a retry-after instead of invoked
             "deadline-ms": 0.0,
             # the retry-after hint carried by SHED replies
             "retry-after-ms": 50.0,
             # mesh-aware serving ("DxSxT"/"auto", matching the
             # downstream filter's custom=mesh:...): buckets snap up to
             # multiples of the data-parallel degree and each stacked
             # batch is device_put batch-major across the mesh before
             # dispatch — one sharded invoke per batch. "" = per-chip.
             "mesh": "",
             # disaggregated LLM serving: advertise this replica's phase
             # ("prefill" | "decode" | "both"; "" = not an LLM replica)
             # so the fleet router can steer prompt frames to prefill
             # capacity and pin each stream's decode home
             "llm-role": "",
             # model/config version tag, advertised on REGISTER and
             # every PONG load report: the fleet's blue/green rollout
             # verifies the whole ring converged on the new version
             # before retiring the old one ("" = unversioned)
             "version": ""}

    # the scheduler records queue_wait + batch spans on the request ctx
    SPAN_POINTS = ("queue-wait", "batch", "chain")

    # conservation identities flowcheck proves statically and
    # check_identities() asserts over live report() snapshots
    SETTLEMENT_IDENTITY = ("serve-settlement", "roi-settlement")

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._next_client = [0]
        # checkpoint/: pending ledger + session ids recovered by
        # restore_state, applied at start() (REGISTER advertises the
        # restored sessions so the fleet knows this replica resurrected)
        self._restored: Optional[Dict] = None
        # cid -> (conn, send lock, negotiated wire config): replies come
        # from the sink's streaming thread, sheds from the batcher and
        # recv threads — the per-connection lock keeps wire frames
        # atomic; the config (None = plain v1 peer) is rebound under
        # _clock once the client's CAPS advertisement arrives
        self._conns: Dict[int, Tuple[socket.socket, threading.Lock,
                                     Optional[wire.WireConfig]]] = {}
        self._clock = threading.Lock()
        self.scheduler: Optional[ServeScheduler] = None
        self._broker_sock: Optional[socket.socket] = None
        # per-incarnation token (reminted by every start()), echoed in
        # CAPS_ACK so a fleet router can tell "reconnect to the same
        # process life" from "a new process at the same endpoint"
        self._instance = uuid.uuid4().hex[:12]
        self.stats["link_errors"] = 0
        self.stats.update({"serve_roi_requests": 0, "serve_roi_crops": 0,
                           "serve_roi_shed": 0, "serve_roi_results": 0})

    @property
    def bound_port(self) -> int:
        return self._listener.getsockname()[1] if self._listener else self.port

    def negotiate_src_caps(self) -> Optional[Caps]:
        return Caps(_FLEX_CAPS)

    def static_src_caps(self) -> Optional[Caps]:
        """Flexible tensors (bucketed padded batches, shapes per
        request); the jit cache sees at most len(buckets) signatures."""
        return Caps(_FLEX_CAPS)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        self._instance = uuid.uuid4().hex[:12]
        self.scheduler = ServeScheduler(
            buckets=[int(b) for b in str(self.buckets).split(",") if b],
            max_wait_s=float(self.max_wait_ms) / 1e3,
            max_queue=int(self.max_queue),
            deadline_s=float(self.deadline_ms) / 1e3,
            name=self.name, mesh_spec=str(self.mesh))
        if self._restored is not None:
            # declare (never replay) the pre-crash pending ledger: reply
            # routes died with the old process, the router's failover
            # owns re-dispatch, late duplicates settle as orphans
            self.scheduler.record_recovered(self._restored.get("ledger"))
        register_scheduler(self.id, self.scheduler)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, self.port))
        self._listener.listen(64)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"serve-accept:{self.name}",
            daemon=True)
        self._accept_thread.start()
        if str(self.connect_type).upper() == "HYBRID":
            # hold the registration connection open for our lifetime
            # (the broker drops the advertisement the moment it closes);
            # the metadata seeds a fleet router's least-loaded dispatch
            try:
                self._broker_sock = socket.create_connection(
                    (self.dest_host or "localhost", int(self.dest_port)),
                    timeout=self.timeout)
                reg_meta = dict(self.scheduler.occupancy(), role="serve")
                if str(self.llm_role):
                    reg_meta["llm_role"] = str(self.llm_role)
                if str(self.version):
                    reg_meta["version"] = str(self.version)
                if self._restored is not None:
                    # resurrection announcement: the router counts these
                    # and knows the replica carries restored session ids
                    reg_meta["restored_sessions"] = list(
                        self._restored.get("sessions") or [])
                send_msg(self._broker_sock, MsgKind.REGISTER,
                         {"topic": self.topic, "host": self.host,
                          "port": self.bound_port, "meta": reg_meta})
            except OSError:
                # don't leak a half-started server: closing the listener
                # also terminates the accept thread
                if self._broker_sock is not None:
                    try:
                        self._broker_sock.close()
                    except OSError:
                        pass
                    self._broker_sock = None
                try:
                    self._listener.close()
                except OSError:
                    pass
                self._listener = None
                unregister_scheduler(self.id)
                raise
        self._restored = None
        super().start()

    def stop(self) -> None:
        super().stop()
        unregister_scheduler(self.id)
        if self._broker_sock is not None:
            try:
                self._broker_sock.close()
            except OSError:
                pass
            self._broker_sock = None
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
        with self._clock:
            victims = list(self._conns.values())
            self._conns.clear()
        for conn, _, _ in victims:
            try:
                conn.close()
            except OSError:
                pass

    # -- client side -------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop_evt.is_set():
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return
            try:
                wire.tune_socket(conn)
            except OSError:
                # peer died between accept and setsockopt: close the
                # fd instead of leaking it
                conn.close()
                continue
            cid = self._next_client[0]
            self._next_client[0] += 1
            with self._clock:
                self._conns[cid] = (conn, threading.Lock(), None)
            threading.Thread(target=self._client_loop, args=(conn, cid),
                             name=f"serve-client{cid}:{self.name}",
                             daemon=True).start()

    def _client_loop(self, conn: socket.socket, cid: int) -> None:
        # a per-op timeout detects half-open (silently dead) peers: a
        # live-but-idle client just times out between messages and loops
        conn.settimeout(max(0.1, float(self.timeout)))
        try:
            while not self._stop_evt.is_set():
                try:
                    kind, meta, payloads = recv_msg(conn, stats=self.stats)
                except TimeoutError:
                    continue  # idle keep-alive; re-check stop
                if kind == MsgKind.CAPS:
                    # wire v2 negotiation: fold the client's advertised
                    # codec/precision wish into the link config and echo
                    # the choice; a client without a "wire" block is a
                    # v1 peer and gets plain v1 replies
                    cfg = wire.negotiate(meta.get("wire"))
                    with self._clock:
                        entry = self._conns.get(cid)
                        if entry is not None:
                            self._conns[cid] = (entry[0], entry[1], cfg)
                    ack = {"caps": _FLEX_CAPS, "client_id": cid,
                           "instance": self._instance}
                    if cfg is not None:
                        ack["wire"] = cfg.to_meta()
                    send_msg(conn, MsgKind.CAPS_ACK, ack)
                elif kind == MsgKind.DATA:
                    self._admit(cid, meta, payloads)
                elif kind == MsgKind.DATA_BATCH:
                    for b in wire.unpack_batch(meta, payloads,
                                               stats=self.stats):
                        self._admit_buf(cid, b, b.extras.get("seq"))
                elif kind == MsgKind.PING:
                    # heartbeat reply doubles as a load report: the
                    # fleet router's least-loaded tiebreak reads the
                    # occupancy snapshot it carries (uses the per-conn
                    # send lock — a PONG must not interleave with a
                    # RESULT the sink thread is writing)
                    load = (self.scheduler.occupancy()
                            if self.scheduler is not None else {})
                    if str(self.llm_role):
                        load = dict(load, llm_role=str(self.llm_role))
                    if str(self.version):
                        load = dict(load, version=str(self.version))
                    self._send(cid, MsgKind.PONG,
                               {"t": meta.get("t"), "load": load})
                elif kind == MsgKind.EOS:
                    break
        except (ConnectionError, OSError, ValueError) as exc:
            # routine client death, but logged + counted (never a bare
            # discard): flapping clients must show up in stats()
            self.stats.inc("link_errors")
            logger.info("%s: client %d connection ended: %r",
                        self.name, cid, exc)
        finally:
            # slot reclamation: a stream that dies mid-request must not
            # wedge the batcher or leak its queued slots
            self._drop_client(cid)
            try:
                conn.close()
            except OSError:
                pass

    def _admit(self, cid: int, meta, payloads) -> None:
        buf = wire.unpack_buffer(meta, payloads, stats=self.stats)
        roi = meta.get("delta_roi")
        if roi and roi.get("rois"):
            self._admit_roi(cid, buf, meta.get("seq"), roi)
            return
        self._admit_buf(cid, buf, meta.get("seq"))

    def _admit_buf(self, cid: int, buf: Buffer, seq) -> None:
        self.scheduler.submit(
            cid, [c.host() for c in buf.chunks],
            seq=seq, pts=buf.pts,
            on_result=self._on_result, on_shed=self._on_shed,
            ctx=_obs_ctx.ctx_of(buf))

    # -- ROI-gated admission (tensor_delta mode=roi upstream) --------------
    def _admit_roi(self, cid: int, buf: Buffer, seq, roi: dict) -> None:
        """One DATA frame carrying N changed-tile crops becomes N
        single-crop submissions through the bucketed batcher — the
        unchanged tiles were never shipped, and here they are never
        *inferred* either.  One RESULT goes back once every crop's row
        lands (the echoed ``delta_roi`` block lets the client-side
        tensor_delta_stitch scatter the rows over its cached canvas)."""
        crops = buf.chunks[0].host()
        n = int(crops.shape[0])
        self.stats.add(serve_roi_requests=1, serve_roi_crops=n)
        agg = {"rows": [None] * n, "left": n, "settled": False,
               "lock": threading.Lock(), "roi": roi, "pts": buf.pts,
               "seq": seq, "reqs": []}
        ctx = _obs_ctx.ctx_of(buf)
        for k in range(n):
            with agg["lock"]:
                if agg["settled"]:
                    # an earlier crop already shed the frame (admission
                    # shed runs its callback inline): stop feeding the
                    # batcher work whose results would be discarded
                    break
            req = self.scheduler.admit(
                cid, [np.ascontiguousarray(crops[k])],
                seq=seq, pts=buf.pts,
                on_result=lambda req, row, k=k, agg=agg:
                    self._roi_part(cid, agg, k, row),
                on_shed=lambda req, agg=agg: self._roi_shed(cid, agg),
                ctx=ctx)
            if req is not None:
                with agg["lock"]:
                    agg["reqs"].append(req)
        # the shed may have landed between the final admit and here:
        # reclaim whatever siblings are still queued (idempotent)
        with agg["lock"]:
            siblings = list(agg["reqs"]) if agg["settled"] else []
        if siblings:
            self.scheduler.cancel_requests(siblings)

    def _roi_part(self, cid: int, agg: dict, k: int, row) -> None:
        with agg["lock"]:
            if agg["settled"]:
                return  # a sibling crop was shed; the SHED already went
            agg["rows"][k] = list(row)
            agg["left"] -= 1
            if agg["left"] > 0:
                return
            agg["settled"] = True
        # frame-level terminal: exactly one RESULT per ROI request
        # (roi-settlement identity: requests == results + shed + pending)
        self.stats.inc("serve_roi_results")
        rows = agg["rows"]
        stacked = [np.stack([r[j] for r in rows])
                   for j in range(len(rows[0]))]
        with self._clock:
            entry = self._conns.get(cid)
        cfg = entry[2] if entry is not None else None
        reply = Buffer.from_arrays(stacked, pts=agg["pts"])
        meta, payloads = wire.pack_buffer(reply, cfg, stats=self.stats)
        meta["client_id"] = cid
        meta["seq"] = agg["seq"]
        meta["delta_roi"] = agg["roi"]
        self._send(cid, MsgKind.RESULT, meta, payloads)

    def _roi_shed(self, cid: int, agg: dict) -> None:
        """Any shed crop sheds the whole frame: a partial stitch would
        silently mix epochs. Exactly one SHED answers the request, and
        the frame's still-queued sibling crops are cancelled — leaving
        them would burn TPU batches on rows whose frame already died."""
        with agg["lock"]:
            if agg["settled"]:
                return
            agg["settled"] = True
            siblings = list(agg["reqs"])
        if siblings:
            self.scheduler.cancel_requests(siblings)
        self.stats.inc("serve_roi_shed")
        self._send(cid, MsgKind.SHED,
                   {"pts": agg["pts"], "seq": agg["seq"], "client_id": cid,
                    "retry_after_ms": float(self.retry_after_ms)})

    # -- reply side (called by the scheduler's demux) ----------------------
    def _on_result(self, req: Request, row) -> None:
        # encode under the client's negotiated link config (None = v1:
        # byte-identical to the old raw framing, minus the copies)
        with self._clock:
            entry = self._conns.get(req.stream_id)
        cfg = entry[2] if entry is not None else None
        reply = Buffer.from_arrays(list(row), pts=req.pts)
        if req.ctx is not None:
            # the reply carries the request's trace context home so the
            # client-side sink attributes the whole journey
            _obs_ctx.attach(reply, req.ctx)
        meta, payloads = wire.pack_buffer(reply, cfg, stats=self.stats)
        meta["client_id"] = req.stream_id
        meta["seq"] = req.seq
        self._send(req.stream_id, MsgKind.RESULT, meta, payloads)

    def _on_shed(self, req: Request) -> None:
        # backpressure on the wire: the client translates this into an
        # upstream QosEvent and a retry-after spacing hint
        self._send(req.stream_id, MsgKind.SHED,
                   {"pts": req.pts, "seq": req.seq,
                    "client_id": req.stream_id,
                    "retry_after_ms": float(self.retry_after_ms)})

    def _send(self, cid, kind, meta, payloads=()) -> None:
        with self._clock:
            entry = self._conns.get(cid)
        if entry is None:
            logger.warning("%s: no connection for client %s", self.name, cid)
            return
        conn, lock, _cfg = entry
        try:
            with lock:
                send_msg(conn, kind, meta, payloads, stats=self.stats)
        except (ConnectionError, OSError):
            self._drop_client(cid)

    def _drop_client(self, cid) -> None:
        with self._clock:
            self._conns.pop(cid, None)
        if self.scheduler is not None:
            n = self.scheduler.cancel_stream(cid)
            if n:
                logger.info("%s: client %s died, reclaimed %d queued "
                            "slot(s)", self.name, cid, n)

    # -- graceful teardown / chaos hooks -----------------------------------
    def drain(self) -> None:
        """Graceful teardown: close scheduler admission (late frames
        shed with retry-after), tell every client DRAIN so it stops
        sending and settles, and flush everything already admitted
        through the batcher -> filter -> sink demux behind the EOS
        barrier (next_batch returns None once the queue is dry). Every
        pending correlation is answered — RESULT or SHED — before the
        pipeline closes."""
        super().drain()
        _obs_events.emit("drain", source=self.name, element=self,
                         clients=len(self._conns))
        if self.scheduler is not None:
            self.scheduler.drain()
        with self._clock:
            entries = list(self._conns.items())
        for cid, (conn, lock, _cfg) in entries:
            try:
                with lock:
                    send_msg(conn, MsgKind.DRAIN,
                             {"client_id": cid,
                              "retry_after_ms": float(self.retry_after_ms)})
            except (ConnectionError, OSError):
                pass

    def drain_flushed(self) -> bool:
        # the streaming loop may only stop once everything admitted has
        # been batched out (create()'s next_batch -> None is the same
        # barrier; this keeps the loop-head check honest)
        return self.scheduler is None or self.scheduler.pending() == 0

    def kill_link(self) -> int:
        """Chaos hook (tensor_fault mode=kill-link): force-close every
        live client connection mid-stream, exactly like the server side
        of a network partition. Clients reconnect and replay their
        pending correlations."""
        with self._clock:
            victims = list(self._conns.values())
        for conn, _lock, _cfg in victims:
            _sever(conn)
        self.stats.inc("link_kills", len(victims))
        return len(victims)

    # -- checkpoint/restore (checkpoint/) ----------------------------------
    CHECKPOINTABLE = ("the pending-request ledger (declared, not "
                      "replayed) + connected client ids")

    def snapshot_state(self, snap_dir):
        if self.scheduler is None:
            return self._restored  # restored but never started: re-emit
        ledger = self.scheduler.pending_ledger()
        with self._clock:
            sessions = sorted(self._conns)
        if not ledger and not sessions:
            return None
        return {"ledger": ledger, "sessions": sessions}

    def restore_state(self, state, snap_dir):
        # applied at start(): the fresh scheduler records the recovered
        # ledger and REGISTER advertises restored_sessions to the fleet
        self._restored = state

    def preempt_inflight(self) -> int:
        # admitted-but-unsettled requests abandoned by a degraded
        # (no-drain) preemption — declared in the preempt report
        return self.scheduler.pending() if self.scheduler is not None else 0

    # -- the src loop ------------------------------------------------------
    def create(self) -> Optional[Buffer]:
        if self.scheduler.tracer is None:
            self.scheduler.tracer = getattr(self.pipeline, "tracer", None)
        nb = self.scheduler.next_batch(self._stop_evt)
        if nb is None:
            return None
        batch, _bucket, stacked = nb
        out = Buffer([Chunk(x) for x in stacked], pts=batch[0].pts)
        out.extras["serve_rows"] = batch
        out.extras["serve_id"] = self.id
        # the filter's failure paths (invoke error, breaker-open shed)
        # settle rows via on_shed but the scheduler never sees a demuxed
        # result for them — this handle lets the filter report them as
        # shed_failed so the settlement identity stays balanced
        out.extras["serve_sched"] = self.scheduler
        # the filter slices padded HOST rows off before any D2H
        out.extras["batch_valid_rows"] = len(batch)
        if batch[0].ctx is not None:
            # batch adoption: the fused-segment spans downstream join the
            # first request's trace tree (the other rows stay connected
            # through their own queue_wait/batch spans)
            _obs_ctx.attach(out, batch[0].ctx.child())
        return out


@register_element("tensor_serve_sink")
class TensorServeSink(SinkElement):
    """Serving exit: hands each result batch back to the scheduler's
    demux, which routes row i to the stream that contributed input row i
    (correlation rides IN the buffer as the originating requests)."""

    PROPS = {"id": 0}

    def handle_event(self, pad, event) -> None:
        from ..pipeline.events import CapsEvent
        if isinstance(event, CapsEvent):
            pad.set_caps(event.caps)
            return
        super().handle_event(pad, event)

    def render(self, buf: Buffer) -> None:
        rows = buf.extras.get("serve_rows")
        if not rows:
            logger.warning("%s: buffer without serve_rows dropped", self.name)
            return
        sched = get_scheduler(buf.extras.get("serve_id", self.id))
        hosts = [c.host() for c in buf.chunks]
        if sched is None:
            # server stopping: requests are orphaned, nothing to answer
            return
        sched.complete(rows, hosts)
