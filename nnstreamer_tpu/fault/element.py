"""tensor_fault — deterministic fault injection for chaos testing.

A passthrough element that injects failures into a live pipeline on a
seeded, reproducible schedule — the chaos harness's hand on the wheel::

    ... ! tensor_fault mode=transient every=5 on-error=retry ! ...

Modes:

* ``raise``      — raise RuntimeError (classified FATAL)
* ``transient``  — raise :class:`~..errors.FaultInjected`
                   (a TransientError: retry policies apply)
* ``delay``      — sleep ``delay-ms`` then pass the buffer through
* ``corrupt``    — invert the first chunk's bytes (shape/dtype intact:
                   caps stay valid, the VALUES are garbage)
* ``drop``       — swallow the buffer (counted in ``stats['dropped']``)
* ``kill-link``  — call ``kill_link()`` on the element named by
                   ``target`` (edgesrc/edgesink, query client,
                   serversrc, servesrc): force-close its live
                   socket(s) mid-stream, then pass the buffer through.
                   The session layer's reconnect + resume must absorb
                   it with zero loss — that is the chaos assertion.

Firing: ``every=N`` fires on every Nth ``transform`` call (N>0), else
``probability=p`` fires per-call from a ``seed``-ed RNG — both replay
identically run to run. ``max-faults`` caps the total injected (-1 =
unlimited). ``stats['faults']`` counts injections, so a chaos test can
assert every injected fault is accounted for as retried/skipped/shed.

Note the every-N counter counts *calls*: when an ``on-error=retry``
policy re-runs the failed buffer, the retry is call N+1 and passes —
i.e. a transient fault heals on first retry, exactly the fault shape
retry policies exist for.
"""
from __future__ import annotations

import random
import time
from typing import Optional

import numpy as np

from ..pipeline.element import TransformElement
from ..pipeline.registry import register_element
from ..tensors.buffer import Buffer, Chunk
from .errors import FaultInjected

_MODES = ("raise", "transient", "delay", "corrupt", "drop", "kill-link")


@register_element("tensor_fault")
class TensorFault(TransformElement):
    PROPS = {"mode": "transient",
             "every": 0,          # fire on every Nth call; 0 = use probability
             "probability": 0.0,  # per-call fire probability when every=0
             "seed": 0,           # RNG seed: schedules replay exactly
             "delay-ms": 10.0,    # sleep length for mode=delay
             "max-faults": -1,    # total injection cap; -1 = unlimited
             "target": ""}        # element whose link mode=kill-link kills

    def __init__(self, name=None, **props):
        super().__init__(name, **props)
        self._rng = random.Random(int(self.seed))
        self._calls = 0
        self.stats.update({"faults": 0, "passed": 0})

    def start(self) -> None:
        super().start()
        # a restart (on-error=restart) replays the schedule from zero —
        # the element is restart-safe BECAUSE its state is just this
        self._rng = random.Random(int(self.seed))
        self._calls = 0

    def _should_fire(self) -> bool:
        self._calls += 1
        mf = int(self.max_faults)
        if 0 <= mf <= self.stats["faults"]:
            return False
        every = int(self.every)
        if every > 0:
            return self._calls % every == 0
        p = float(self.probability)
        return p > 0 and self._rng.random() < p

    def transform(self, buf: Buffer) -> Optional[Buffer]:
        if not self._should_fire():
            self.stats.inc("passed")
            return buf
        n = self.stats.inc("faults")
        mode = str(self.mode)
        if mode == "raise":
            raise RuntimeError(
                f"{self.name}: injected fatal fault #{n} "
                f"(call {self._calls})")
        if mode == "transient":
            raise FaultInjected(
                f"{self.name}: injected transient fault #{n} "
                f"(call {self._calls})")
        if mode == "delay":
            time.sleep(max(0.0, float(self.delay_ms)) / 1e3)
            return buf
        if mode == "corrupt":
            if not buf.chunks:
                return buf
            host = np.array(buf.chunks[0].host(), copy=True)
            flat = host.view(np.uint8)
            flat ^= 0xFF  # every byte inverted: loud, shape-preserving
            out = buf.with_chunks([Chunk(host)] +
                                  list(buf.chunks[1:]))
            return out
        if mode == "drop":
            self.stats.inc("dropped")
            return None
        if mode == "kill-link":
            self._kill_target_link(n)
            return buf
        raise ValueError(f"{self.name}: unknown mode {mode!r} "
                         f"(expected one of {_MODES})")

    def _kill_target_link(self, n: int) -> None:
        """Sever the target element's live socket(s): the network-
        partition fault shape the session layer must absorb. The buffer
        in hand passes through — only the LINK dies, not the stream."""
        tname = str(self.target)
        el = (self.pipeline.elements.get(tname)
              if self.pipeline is not None else None)
        kill = getattr(el, "kill_link", None)
        if not callable(kill):
            raise ValueError(
                f"{self.name}: mode=kill-link needs target= naming an "
                f"element with a kill_link() hook (got {tname!r})")
        killed = kill()
        self.post_message("warning", fault=n, target=tname,
                          links_killed=killed,
                          detail="injected link kill")
