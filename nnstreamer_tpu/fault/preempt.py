"""Preemption signal wiring: SIGTERM → drain-and-snapshot → exit.

TPU VMs are preempted with seconds of notice delivered as SIGTERM.
:class:`PreemptGuard` turns that signal into a bounded
``Pipeline.preempt(grace_s, directory)`` — quiesce, drain what the
grace budget allows, snapshot the rest, declare what was abandoned —
so a restarted process can ``Pipeline.restore(directory)`` and resume
instead of starting cold.

The handler itself only sets a flag and spawns a worker thread: the
preempt sequence joins element threads and waits on drain events,
none of which belongs inside a signal handler frame.
"""
from __future__ import annotations

import logging
import os
import signal
import threading
from typing import Callable, Dict, Optional

logger = logging.getLogger(__name__)


class PreemptGuard:
    """Installable SIGTERM handler driving one pipeline's preemption.

    Usage::

        guard = PreemptGuard(pipe, "/var/ckpt", grace_s=5.0)
        guard.install()            # from the main thread
        ...
        guard.done.wait()          # or let exit_code terminate us
        print(guard.report)

    ``exit_code`` non-None makes the guard call :func:`os._exit` once
    the snapshot is published — the clean-exit path a preempted
    replica wants (atexit hooks of a half-drained pipeline have
    nothing left to add).
    """

    def __init__(self, pipeline, directory: str, grace_s: float = 5.0,
                 retain: int = 3, exit_code: Optional[int] = None,
                 signum: int = signal.SIGTERM,
                 on_done: Optional[Callable[[Optional[Dict]], None]] = None):
        self.pipeline = pipeline
        self.directory = directory
        self.grace_s = float(grace_s)
        self.retain = int(retain)
        self.exit_code = exit_code
        self.signum = signum
        # last-words hook, called with the preempt report (None when the
        # preempt itself failed) after the snapshot publishes but BEFORE
        # os._exit — a fleet replica prints its settlement accounting
        # here so the parent can audit exact preempt_abandoned counts
        self.on_done = on_done
        self.done = threading.Event()
        self.report: Optional[Dict] = None
        self._fired = threading.Event()
        self._prev = None

    def install(self) -> "PreemptGuard":
        self._prev = signal.signal(self.signum, self._on_signal)
        return self

    def uninstall(self) -> None:
        if self._prev is not None:
            signal.signal(self.signum, self._prev)
            self._prev = None

    # -- internals ---------------------------------------------------------
    def _on_signal(self, signum, frame) -> None:
        if self._fired.is_set():
            return  # repeated SIGTERM while already draining
        self._fired.set()
        threading.Thread(target=self._run, name="preempt-guard",
                         daemon=True).start()

    def _run(self) -> None:
        try:
            self.report = self.pipeline.preempt(
                self.grace_s, self.directory, retain=self.retain)
            logger.warning("preempted: %s", self.report)
        except BaseException:
            logger.exception("preempt failed; exiting without snapshot")
        finally:
            if self.on_done is not None:
                try:
                    self.on_done(self.report)
                except BaseException:
                    logger.exception("preempt on_done hook failed")
            self.done.set()
            if self.exit_code is not None:
                os._exit(self.exit_code)


def install_sigterm(pipeline, directory: str, grace_s: float = 5.0,
                    retain: int = 3, exit_code: Optional[int] = None,
                    on_done: Optional[Callable[[Optional[Dict]], None]]
                    = None) -> PreemptGuard:
    """Convenience wrapper: build + install a :class:`PreemptGuard`."""
    return PreemptGuard(pipeline, directory, grace_s=grace_s,
                        retain=retain, exit_code=exit_code,
                        on_done=on_done).install()
