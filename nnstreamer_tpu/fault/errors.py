"""Error classification: transient vs fatal.

≙ the reference's GstFlowReturn discipline — GST_FLOW_ERROR kills the
pipeline, but element errors that are *recoverable* (a flaky socket, a
torn wire frame) are bus warnings with a retry story. An exception is
transient when retrying the same operation can plausibly succeed:
network hiccups, timeouts, torn codec frames. Everything else (shape
mismatches, programming errors, OOM) is fatal — retrying reproduces it.

Elements (and tests) signal an explicitly-retryable failure by raising
:class:`TransientError`; the registry classifies stdlib exception types
so socket/codec failures from third-party code classify correctly
without wrapping.
"""
from __future__ import annotations

import socket
from typing import Tuple, Type


class TransientError(RuntimeError):
    """An operation failed in a way that a retry can plausibly fix
    (lost packet, momentary overload, torn frame). Raise it from
    ``do_chain``/``create`` to opt a failure into retry/skip policies
    explicitly."""


class FaultInjected(TransientError):
    """Raised by the ``tensor_fault`` element in ``transient`` mode —
    a :class:`TransientError` tagged as synthetic so chaos tests can
    tell injected faults from organic ones."""


# exception types whose instances classify as transient. socket.timeout
# is an alias of TimeoutError since 3.10 but listed for clarity; codec
# errors surface as ValueError/EOFError from struct/json/numpy parsing
# of torn wire frames.
_TRANSIENT_TYPES: Tuple[Type[BaseException], ...] = (
    TransientError,
    TimeoutError,
    socket.timeout,
    ConnectionError,        # ConnectionReset/Aborted/Refused, BrokenPipe
    InterruptedError,
    BlockingIOError,
)

# fatal even if a registered transient base matches (checked first);
# e.g. a subclass someone registers too broadly can be carved back out
_FATAL_TYPES: Tuple[Type[BaseException], ...] = ()


def register_transient(*types: Type[BaseException]) -> None:
    """Extend the transient registry (module-global, like the element
    registry): deployments mapping their own codec/driver exceptions
    into retry policies register them here."""
    global _TRANSIENT_TYPES
    _TRANSIENT_TYPES = _TRANSIENT_TYPES + tuple(
        t for t in types if t not in _TRANSIENT_TYPES)


def register_fatal(*types: Type[BaseException]) -> None:
    """Mark exception types fatal even when a transient base class
    matches (fatal wins over transient)."""
    global _FATAL_TYPES
    _FATAL_TYPES = _FATAL_TYPES + tuple(
        t for t in types if t not in _FATAL_TYPES)


def is_transient(exc: BaseException) -> bool:
    """True when retrying the failed operation can plausibly succeed."""
    if _FATAL_TYPES and isinstance(exc, _FATAL_TYPES):
        return False
    return isinstance(exc, _TRANSIENT_TYPES)
