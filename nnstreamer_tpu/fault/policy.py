"""Per-element error policies for the buffer chain path.

Every element carries an ``on-error`` property (base ``Element.PROPS``)
naming what happens when its ``do_chain`` raises:

=========  ==============================================================
policy     behavior
=========  ==============================================================
fail       post the error, raise FlowError — aborts the pipeline
           (today's behavior; the default, so nothing changes unless
           a policy is asked for)
skip       drop the failing buffer, count it in ``stats['dropped']``,
           keep streaming (rate-limited bus warning)
retry      transient errors only: re-run ``do_chain`` on the SAME
           buffer up to N times with exponential backoff + jitter
           (``stats['retries']``); fatal errors and exhausted retries
           escalate to ``fail``
restart    tear the element down (``stop()``/``start()``), replay the
           negotiated caps, and re-run the buffer once; budgeted at
           most N restarts per rolling window (``stats['restarts']``)
=========  ==============================================================

Spec grammar (launch string or Python API, no spaces)::

    on-error=fail | skip | retry | retry(n[,backoff_s[,jitter]])
           | restart | restart(budget[,window_s])
"""
from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional

from ..utils.log import logger
from .backoff import Backoff, RestartBudget
from .errors import is_transient

_SPEC_RE = re.compile(
    r"^(?P<action>fail|skip|retry|restart)"
    r"(?:\((?P<args>[^)]*)\))?$")


@dataclass(frozen=True)
class ErrorPolicy:
    action: str = "fail"
    max_retries: int = 3
    backoff_s: float = 0.05
    multiplier: float = 2.0
    jitter: float = 0.5
    max_backoff_s: float = 2.0
    restart_budget: int = 3
    window_s: float = 30.0

    @classmethod
    def parse(cls, spec) -> "ErrorPolicy":
        """``"retry(5,0.01)"`` -> ErrorPolicy. Raises ValueError with
        the offending spec (pipelint surfaces it pre-launch)."""
        if isinstance(spec, ErrorPolicy):
            return spec
        text = str(spec or "fail").strip().lower()
        m = _SPEC_RE.match(text)
        if m is None:
            raise ValueError(
                f"bad on-error spec {spec!r}: expected fail | skip | "
                f"retry[(n[,backoff_s[,jitter]])] | "
                f"restart[(budget[,window_s])]")
        action = m.group("action")
        args = [a.strip() for a in (m.group("args") or "").split(",") if
                a.strip()]
        if args and action in ("fail", "skip"):
            raise ValueError(f"on-error={action} takes no arguments "
                             f"(got {spec!r})")
        try:
            if action == "retry":
                kw = {}
                if len(args) > 0:
                    kw["max_retries"] = int(args[0])
                if len(args) > 1:
                    kw["backoff_s"] = float(args[1])
                if len(args) > 2:
                    kw["jitter"] = float(args[2])
                if len(args) > 3:
                    raise ValueError("too many arguments")
                return cls(action="retry", **kw)
            if action == "restart":
                kw = {}
                if len(args) > 0:
                    kw["restart_budget"] = int(args[0])
                if len(args) > 1:
                    kw["window_s"] = float(args[1])
                if len(args) > 2:
                    raise ValueError("too many arguments")
                return cls(action="restart", **kw)
        except ValueError as exc:
            raise ValueError(f"bad on-error spec {spec!r}: {exc}") from None
        return cls(action=action)

    def make_backoff(self, seed: Optional[int] = None) -> Backoff:
        return Backoff(self.backoff_s, self.multiplier,
                       self.max_backoff_s, self.jitter, seed=seed)

    def make_budget(self) -> RestartBudget:
        return RestartBudget(self.restart_budget, self.window_s)


def policy_of(element) -> ErrorPolicy:
    """The element's parsed policy, cached against the property value
    (the property is a plain string so launch parsing stays dumb)."""
    spec = getattr(element, "on_error", "fail")
    cached = getattr(element, "_error_policy_cache", None)
    if cached is not None and cached[0] == spec:
        return cached[1]
    policy = ErrorPolicy.parse(spec)
    element._error_policy_cache = (spec, policy)
    return policy


def _warn_rate_limited(element, count: int, **data) -> None:
    # 1, 2, 4, 8, ... then every 64th — the tensor_filter invoke-error
    # convention: observable without flooding an unread bus
    if count & (count - 1) == 0 or count % 64 == 0:
        element.post_message("warning", **data)


def escalate(element, exc: Exception, **ctx) -> None:
    """Post a structured error (element, cause, policy context) and
    raise FlowError — the one place policy failures become pipeline
    failures."""
    from ..pipeline.pad import FlowError
    logger.exception("%s: error in chain (policy escalation)", element.name)
    if element.pipeline is not None:
        element.pipeline.post_message(
            "error", element=element.name, error=exc, cause=repr(exc), **ctx)
    raise FlowError(f"{element.name}: {exc}") from exc


def restart_element(element) -> None:
    """Tear down and re-start the element in place, replaying the caps
    each sink pad had negotiated so downstream re-negotiates from the
    same stream state (≙ a READY->PLAYING bounce of one element)."""
    element.stop()
    element.start()
    for pad in element.sink_pads.values():
        if pad.caps is not None:
            element.on_sink_caps(pad, pad.caps)


def handle_chain_error(element, pad, buf, exc: Exception) -> bool:
    """Apply ``element``'s policy to an exception from ``do_chain``.

    Returns True when the buffer was eventually processed (a retry or
    post-restart re-run succeeded) — the caller then does its normal
    success accounting — or False when the buffer was consumed by the
    policy (skipped). Escalations raise FlowError.
    """
    policy = policy_of(element)
    if policy.action == "skip":
        n = element.stats.inc("dropped")
        logger.warning("%s: buffer skipped by on-error=skip (%s)",
                       element.name, exc)
        _warn_rate_limited(element, n, policy="skip", dropped=n,
                           cause=repr(exc))
        return False

    if policy.action == "retry":
        if not is_transient(exc):
            escalate(element, exc, policy="retry",
                     detail="fatal (non-transient) error")
        backoff = policy.make_backoff()
        stop_evt = getattr(element, "_stop_evt", None)
        for attempt in range(1, policy.max_retries + 1):
            backoff.sleep(stop_evt)
            element.stats.inc("retries")
            _warn_rate_limited(element, element.stats["retries"],
                               policy="retry", attempt=attempt,
                               cause=repr(exc))
            try:
                element.do_chain(pad, buf)
                return True
            except Exception as exc2:  # noqa: BLE001 — classified below
                from ..pipeline.pad import FlowError
                if isinstance(exc2, FlowError):
                    raise
                exc = exc2
                if not is_transient(exc):
                    break
        escalate(element, exc, policy="retry", attempts=policy.max_retries,
                 detail="retries exhausted")

    if policy.action == "restart":
        budget = getattr(element, "_restart_budget", None)
        if budget is None:
            budget = element._restart_budget = policy.make_budget()
        if not budget.allow():
            escalate(element, exc, policy="restart",
                     attempts=budget.limit,
                     detail=f"restart budget exhausted "
                            f"({budget.limit}/{policy.window_s:g}s)")
        element.stats.inc("restarts")
        element.post_message("warning", policy="restart",
                             attempt=element.stats["restarts"],
                             cause=repr(exc))
        logger.warning("%s: restarting element after error (%s)",
                       element.name, exc)
        try:
            restart_element(element)
            element.do_chain(pad, buf)
            return True
        except Exception as exc2:  # noqa: BLE001 — one re-run, then escalate
            from ..pipeline.pad import FlowError
            if isinstance(exc2, FlowError):
                raise
            escalate(element, exc2, policy="restart",
                     detail="element failed again after restart")

    # action == "fail" (and any unknown spec caught at parse time)
    escalate(element, exc, policy="fail")
    return False  # unreachable; escalate always raises
