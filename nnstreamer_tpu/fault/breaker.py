"""Circuit breaker for the inference backend path.

States (the classic three-state machine):

    CLOSED ──K consecutive failures──▶ OPEN
      ▲                                 │ reset timer elapses
      │ probe succeeds                  ▼
      └──────────────────────────── HALF_OPEN ──probe fails──▶ OPEN

While OPEN every ``allow()`` answers False — callers shed instead of
invoking a backend that is currently only producing errors (≙ TF-Serving
request shedding; fail-fast beats queueing behind a dead accelerator).
After ``reset_s`` the breaker half-opens and admits exactly ONE probe;
its outcome closes or re-opens the breaker.

Thread-safe; transitions invoke an optional callback (the filter posts
them to the bus) and are counted for ``stats()``.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..utils.atomic import Counters

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class CircuitBreaker:
    def __init__(self, threshold: int = 5, reset_s: float = 1.0,
                 name: str = "breaker",
                 on_transition: Optional[Callable[[str, str], None]] = None):
        self.name = name
        self.threshold = max(1, int(threshold))
        self.reset_s = max(0.001, float(reset_s))
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self.stats = Counters(opened=0, closed=0, rejected=0)

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def _transition_locked(self, new: str) -> None:
        old, self._state = self._state, new
        if new == OPEN:
            self.stats.inc("opened")
            self._opened_at = time.monotonic()
        elif new == CLOSED:
            self.stats.inc("closed")
        cb = self._on_transition
        if cb is not None and old != new:
            # called under the lock: transitions are strictly ordered and
            # callbacks (a bus post) are cheap/non-reentrant
            cb(old, new)

    def _maybe_half_open_locked(self) -> None:
        if self._state == OPEN \
                and time.monotonic() - self._opened_at >= self.reset_s:
            self._probe_inflight = False
            self._transition_locked(HALF_OPEN)

    def allow(self) -> bool:
        """May the caller invoke the backend now? False = shed. In
        HALF_OPEN exactly one caller gets True (the probe)."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                return True
            self.stats.inc("rejected")
            return False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._probe_inflight = False
            if self._state != CLOSED:
                self._transition_locked(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive += 1
            if self._state == HALF_OPEN:
                # the probe failed: back to OPEN, re-arm the timer
                self._probe_inflight = False
                self._transition_locked(OPEN)
            elif self._state == CLOSED \
                    and self._consecutive >= self.threshold:
                self._transition_locked(OPEN)
