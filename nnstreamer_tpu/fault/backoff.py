"""Exponential backoff with jitter, and a rolling restart budget.

The two primitives every retry/reconnect/supervise site shares, so the
delay discipline cannot drift between the query client, the MQTT sink's
qos1 flush, and the source-loop supervisor:

* :class:`Backoff` — exponential delay with multiplicative jitter
  (jitter breaks the thundering-herd synchronization of N clients all
  reconnecting on the same schedule after a broker restart).
* :class:`RestartBudget` — at most N events per rolling window; the
  supervisor's guard against a crash-looping element restarting forever.
"""
from __future__ import annotations

import collections
import random
import threading
import time
from typing import Optional


class Backoff:
    """delay_k = min(max_s, base * multiplier**k), each draw scaled by a
    uniform factor in [1-jitter, 1]. Seeded, so chaos schedules replay
    identically."""

    def __init__(self, base: float = 0.05, multiplier: float = 2.0,
                 max_s: float = 2.0, jitter: float = 0.5,
                 seed: Optional[int] = None):
        self.base = max(0.0, float(base))
        self.multiplier = max(1.0, float(multiplier))
        self.max_s = max(self.base, float(max_s))
        self.jitter = min(1.0, max(0.0, float(jitter)))
        self._rng = random.Random(seed)
        self._attempt = 0

    @property
    def attempt(self) -> int:
        return self._attempt

    def reset(self) -> None:
        self._attempt = 0

    def next(self) -> float:
        """The next delay in seconds (advances the attempt counter)."""
        delay = min(self.max_s, self.base * self.multiplier ** self._attempt)
        self._attempt += 1
        if self.jitter:
            delay *= 1.0 - self.jitter * self._rng.random()
        return delay

    def sleep(self, stop_evt: Optional[threading.Event] = None) -> float:
        """Sleep the next delay; a ``stop_evt`` interrupts it (a stopping
        pipeline must not wait out a long backoff). Returns the delay."""
        delay = self.next()
        if delay <= 0:
            return 0.0
        if stop_evt is not None:
            stop_evt.wait(delay)
        else:
            time.sleep(delay)
        return delay


class RestartBudget:
    """Sliding-window rate limit: ``allow()`` consumes one slot and
    answers False once ``limit`` events landed inside ``window_s`` —
    the point where supervised restarting becomes crash-looping and the
    failure must escalate."""

    def __init__(self, limit: int = 3, window_s: float = 30.0):
        self.limit = max(1, int(limit))
        self.window_s = max(0.001, float(window_s))
        self._events: collections.deque = collections.deque()
        self._lock = threading.Lock()

    def allow(self) -> bool:
        now = time.monotonic()
        with self._lock:
            while self._events and now - self._events[0] > self.window_s:
                self._events.popleft()
            if len(self._events) >= self.limit:
                return False
            self._events.append(now)
            return True

    @property
    def used(self) -> int:
        now = time.monotonic()
        with self._lock:
            while self._events and now - self._events[0] > self.window_s:
                self._events.popleft()
            return len(self._events)
