"""Supervisor for streaming threads (source loops, serve workers).

A source's streaming thread has no caller to unwind into — when
``create()`` or the stream preamble raises, somebody must decide
between retrying, restarting the stream, and declaring the pipeline
dead. The supervisor is that somebody: it applies the element's
``on-error`` policy with exponential backoff + jitter and a restart
budget (max N restarts per rolling window), posts structured
``"warning"`` messages (element, attempt, cause) for every recovery,
and answers :data:`ESCALATE` once the budget is spent — at which point
the loop posts the pipeline error exactly like today.

≙ GStreamer's error-resilient sources (rtspsrc retry/reconnect) plus an
Erlang-style restart intensity limit.
"""
from __future__ import annotations

from typing import Optional

from ..utils.log import logger
from .backoff import Backoff, RestartBudget
from .errors import is_transient
from .policy import ErrorPolicy, policy_of

# decisions handed back to the supervised loop
CONTINUE = "continue"   # drop/retry at the failure site, keep the stream
RESTART = "restart"     # replay the stream preamble (caps et al.)
ESCALATE = "escalate"   # out of policy: post the pipeline error


class Supervisor:
    """One per supervised thread (created inside the loop, so a
    stop()/start() bounce gets a fresh budget)."""

    def __init__(self, element, policy: Optional[ErrorPolicy] = None):
        self.element = element
        self.policy = policy if policy is not None else policy_of(element)
        self.backoff: Backoff = self.policy.make_backoff()
        self.budget: RestartBudget = self.policy.make_budget()
        self._consecutive = 0

    def ok(self) -> None:
        """Call after a successful unit of work: resets the consecutive
        failure count and the backoff ladder."""
        if self._consecutive:
            self._consecutive = 0
            self.backoff.reset()

    def handle(self, exc: Exception, where: str = "stream") -> str:
        """Apply the policy to a failure escaping the supervised loop;
        sleeps the backoff itself (interruptibly) before answering
        CONTINUE/RESTART."""
        action = self.policy.action
        self._consecutive += 1
        stop_evt = getattr(self.element, "_stop_evt", None)
        if stop_evt is not None and stop_evt.is_set():
            return ESCALATE  # stopping: don't retry into a torn-down world

        if action == "skip":
            n = self.element.stats.inc("dropped")
            logger.warning("%s: %s failure skipped by on-error=skip (%s)",
                           self.element.name, where, exc)
            self._post_warning(policy="skip", where=where, dropped=n,
                               cause=repr(exc))
            return CONTINUE

        if action == "retry":
            if not is_transient(exc) \
                    or self._consecutive > self.policy.max_retries:
                return ESCALATE
            delay = self.backoff.sleep(stop_evt)
            self.element.stats.inc("retries")
            self._post_warning(policy="retry", where=where,
                               attempt=self._consecutive,
                               backoff_s=round(delay, 4), cause=repr(exc))
            logger.warning("%s: %s failed (attempt %d/%d), retrying: %s",
                           self.element.name, where, self._consecutive,
                           self.policy.max_retries, exc)
            return CONTINUE

        if action == "restart":
            if not self.budget.allow():
                return ESCALATE
            delay = self.backoff.sleep(stop_evt)
            self.element.stats.inc("restarts")
            self._post_warning(policy="restart", where=where,
                               attempt=self.element.stats["restarts"],
                               backoff_s=round(delay, 4), cause=repr(exc))
            logger.warning("%s: restarting %s after error (%s)",
                           self.element.name, where, exc)
            return RESTART

        return ESCALATE  # fail (default)

    def _post_warning(self, **data) -> None:
        self.element.post_message("warning", **data)
