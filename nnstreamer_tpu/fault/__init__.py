"""Fault-tolerance layer: error policies, supervised restarts, link
backoff, circuit breaking, and fault injection.

See ``Documentation/robustness.md`` for the policy table and the
breaker state machine; ``tests/test_chaos.py`` is the seeded chaos
harness driving all of it.
"""
from .backoff import Backoff, RestartBudget
from .breaker import CLOSED, HALF_OPEN, OPEN, CircuitBreaker
from .errors import (FaultInjected, TransientError, is_transient,
                     register_fatal, register_transient)
from .policy import ErrorPolicy, handle_chain_error, policy_of, \
    restart_element
from .preempt import PreemptGuard, install_sigterm
from .supervisor import CONTINUE, ESCALATE, RESTART, Supervisor

__all__ = [
    "Backoff", "RestartBudget", "CircuitBreaker",
    "CLOSED", "OPEN", "HALF_OPEN",
    "TransientError", "FaultInjected", "is_transient",
    "register_transient", "register_fatal",
    "ErrorPolicy", "policy_of", "handle_chain_error", "restart_element",
    "PreemptGuard", "install_sigterm",
    "Supervisor", "CONTINUE", "RESTART", "ESCALATE",
]
