"""Fusion planner: mark maximal device-capable runs and rewire them.

Two layers of fusibility, mirroring pipelint's never-start discipline:

* :func:`static_veto` — purely static, safe for lint rules: pad
  topology, thread boundaries, the element's own
  :meth:`Element.device_veto` declaration, and caps knowable from the
  shared inference pass. Never opens a model or touches a device.
* plan time (:func:`plan_fusion`) — runs inside ``Pipeline.start()``
  after validation, so it MAY open resources: each candidate member's
  :meth:`Element.device_fn` is invoked with the planned input config
  and may still decline (return None) for config-specific reasons
  (e.g. a dtype whose host/device promotion rules diverge, which would
  break the byte-parity oracle). A member declining ends the run at
  that point; upstream members ≥ ``min_run`` still fuse.

Segment boundaries (kept on :attr:`FusionPlan.vetoes` for
observability): sources, sinks, queues (deliberate thread boundaries),
multi-pad fan-in/out (mux/demux/tee/crop), edge/query links, stateful
elements (aggregator/trainer — no ``device_fn``), unknown or non-STATIC
caps, 64-bit dtypes (jax x64 is off), a change of ``on-error``
policy mid-run (a segment applies ONE policy; splitting keeps each
member under the policy its author chose), and a change of ``mesh:``
spec mid-run (one fused program compiles for one mesh — uniform
members stay mesh-resident across member boundaries instead).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..analysis.infer import (InferenceResult, config_of, element_transfer,
                              infer_caps)
from ..pipeline.element import Element, SinkElement, SrcElement
from ..tensors.caps import Caps
from ..tensors.info import TensorsConfig
from ..tensors.types import TensorFormat, TensorType
from ..utils.log import logger
from .segment import FusedSegment

# fusing a single element buys nothing (same one-in/one-out transfer
# the chain path already does) but costs a retrace; runs must be >= 2
DEFAULT_MIN_RUN = 2

# jax runs with x64 disabled (conftest + deployment default): a 64-bit
# stream would be silently downcast inside the program, breaking the
# byte-parity contract with the host chain path
_WIDE_TYPES = {TensorType.FLOAT64, TensorType.INT64, TensorType.UINT64}


def _kind(elem: Element) -> str:
    return getattr(type(elem), "ELEMENT_NAME", type(elem).__name__.lower())


@dataclass
class FusionCtx:
    """Plan-time context handed to :meth:`Element.device_fn`: the
    statically planned caps/config on the member's (single) input."""

    element: Element
    in_caps: Optional[Caps] = None
    in_config: Optional[TensorsConfig] = None


@dataclass
class PlannedSegment:
    members: List[Element]
    fns: List[Callable]
    ctxs: List[FusionCtx]
    in_caps: Optional[Caps] = None

    @property
    def names(self) -> List[str]:
        return [m.name for m in self.members]


@dataclass
class FusionPlan:
    segments: List[PlannedSegment] = field(default_factory=list)
    # element name -> why it did not fuse (lint/trace observability)
    vetoes: Dict[str, str] = field(default_factory=dict)

    def summary(self) -> Dict[str, object]:
        return {
            "segments": [s.names for s in self.segments],
            "vetoes": dict(self.vetoes),
        }


def static_veto(elem: Element,
                inference: Optional[InferenceResult] = None) -> Optional[str]:
    """Reason *elem* can never join a fused run, or None when it is a
    static fusion candidate. Pipelint-safe: never opens anything."""
    if isinstance(elem, SrcElement):
        return "source element (owns the streaming thread)"
    if isinstance(elem, SinkElement):
        return "sink element (host boundary)"
    kind = _kind(elem)
    if kind == "queue":
        return "thread boundary (queue)"
    sink_linked = [p for p in elem.sink_pads.values() if p.is_linked]
    src_linked = [p for p in elem.src_pads.values() if p.is_linked]
    if len(sink_linked) != 1 or len(src_linked) != 1:
        return (f"not a linear 1-in/1-out element "
                f"({len(sink_linked)} sink / {len(src_linked)} src links)")
    veto = elem.device_veto()
    if veto:
        return veto
    if inference is not None:
        in_caps = inference.in_caps(elem)
        caps = next(iter(in_caps.values())) if len(in_caps) == 1 else None
        if caps is not None:
            v = _caps_veto(caps)
            if v:
                return v
    return None


def _caps_veto(caps: Optional[Caps]) -> Optional[str]:
    """Why *caps* cannot feed a fused member, or None when they can."""
    cfg = config_of(caps)
    if cfg is None:
        return "input caps unknown or not fixed (dynamic-caps boundary)"
    if cfg.format != TensorFormat.STATIC or not len(cfg.info):
        return f"non-static stream format ({cfg.format})"
    for i in range(len(cfg.info)):
        if cfg.info[i].type in _WIDE_TYPES:
            return (f"64-bit tensor dtype {cfg.info[i].type} "
                    f"(jax x64 is disabled)")
    return None


def _plan_out_caps(elem: Element, in_caps: Caps) -> Optional[Caps]:
    """Output caps of *elem* under the planned input. The declared
    static transfer is authoritative (declared once, in infer.py's
    shared discipline); when it answers unknown — a tensor_filter with
    no declared output props — fall back to the element's plan-time
    refinement, which may open the model (we run after validation,
    before start, so that is allowed here and only here)."""
    pname = next(iter(elem.sink_pads))
    out = element_transfer(elem, {pname: in_caps})
    caps = next(iter(out.values())) if len(out) == 1 else None
    if caps is not None:
        return caps
    plan = getattr(elem, "plan_out_caps", None)
    if plan is None:
        return None
    try:
        return plan(in_caps)
    except Exception:  # noqa: BLE001 -- a refusal, not a planner error
        logger.debug("fusion: %s.plan_out_caps failed", elem.name,
                     exc_info=True)
        return None


def _policy_of(elem: Element) -> str:
    return str(getattr(elem, "on_error", "fail"))


def _mesh_of(elem: Element) -> str:
    """The member's declared ``mesh:`` spec ("" = unsharded). A fused
    program runs under ONE placement: every member must agree, so a
    spec change breaks the run (mixing meshes inside one jit would
    force cross-mesh reshards at member boundaries — exactly the
    transfers fusion exists to delete)."""
    get = getattr(elem, "mesh_spec", None)
    return str(get()) if callable(get) else ""


def _linked_sink(elem: Element):
    """The element's sole linked sink pad (candidates have exactly one,
    which need not be the FIRST declared pad)."""
    return next(p for p in elem.sink_pads.values() if p.is_linked)


def _linked_src(elem: Element):
    return next(p for p in elem.src_pads.values() if p.is_linked)


def plan_fusion(pipeline, inference: Optional[InferenceResult] = None,
                min_run: int = DEFAULT_MIN_RUN) -> FusionPlan:
    """Walk the graph and build the fusion plan. May open member
    models/subplugins (via ``device_fn``); mutates nothing."""
    inference = inference if inference is not None else infer_caps(pipeline)
    plan = FusionPlan()
    candidates: Dict[str, Element] = {}
    for elem in pipeline.elements.values():
        v = static_veto(elem, inference)
        if v is None:
            candidates[elem.name] = elem
        else:
            plan.vetoes[elem.name] = v

    def extends(prev: Element, elem: Element) -> bool:
        """True when *elem* continues *prev*'s run (same predicate for
        head detection and forward extension, so runs are maximal)."""
        if elem.name not in candidates or prev.name not in candidates:
            return False
        if _linked_src(prev).peer.element is not elem:
            return False
        if _policy_of(prev) != _policy_of(elem):
            plan.vetoes.setdefault(
                elem.name, f"on-error policy changes mid-run "
                           f"({_policy_of(prev)!r} -> {_policy_of(elem)!r})")
            return False
        if _mesh_of(prev) != _mesh_of(elem):
            plan.vetoes.setdefault(
                elem.name, f"mesh spec changes mid-run "
                           f"({_mesh_of(prev)!r} -> {_mesh_of(elem)!r}); "
                           f"one fused program runs on one mesh")
            return False
        return True

    visited: set = set()
    for head in inference.order:
        if head.name not in candidates or head.name in visited:
            continue
        up = _linked_sink(head).peer.element
        if extends(up, head):
            continue  # not a run head; reached from `up`'s walk
        # walk forward, propagating caps and binding device programs
        in_caps = inference.in_caps(head)
        cur_caps = next(iter(in_caps.values())) if len(in_caps) == 1 else None
        members: List[Element] = []
        fns: List[Callable] = []
        ctxs: List[FusionCtx] = []
        elem: Optional[Element] = head
        while elem is not None:
            visited.add(elem.name)
            v = _caps_veto(cur_caps)
            if v:
                plan.vetoes.setdefault(elem.name, v)
                break
            ctx = FusionCtx(elem, cur_caps, config_of(cur_caps))
            try:
                fn = elem.device_fn(ctx)
            except Exception:  # noqa: BLE001 -- decline, don't block launch
                logger.warning("fusion: %s.device_fn raised; leaving it "
                               "on the chain path", elem.name, exc_info=True)
                fn = None
            if fn is None:
                plan.vetoes.setdefault(
                    elem.name, "device_fn declined at plan time")
                break
            out_caps = _plan_out_caps(elem, cur_caps)
            if out_caps is None:
                plan.vetoes.setdefault(
                    elem.name, "output caps not plannable")
                break
            members.append(elem)
            fns.append(fn)
            ctxs.append(ctx)
            cur_caps = out_caps
            nxt = _linked_src(elem).peer.element
            elem = nxt if extends(members[-1], nxt) else None
        if len(members) >= max(2, min_run):
            plan.segments.append(PlannedSegment(
                members, fns, ctxs, in_caps=ctxs[0].in_caps))
        elif members:
            plan.vetoes.setdefault(
                members[0].name,
                "run of 1 (nothing adjacent to fuse with)")
    return plan


def apply_fusion(pipeline, plan: FusionPlan) -> List[FusedSegment]:
    """Rewire each planned run behind a :class:`FusedSegment`.

    Members stay in ``pipeline.elements`` (stats, name lookup, stop()
    all keep working) but their external links move to the segment:
    upstream src pad -> segment sink pad, segment src pad -> downstream
    sink pad. Member-to-member links are left intact — the segment
    replays caps negotiation through them (fusion/segment.py), and the
    tail's now-unlinked src pad drops the cascade at the boundary."""
    segments: List[FusedSegment] = []
    for planned in plan.segments:
        head, tail = planned.members[0], planned.members[-1]
        seg = FusedSegment(planned.members, planned.fns,
                           name=f"fused_{head.name}")
        head_sink, tail_src = _linked_sink(head), _linked_src(tail)
        up_src = head_sink.peer          # upstream element's src pad
        down_sink = tail_src.peer        # downstream element's sink pad
        up_src.unlink()
        tail_src.unlink()
        up_src.link(seg.sinkpad)
        seg.srcpad.link(down_sink)
        pipeline.add(seg)
        segments.append(seg)
    return segments


def fuse_pipeline(pipeline, inference: Optional[InferenceResult] = None,
                  min_run: int = DEFAULT_MIN_RUN) -> FusionPlan:
    """Plan and apply fusion over *pipeline*; returns the plan (also
    stored on ``pipeline._fusion_plan`` by Pipeline.start)."""
    plan = plan_fusion(pipeline, inference, min_run)
    apply_fusion(pipeline, plan)
    if plan.segments:
        logger.info("fusion: %d segment(s): %s",
                    len(plan.segments),
                    "; ".join(" ! ".join(s.names) for s in plan.segments))
    return plan
