"""FusedSegment: one element standing in for a run of device-capable
members, executing their composed ``device_fn`` programs as a single
cached ``jax.jit`` per caps signature.

Dataflow after rewiring (planner.apply_fusion): the upstream element
pushes into the segment's sink pad; the segment pushes one buffer per
input buffer from its src pad — member activations never leave the
device between stages, so a frame crosses the host↔device link once in
and once out instead of once per element.

Caps negotiation is NOT re-implemented: the members' internal pad
links are left intact, so the segment replays the incoming CAPS event
through the head member's chain and lets the members' own
``on_sink_caps`` cascade settle it (the tail's src pad is unlinked, so
the cascade stops at the segment boundary). Whatever the unfused chain
would have negotiated, the fused segment negotiates — by construction.

Fault integration: the segment adopts the run's (uniform) ``on-error``
policy and the strongest member circuit-breaker settings. A failure
inside the compiled program records on the breaker and re-raises, so
``Element.chain`` applies the policy exactly as it would for a member;
an open breaker sheds frames with the filter's QosEvent retry-after
convention. Stats live in the locked :class:`utils.atomic.Counters`
(chain thread writes, user thread reads) so racecheck stays clean.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from ..pipeline.element import TransformElement
from ..pipeline.events import CapsEvent, QosEvent
from ..pipeline.pad import Pad
from ..tensors.buffer import Buffer, Chunk
from ..tensors.transfer import submit_fetch
from ..utils.log import logger


class FusedSegment(TransformElement):
    """Composite element executing fused member programs on device.

    Constructed only by the fusion planner — it is deliberately not
    registered for launch strings (a launch string describes the
    *unfused* graph; fusion is a start-time placement decision).
    """

    ELEMENT_NAME = "fused_segment"
    SINK_TEMPLATES = {"sink": None}
    SRC_TEMPLATES = {"src": None}
    # stop()/start() drops only the jit cache; programs rebuild from
    # the bound member fns, so on-error=restart is lossless
    RESTART_SAFE = True
    IS_FUSED_SEGMENT = True

    def __init__(self, members: List, fns: List[Callable],
                 name: Optional[str] = None, **props):
        assert len(members) == len(fns) and members, "empty fused run"
        # the run has a uniform policy (planner breaks runs otherwise);
        # adopt it so chain-level error handling matches the members'
        props.setdefault("on-error", str(getattr(members[0], "on_error",
                                                 "fail")))
        super().__init__(name, **props)
        self.members = list(members)
        self._fns = list(fns)
        # a member asking for prefetch-host meant "ship my output via
        # the coalescing fetcher"; mid-segment outputs no longer leave
        # the device, but the SEGMENT's output does — honor the intent
        # there
        self._prefetch = any(bool(getattr(m, "prefetch_host", False))
                             for m in members)
        # per-caps-signature compiled programs; only the segment's
        # streaming thread touches it (one segment = one thread)
        self._programs: dict = {}
        # the run's (uniform — the planner breaks runs on a mesh-spec
        # change) device mesh: when set, the fused program pins a
        # batch-major layout at every member boundary and inputs are
        # committed to the mesh before dispatch, so a fused run stays
        # mesh-resident end to end instead of collapsing to one chip
        self._mesh = next(
            (m for m in (getattr(getattr(e, "fw", None), "mesh", None)
                         for e in members) if m is not None), None)
        self.stats.update(jit_hits=0, jit_misses=0, jit_prewarmed=0, shed=0,
                          breaker_opened=0, fused_elements=len(members),
                          devices=(len(self._mesh.devices.ravel())
                                   if self._mesh is not None else 1))
        # strongest member breaker settings win; 0 threshold = no breaker
        self._breaker = None
        self.breaker_threshold = max(
            (int(getattr(m, "breaker_threshold", 0) or 0) for m in members),
            default=0)
        resets = [float(getattr(m, "breaker_reset_ms", 0) or 0)
                  for m in members
                  if int(getattr(m, "breaker_threshold", 0) or 0) > 0]
        self.breaker_reset_ms = min(resets) if resets else 1000.0
        retries = [float(getattr(m, "breaker_retry_after_ms", 0) or 0)
                   for m in members]
        self.breaker_retry_after_ms = max(retries) if retries else 100.0
        # overlapped execution: the widest member window wins (the run
        # was device-capable end to end, so one window governs the fused
        # program); reorder stays on unless EVERY member opted out
        self.in_flight = max(
            (int(getattr(m, "in_flight", 1) or 1) for m in members),
            default=1)
        self.reorder = all(bool(getattr(m, "reorder", True))
                           for m in members)
        self.reorder_deadline_ms = max(
            (float(getattr(m, "reorder_deadline_ms", 1000.0) or 1000.0)
             for m in members), default=1000.0)
        self._overlap = None
        # completion errors are latched by the completer and re-raised
        # on the NEXT frame's chain (so Element.chain applies the
        # on-error policy on the chain thread, one frame late); two
        # roles store the field — completer sets, chain clears — so a
        # plain store is not enough: the lock makes the handoff atomic
        self._err_lock = threading.Lock()
        self._pending_error: Optional[BaseException] = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        super().start()
        if int(self.breaker_threshold) > 0:
            from ..fault.breaker import CircuitBreaker
            self._breaker = CircuitBreaker(
                threshold=int(self.breaker_threshold),
                reset_s=float(self.breaker_reset_ms) / 1e3,
                name=self.name, on_transition=self._on_breaker_transition)
        else:
            self._breaker = None
        self._overlap = None
        if int(self.in_flight) > 1:
            from ..elements.overlap import OverlapExecutor
            self._overlap = OverlapExecutor(
                int(self.in_flight),
                complete_cb=self._complete_frame,
                error_cb=self._complete_error,
                push_cb=self.push,
                name=self.name,
                reorder=bool(self.reorder),
                reorder_deadline_s=float(self.reorder_deadline_ms) / 1e3,
                devices=(len(self._mesh.devices.ravel())
                         if self._mesh is not None else 1))
        self._prewarm_from_cache()

    def _cache_key(self) -> str:
        """Segment identity for the persistent compile cache: the
        member names (launch-string stable) — the same fused run in a
        resurrected replica maps to the same signature bucket."""
        return "+".join(m.name for m in self.members)

    def _prewarm_from_cache(self) -> None:
        """Compile (and execute once, on zeros) every caps signature
        this segment's previous incarnations served, so the first real
        frame hits a warm program (fleet/cache.py)."""
        from ..fleet import cache as compile_cache
        cc = compile_cache.active()
        if cc is None:
            return
        cc.enable_xla_cache()
        import jax
        import numpy as np
        for sig, _donate in cc.signatures("fusion", self._cache_key()):
            if sig in self._programs:
                continue
            try:
                arrays = [np.zeros(shape, dtype) for shape, dtype in sig]
                if self._mesh is not None:
                    from ..parallel.sharding import place_batch
                    arrays = place_batch(arrays, self._mesh)
                exe = self._compile()
                jax.block_until_ready(exe(arrays))
                self._programs[sig] = exe
                self.stats.inc("jit_prewarmed")
            except Exception as exc:
                # a stale signature only costs its own replay
                logger.info("%s: cached fused signature %s skipped: %s",
                            self.name, sig, exc)

    def _record_signature(self, sig) -> None:
        from ..fleet import cache as compile_cache
        cc = compile_cache.active()
        if cc is None:
            return
        try:
            cc.record("fusion", self._cache_key(), sig)
        except Exception as exc:  # cache IO must never fail the chain
            logger.warning("%s: compile-cache record failed: %s",
                           self.name, exc)

    def drain(self) -> None:
        super().drain()
        if self._overlap is not None:
            self._overlap.flush()

    def stop(self) -> None:
        super().stop()
        if self._overlap is not None:
            self._overlap.flush()
            self._overlap.stop()
        self._programs.clear()

    def _on_breaker_transition(self, old: str, new: str) -> None:
        from ..fault.breaker import OPEN
        if new == OPEN:
            self.stats.inc("breaker_opened")
        logger.warning("%s: circuit breaker %s -> %s", self.name, old, new)
        self.post_message("warning", breaker=new, breaker_from=old,
                          retry_after_ms=float(self.breaker_retry_after_ms))

    # -- negotiation ------------------------------------------------------
    def on_sink_caps(self, pad: Pad, caps) -> None:
        """Replay the CAPS event through the members' own negotiation
        (their internal links are intact; the tail's unlinked src pad
        ends the cascade), then forward the tail's answer."""
        head, tail = self.members[0], self.members[-1]
        head.chain(head.sinkpad, CapsEvent(caps))
        out = None
        for p in tail.src_pads.values():
            if p.caps is not None:
                out = p.caps
                break
        if out is None:
            raise ValueError(
                f"{self.name}: member negotiation produced no caps for "
                f"{caps} (members: {[m.name for m in self.members]})")
        self.set_src_caps(out)

    # -- dataflow ---------------------------------------------------------
    def do_chain(self, pad: Pad, buf: Buffer) -> None:
        if self._overlap is not None:
            # a completion error latched by the completer surfaces HERE,
            # one frame late, so Element.chain applies the segment's
            # on-error policy on the chain thread exactly as it would
            # for a synchronous failure (the failed frame itself was
            # already accounted dropped by _complete_error)
            with self._err_lock:
                err, self._pending_error = self._pending_error, None
            if err is not None:
                raise err
        if self._breaker is not None and not self._breaker.allow():
            self._shed_frame(buf)
            return
        arrays = [c.raw for c in buf.chunks]
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in arrays)
        if self._mesh is not None:
            # commit inputs batch-major before dispatch; arrays the
            # serve scheduler already placed pass through untouched
            from ..parallel.sharding import place_batch
            arrays = place_batch(arrays, self._mesh)
        t0 = time.perf_counter_ns()
        exe = self._programs.get(sig)
        missed = exe is None
        if missed:
            self.stats.inc("jit_misses")
            exe = self._compile()
        else:
            self.stats.inc("jit_hits")
        try:
            # jit tracing/compilation errors surface here on the chain
            # thread in BOTH modes; with a window the device execution
            # itself is still in flight when this returns
            outs = exe(arrays)
        except Exception:
            # device program failed (trace or dispatch): count it on
            # the breaker, then let Element.chain apply the segment's
            # on-error policy — exactly the member path's fault flow
            if self._breaker is not None:
                self._breaker.record_failure()
            raise
        self._programs[sig] = exe
        if missed:
            self._record_signature(sig)
        dt = time.perf_counter_ns() - t0
        tracer = getattr(self.pipeline, "tracer", None)
        if tracer is not None:
            tracer.observe(f"fusion/{self.name}", dt)
        if self._overlap is not None:
            t_disp = self._overlap.window.acquire()
            try:
                self._overlap.submit(buf, outs, t_disp)
            except BaseException:
                # never strand the slot on a failed enqueue: the
                # completer will not see this frame
                self._overlap.window.release(t_disp)
                raise
            return
        if self._breaker is not None:
            self._breaker.record_success()
        self.push(buf.with_chunks(self._out_chunks(outs)))

    def _out_chunks(self, outs) -> List[Chunk]:
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        if self._prefetch:
            outs = submit_fetch(outs)
        return [Chunk(o) for o in outs]

    # -- completer side (in-flight window) --------------------------------
    def _complete_frame(self, entry) -> Buffer:
        """Materialize one in-flight fused program's outputs; raises the
        deferred device error, routed to :meth:`_complete_error`. No
        donation for segment programs: member activations alias through
        the fused XLA program already; input donation would invalidate
        upstream-owned device buffers."""
        import jax
        outs = jax.block_until_ready(entry.payload)
        if self._breaker is not None:
            self._breaker.record_success()
        return entry.buf.with_chunks(self._out_chunks(outs))

    def _complete_error(self, entry, exc: BaseException) -> None:
        """Per-frame accounting for a deferred device failure, then
        latch the error for the chain thread to re-raise."""
        if self._breaker is not None:
            self._breaker.record_failure()
        self.stats.inc("dropped")
        logger.warning("%s: fused program failed at completion (frame "
                       "dropped): %s", self.name, exc)
        with self._err_lock:
            if self._pending_error is None:
                self._pending_error = exc

    def handle_event(self, pad: Pad, event) -> None:
        if self._overlap is not None:
            # serialized events must not overtake in-flight frames
            self._overlap.flush()
        super().handle_event(pad, event)

    def transfer_report(self) -> dict:
        """Window occupancy / overlap stats for trace.report()'s
        ``transfer`` block; {} when running synchronously."""
        return self._overlap.report() if self._overlap is not None else {}

    def _compile(self):
        import jax
        fns = self._fns
        mesh = self._mesh
        if mesh is not None and len(mesh.devices.ravel()) > 1:
            from ..parallel.sharding import batch_sharding

            def pin(arrs):
                # batch-major at every member boundary: without the
                # constraint XLA may re-layout mid-program activations
                # around a tensor-parallel member and pay an all-gather
                # at the next batch-parallel stage
                return [jax.lax.with_sharding_constraint(
                            a, batch_sharding(
                                mesh, a.ndim,
                                a.shape[0] if a.ndim else 0))
                        for a in arrs]

            def program(arrs):
                arrs = pin(arrs)
                for fn in fns:
                    arrs = fn(arrs)
                    if not isinstance(arrs, (list, tuple)):
                        arrs = [arrs]
                    arrs = pin(arrs)
                return arrs
        else:
            def program(arrs):
                for fn in fns:
                    arrs = fn(arrs)
                return arrs

        # one jax.jit object per caps signature: jit would retrace a
        # shared object silently, which would skew the hit/miss stats
        # the trace report promises
        return jax.jit(program)

    def _shed_frame(self, buf: Buffer) -> None:
        self.stats.inc("shed")
        self.stats.inc("dropped")
        self.send_upstream_event(QosEvent(
            proportion=2.0,
            period_ns=int(float(self.breaker_retry_after_ms) * 1e6),
            timestamp=buf.pts))
