"""FusedSegment: one element standing in for a run of device-capable
members, executing their composed ``device_fn`` programs as a single
cached ``jax.jit`` per caps signature.

Dataflow after rewiring (planner.apply_fusion): the upstream element
pushes into the segment's sink pad; the segment pushes one buffer per
input buffer from its src pad — member activations never leave the
device between stages, so a frame crosses the host↔device link once in
and once out instead of once per element.

Caps negotiation is NOT re-implemented: the members' internal pad
links are left intact, so the segment replays the incoming CAPS event
through the head member's chain and lets the members' own
``on_sink_caps`` cascade settle it (the tail's src pad is unlinked, so
the cascade stops at the segment boundary). Whatever the unfused chain
would have negotiated, the fused segment negotiates — by construction.

Fault integration: the segment adopts the run's (uniform) ``on-error``
policy and the strongest member circuit-breaker settings. A failure
inside the compiled program records on the breaker and re-raises, so
``Element.chain`` applies the policy exactly as it would for a member;
an open breaker sheds frames with the filter's QosEvent retry-after
convention. Stats live in the locked :class:`utils.atomic.Counters`
(chain thread writes, user thread reads) so racecheck stays clean.
"""
from __future__ import annotations

import time
from typing import Callable, List, Optional

from ..pipeline.element import TransformElement
from ..pipeline.events import CapsEvent, QosEvent
from ..pipeline.pad import Pad
from ..tensors.buffer import Buffer, Chunk
from ..utils.log import logger


class FusedSegment(TransformElement):
    """Composite element executing fused member programs on device.

    Constructed only by the fusion planner — it is deliberately not
    registered for launch strings (a launch string describes the
    *unfused* graph; fusion is a start-time placement decision).
    """

    ELEMENT_NAME = "fused_segment"
    SINK_TEMPLATES = {"sink": None}
    SRC_TEMPLATES = {"src": None}
    # stop()/start() drops only the jit cache; programs rebuild from
    # the bound member fns, so on-error=restart is lossless
    RESTART_SAFE = True
    IS_FUSED_SEGMENT = True

    def __init__(self, members: List, fns: List[Callable],
                 name: Optional[str] = None, **props):
        assert len(members) == len(fns) and members, "empty fused run"
        # the run has a uniform policy (planner breaks runs otherwise);
        # adopt it so chain-level error handling matches the members'
        props.setdefault("on-error", str(getattr(members[0], "on_error",
                                                 "fail")))
        super().__init__(name, **props)
        self.members = list(members)
        self._fns = list(fns)
        # a member asking for prefetch-host meant "ship my output via
        # the coalescing fetcher"; mid-segment outputs no longer leave
        # the device, but the SEGMENT's output does — honor the intent
        # there
        self._prefetch = any(bool(getattr(m, "prefetch_host", False))
                             for m in members)
        # per-caps-signature compiled programs; only the segment's
        # streaming thread touches it (one segment = one thread)
        self._programs: dict = {}
        self.stats.update(jit_hits=0, jit_misses=0, shed=0,
                          breaker_opened=0, fused_elements=len(members))
        # strongest member breaker settings win; 0 threshold = no breaker
        self._breaker = None
        self.breaker_threshold = max(
            (int(getattr(m, "breaker_threshold", 0) or 0) for m in members),
            default=0)
        resets = [float(getattr(m, "breaker_reset_ms", 0) or 0)
                  for m in members
                  if int(getattr(m, "breaker_threshold", 0) or 0) > 0]
        self.breaker_reset_ms = min(resets) if resets else 1000.0
        retries = [float(getattr(m, "breaker_retry_after_ms", 0) or 0)
                   for m in members]
        self.breaker_retry_after_ms = max(retries) if retries else 100.0

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        super().start()
        if int(self.breaker_threshold) > 0:
            from ..fault.breaker import CircuitBreaker
            self._breaker = CircuitBreaker(
                threshold=int(self.breaker_threshold),
                reset_s=float(self.breaker_reset_ms) / 1e3,
                name=self.name, on_transition=self._on_breaker_transition)
        else:
            self._breaker = None

    def stop(self) -> None:
        super().stop()
        self._programs.clear()

    def _on_breaker_transition(self, old: str, new: str) -> None:
        from ..fault.breaker import OPEN
        if new == OPEN:
            self.stats.inc("breaker_opened")
        logger.warning("%s: circuit breaker %s -> %s", self.name, old, new)
        self.post_message("warning", breaker=new, breaker_from=old,
                          retry_after_ms=float(self.breaker_retry_after_ms))

    # -- negotiation ------------------------------------------------------
    def on_sink_caps(self, pad: Pad, caps) -> None:
        """Replay the CAPS event through the members' own negotiation
        (their internal links are intact; the tail's unlinked src pad
        ends the cascade), then forward the tail's answer."""
        head, tail = self.members[0], self.members[-1]
        head.chain(head.sinkpad, CapsEvent(caps))
        out = None
        for p in tail.src_pads.values():
            if p.caps is not None:
                out = p.caps
                break
        if out is None:
            raise ValueError(
                f"{self.name}: member negotiation produced no caps for "
                f"{caps} (members: {[m.name for m in self.members]})")
        self.set_src_caps(out)

    # -- dataflow ---------------------------------------------------------
    def do_chain(self, pad: Pad, buf: Buffer) -> None:
        if self._breaker is not None and not self._breaker.allow():
            self._shed_frame(buf)
            return
        arrays = [c.raw for c in buf.chunks]
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in arrays)
        t0 = time.perf_counter_ns()
        exe = self._programs.get(sig)
        if exe is None:
            self.stats.inc("jit_misses")
            exe = self._compile()
        else:
            self.stats.inc("jit_hits")
        try:
            outs = exe(arrays)
        except Exception:
            # device program failed (trace or dispatch): count it on
            # the breaker, then let Element.chain apply the segment's
            # on-error policy — exactly the member path's fault flow
            if self._breaker is not None:
                self._breaker.record_failure()
            raise
        self._programs[sig] = exe
        if self._breaker is not None:
            self._breaker.record_success()
        dt = time.perf_counter_ns() - t0
        tracer = getattr(self.pipeline, "tracer", None)
        if tracer is not None:
            tracer.observe(f"fusion/{self.name}", dt)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        if self._prefetch:
            from ..tensors.fetch import submit_fetch
            outs = submit_fetch(outs)
        self.push(buf.with_chunks([Chunk(o) for o in outs]))

    def _compile(self):
        import jax
        fns = self._fns

        def program(arrs):
            for fn in fns:
                arrs = fn(arrs)
            return arrs

        # one jax.jit object per caps signature: jit would retrace a
        # shared object silently, which would skew the hit/miss stats
        # the trace report promises
        return jax.jit(program)

    def _shed_frame(self, buf: Buffer) -> None:
        self.stats.inc("shed")
        self.stats.inc("dropped")
        self.send_upstream_event(QosEvent(
            proportion=2.0,
            period_ns=int(float(self.breaker_retry_after_ms) * 1e6),
            timestamp=buf.pts))
