"""Device-resident pipeline compiler: fuse element chains into one
XLA program.

On a remote-attached chip the per-element host↔device round trip — not
compute — is the binding constraint (r04: ``pipeline_vs_invoke_pct`` =
4.4, 509 ms interlatency at the filter, 823 ms at the decoder). This
package promotes pipelint's static transfer pass into a placement IR:
after parse and validation, but before start, the planner walks the
graph, marks maximal runs of device-capable elements (those whose
:meth:`Element.device_fn` yields a pure traceable program), and
replaces each run's dataflow with a single :class:`FusedSegment` whose
body composes the member programs into one cached ``jax.jit`` — so
activations stay HBM-resident and each frame crosses the link once in,
once out.

The per-element chain path stays intact: it is the opt-out fallback
(``fuse=false`` pipeline prop, ``pipeline.fuse = False``) and the
parity oracle — a fused pipeline must produce byte-identical tensors
to the unfused chain on the CPU backend (``make fuse-parity``).

See Documentation/fusion.md for the planner rules and the ``device_fn``
contract.
"""
from .planner import (FusionCtx, FusionPlan, PlannedSegment,  # noqa: F401
                      fuse_pipeline, plan_fusion, static_veto)
from .segment import FusedSegment  # noqa: F401

__all__ = [
    "FusionCtx", "FusionPlan", "PlannedSegment", "FusedSegment",
    "fuse_pipeline", "plan_fusion", "static_veto",
]
