"""pipelint — static pipeline analysis (no element is ever started).

Proves a parsed pipeline well-typed before PLAYING: propagates caps/
shape/dtype through every element's declared transfer function
(:meth:`Element.static_transfer`) and runs a set of graph lint rules
(dangling pads, cycles, un-queued tee branches, jit-signature blowup,
sharding divisibility, …). The same pass backs ``Pipeline.validate()``,
the default pre-PLAYING gate, and ``python -m nnstreamer_tpu lint``.
"""
from .findings import (Finding, PipelineValidationError,  # noqa: F401
                       Report, Severity)
from .infer import (InferenceResult, config_of,  # noqa: F401
                    element_transfer, infer_caps)
from .rules import ALL_RULES, LintContext, Rule, analyze  # noqa: F401

__all__ = [
    "Severity", "Finding", "Report", "PipelineValidationError",
    "InferenceResult", "infer_caps", "element_transfer", "config_of",
    "Rule", "LintContext", "ALL_RULES", "analyze",
]
