"""``python -m nnstreamer_tpu jitcheck`` — the compile/host-sync lint CLI.

    jitcheck [paths...] [--json] [-o FILE] [-q] [-v] [--min-hot-sites N]

Scans the given files/directories (default: the installed
``nnstreamer_tpu`` package) and reports host-sync-in-hot-path,
retrace-hazard, donation-misuse, and impure-device-fn findings.
``--min-hot-sites`` turns the scan's own coverage into a finding: if
fewer hot-path bodies than N were actually walked, the gate fails
rather than silently passing on an unhooked model. Exit codes:
0 clean, 1 findings, 2 usage error.
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List, Optional

from .passes import analyze_paths

USAGE_ERROR = 2


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="nnstreamer_tpu jitcheck",
        description="static JAX compile/host-sync hazard analyzer "
                    "(host syncs, retrace hazards, donation misuse, "
                    "impure device fns) for the streaming runtime")
    ap.add_argument("paths", nargs="*",
                    help="files or directories to scan (default: the "
                         "nnstreamer_tpu package)")
    ap.add_argument("--json", action="store_true",
                    help="emit machine-readable findings")
    ap.add_argument("-o", "--output", metavar="FILE",
                    help="also write the report (JSON) to FILE")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress output; exit code only")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="list suppressed findings too")
    ap.add_argument("--min-hot-sites", type=int, default=0, metavar="N",
                    help="fail (vacuous-coverage) unless at least N "
                         "hot-path bodies were analyzed")
    try:
        opts = ap.parse_args(argv)
    except SystemExit as exc:
        # argparse exits 2 on bad flags and 0 on --help: keep both
        return int(exc.code or 0) and USAGE_ERROR

    paths = opts.paths or [str(Path(__file__).resolve().parents[2])]
    for p in paths:
        if not Path(p).exists():
            print(f"jitcheck: no such path: {p}", file=sys.stderr)
            return USAGE_ERROR

    report = analyze_paths(paths, min_hot_sites=opts.min_hot_sites)

    if opts.output:
        out = Path(opts.output)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(report.to_json() + "\n", encoding="utf-8")
    if not opts.quiet:
        print(report.to_json() if opts.json
              else report.to_text(verbose=opts.verbose))
    return report.exit_code


if __name__ == "__main__":
    raise SystemExit(main())
