"""Finding/report model for jitcheck.

Same contract as the sibling analyzers: findings pin to ``file:line``
of the codebase itself, there is no benign tier (ANY live finding
fails the gate — 0 clean / 1 findings / 2 usage error), and
deliberate exceptions are spelled at the site with a reasoned
``# jitcheck: ok(reason)`` pragma.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# finding classes (the ``rule`` field)
HOST_SYNC = "host-sync-in-hot-path"
RETRACE = "retrace-hazard"
DONATION_MISUSE = "donation-misuse"
IMPURE_DEVICE_FN = "impure-device-fn"
VACUOUS_COVERAGE = "vacuous-coverage"


@dataclass(frozen=True)
class JitFinding:
    rule: str
    file: str
    line: int
    message: str
    cls: Optional[str] = None       # owning class, e.g. "TensorFilter"
    func: Optional[str] = None      # owning function/method name
    roles: Tuple[str, ...] = ()     # hot thread roles the site runs under

    @property
    def location(self) -> str:
        return f"{self.file}:{self.line}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "location": self.location, "class": self.cls,
                "func": self.func, "roles": list(self.roles),
                "message": self.message}

    def __str__(self) -> str:
        return f"{self.rule:22s} {self.location}: {self.message}"


@dataclass
class JitReport:
    findings: List[JitFinding] = field(default_factory=list)
    suppressed: List[JitFinding] = field(default_factory=list)
    num_files: int = 0
    hot_sites: int = 0              # hot-path bodies actually walked
    compiled_bodies: int = 0        # device-program bodies walked
    jit_sites: int = 0              # jax.jit constructions seen
    # kind -> count of jit constructions, the static half of the
    # runtime contract: observed CompileCache kinds must be a subset.
    jit_site_kinds: Dict[str, int] = field(default_factory=dict)

    def by_rule(self, rule: str) -> List[JitFinding]:
        return [f for f in self.findings if f.rule == rule]

    @property
    def exit_code(self) -> int:
        """0 clean / 1 findings (suppressions don't count) — the CLI
        maps usage errors to 2 before analysis ever runs."""
        return 1 if self.findings else 0

    def to_text(self, verbose: bool = False) -> str:
        lines = [str(f) for f in sorted(
            self.findings, key=lambda f: (f.rule, f.file, f.line))]
        if verbose:
            lines += [f"suppressed {f}" for f in sorted(
                self.suppressed, key=lambda f: (f.file, f.line))]
        lines.append(
            f"jitcheck: {len(self.findings)} finding(s) "
            f"({len(self.suppressed)} suppressed) across "
            f"{self.num_files} file(s); walked {self.hot_sites} hot-path "
            f"site(s) + {self.compiled_bodies} compiled bod(ies), "
            f"{self.jit_sites} jit site(s)")
        return "\n".join(lines)

    def to_json(self) -> str:
        return json.dumps({
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "files": self.num_files,
            "hot_sites": self.hot_sites,
            "compiled_bodies": self.compiled_bodies,
            "jit_sites": self.jit_sites,
            "jit_site_kinds": dict(sorted(self.jit_site_kinds.items())),
            "exit_code": self.exit_code,
        }, indent=2)
