"""jitcheck — static JAX compile/host-sync hazard analysis.

The fourth analyzer in the family: pipelint validates pipeline GRAPHS,
racecheck the lock discipline of the CODE, flowcheck the settlement
LEDGER — jitcheck proves the hot path stays on-device. It rides on
racecheck's thread-role model to find the bodies a frame actually
crosses, tracks device-array taint through them, and reports hidden
host syncs, silent retrace triggers, donation-after-use, and impurity
inside compiled functions; a runtime compile-stability monitor
(``make jit-stability``) then cross-checks the static jit-site map
against what a warmed process actually compiles.

    from nnstreamer_tpu.analysis.jit import analyze_paths
    report = analyze_paths(["nnstreamer_tpu/"])
    assert report.exit_code == 0, report.to_text()

See Documentation/jitcheck.md for the taint model, the finding
classes, and the ``# jitcheck: ok(reason)`` suppression pragma.
"""
from .findings import (DONATION_MISUSE, HOST_SYNC, IMPURE_DEVICE_FN,
                       RETRACE, VACUOUS_COVERAGE, JitFinding, JitReport)
from .model import (EXTRA_SEEDS, HOT_ROLES, FuncUnit, JitBinding,
                    JitModel, JitSite, scan_paths, site_kind)
from .passes import analyze_paths, run_passes
from .runtime import (CompileEventMonitor, StabilityResult,
                      check_against_static, jit_stat_snapshot,
                      steady_recompiles)

__all__ = [
    "analyze_paths", "run_passes", "scan_paths", "JitModel", "FuncUnit",
    "JitBinding", "JitSite", "JitFinding", "JitReport", "HOST_SYNC",
    "RETRACE", "DONATION_MISUSE", "IMPURE_DEVICE_FN", "VACUOUS_COVERAGE",
    "HOT_ROLES", "EXTRA_SEEDS", "site_kind", "CompileEventMonitor",
    "StabilityResult", "check_against_static", "jit_stat_snapshot",
    "steady_recompiles",
]
