"""jitcheck analysis passes.

One intra-procedural walker per analyzable body, two modes:

hot bodies (chain / source-loop / dispatcher / completer / worker /
uploader roles) get the *host-boundary* rules — a device-taint lattice
(none < seq-of-arrays < array) seeded from ``.raw`` reads, jnp/lax
producers, framework invoke/dispatch results, and declared device
params, sanitized only by ``.host()`` / ``jax.device_get``:

* host-sync-in-hot-path — ``float()/int()/bool()``, ``.item()``,
  ``np.*`` (implicit ``__array__`` D2H), implicit truthiness on a
  device value; ``block_until_ready`` outside the completer role or
  while holding a lock.
* retrace-hazard — ``jax.jit`` constructed per call or inside a loop;
  non-hashable or per-call-computed values at static positions of a
  known jitted binding; ``*set(...)`` feeding a jitted signature.
* donation-misuse — any read of a name after it was passed to a
  donating dispatch (``donate=``/``donate_argnums``) without rebinding.

compiled bodies (``device_fn`` inner programs, ``@jax.jit`` ops,
fused-segment programs — every param is a traced value) get the
*device-program* rules:

* impure-device-fn — writes to captured/self state, Counters bumps,
  I/O, host randomness or clocks, host conversion of a traced value.
* retrace-hazard — data-dependent or shape-dependent Python control
  flow (traces per value / compiles per shape).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .findings import (DONATION_MISUSE, HOST_SYNC, IMPURE_DEVICE_FN,
                       RETRACE, VACUOUS_COVERAGE, JitFinding, JitReport)
from .model import (COMPLETER, DEVICE_PRODUCERS, META_ATTRS, SANITIZERS,
                    FuncUnit, JitModel, scan_paths)

# taint lattice
NONE, SEQ, ARRAY = 0, 1, 2

NP_ROOTS = frozenset({"np", "numpy"})
DEVICE_NS = frozenset({"jnp", "lax"})
SEQ_BUILTINS = frozenset({"list", "tuple", "sorted", "reversed", "zip",
                          "enumerate"})
SCALAR_CASTS = frozenset({"float", "int", "bool"})
# NB: no "update" — optax's GradientTransformation.update is the
# canonical PURE call inside every jitted train step.
MUTATORS = frozenset({"append", "extend", "add", "inc", "insert",
                      "setdefault", "pop", "popleft", "remove",
                      "clear", "write", "put", "observe"})
IO_ROOTS = frozenset({"print", "open", "logger", "logging", "log"})
HOST_ENTROPY_ROOTS = frozenset({"random", "time"})


def _root_name(node: ast.AST) -> Optional[str]:
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
        node = getattr(node, "value", None) or getattr(node, "func", None)
        if node is None:
            return None
    return node.id if isinstance(node, ast.Name) else None


def _trailing(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _is_jit_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "jit":
        return _root_name(f) == "jax"
    return isinstance(f, ast.Name) and f.id == "jit"


def _emit(report: JitReport, model: JitModel, finding: JitFinding) -> None:
    if model.pragma_reason(finding.file, finding.line):
        report.suppressed.append(finding)
    else:
        report.findings.append(finding)


class _BodyWalker:
    """Statement-ordered walk of one body, carrying the taint
    environment, the donated-name set, and the lexical lock stack."""

    def __init__(self, model: JitModel, report: JitReport,
                 unit: FuncUnit) -> None:
        self.model = model
        self.report = report
        self.unit = unit
        self.env: Dict[str, int] = {}
        self.donated: Dict[str, int] = {}
        self.locks: List[str] = []
        self.locals: Set[str] = set()
        node = unit.node
        args = node.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)
                  + [x for x in (args.vararg, args.kwarg) if x]):
            self.locals.add(a.arg)
            if unit.compiled and a.arg != "self":
                self.env[a.arg] = ARRAY          # traced values
            elif a.arg in unit.tainted_params:
                self.env[a.arg] = ARRAY
        # prepass: every name ever stored is local (not captured state)
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                self.locals.add(n.id)

    # -- emission -----------------------------------------------------
    def finding(self, rule: str, node: ast.AST, message: str) -> None:
        _emit(self.report, self.model, JitFinding(
            rule=rule, file=self.unit.file,
            line=getattr(node, "lineno", 0), message=message,
            cls=self.unit.cls, func=self.unit.name,
            roles=tuple(sorted(self.unit.roles))))

    def sync(self, node: ast.AST, message: str) -> None:
        """host-boundary violation: host-sync in a hot body, impurity
        in a compiled one (there it's a trace-time hazard instead)."""
        if self.unit.compiled:
            self.finding(IMPURE_DEVICE_FN, node, message)
        else:
            self.finding(HOST_SYNC, node, message)

    # -- environment --------------------------------------------------
    def bind(self, target: ast.AST, taint: int) -> None:
        if isinstance(target, ast.Name):
            if taint:
                self.env[target.id] = taint
            else:
                self.env.pop(target.id, None)
            self.donated.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self.bind(elt, ARRAY if taint else NONE)
        elif isinstance(target, ast.Starred):
            self.bind(target.value, SEQ if taint else NONE)
        # attribute/subscript stores don't enter the local env

    # -- statements ---------------------------------------------------
    def run(self) -> None:
        self.block(self.unit.node.body)

    def block(self, stmts: Sequence[ast.stmt]) -> None:
        for s in stmts:
            self.stmt(s)

    def stmt(self, s: ast.stmt) -> None:
        if isinstance(s, ast.Assign):
            t = self.expr(s.value)
            for tgt in s.targets:
                if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                    self.impure_store(tgt)
                self.bind(tgt, t)
        elif isinstance(s, ast.AnnAssign):
            t = self.expr(s.value) if s.value else NONE
            if isinstance(s.target, (ast.Attribute, ast.Subscript)):
                self.impure_store(s.target)
            self.bind(s.target, t)
        elif isinstance(s, ast.AugAssign):
            t = self.expr(s.value)
            if isinstance(s.target, (ast.Attribute, ast.Subscript)):
                self.impure_store(s.target)
            elif isinstance(s.target, ast.Name):
                prev = self.env.get(s.target.id, NONE)
                self.bind(s.target, max(t, prev))
        elif isinstance(s, ast.Expr):
            self.expr(s.value)
        elif isinstance(s, ast.Return):
            if s.value is not None:
                self.expr(s.value)
        elif isinstance(s, ast.If):
            self.test(s.test)
            self.branches([s.body, s.orelse])
        elif isinstance(s, ast.While):
            self.test(s.test)
            self.loop_scan(s)
            self.branches([s.body, []])       # body may run zero times
            self.block(s.orelse)
        elif isinstance(s, ast.For):
            it = self.expr(s.iter)
            self.loop_scan(s)
            pre = (dict(self.env), dict(self.donated))
            self.bind(s.target, ARRAY if it else NONE)
            self.block(s.body)
            self.merge(*pre)                  # zero-iteration path
            self.block(s.orelse)
        elif isinstance(s, ast.With):
            held = []
            for item in s.items:
                lock = self._lock_name(item.context_expr)
                if lock is not None:
                    held.append(lock)
                else:
                    t = self.expr(item.context_expr)
                    if item.optional_vars is not None:
                        self.bind(item.optional_vars, t)
            self.locks.extend(held)
            self.block(s.body)
            for _ in held:
                self.locks.pop()
        elif isinstance(s, ast.Try):
            self.block(s.body)
            for h in s.handlers:
                self.block(h.body)
            self.block(s.orelse)
            self.block(s.finalbody)
        elif isinstance(s, ast.Assert):
            self.test(s.test)
        elif isinstance(s, (ast.Global, ast.Nonlocal)):
            if self.unit.compiled:
                self.finding(IMPURE_DEVICE_FN, s,
                             f"{'global' if isinstance(s, ast.Global) else 'nonlocal'} "
                             "rebinding inside compiled code — compiled "
                             "functions must be pure")
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            pass          # inner defs are separate units (if compiled)
        elif isinstance(s, ast.Raise):
            if s.exc is not None:
                self.expr(s.exc)
        elif isinstance(s, ast.Delete):
            for tgt in s.targets:
                if isinstance(tgt, ast.Name):
                    self.env.pop(tgt.id, None)
                    self.donated.pop(tgt.id, None)
        else:
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    self.expr(child)

    def branches(self, blocks: Sequence[Sequence[ast.stmt]]) -> None:
        """Path-sensitive join: run each block from a copy of the
        pre-state, then merge the post-states (max taint, union of
        donations) — a reassignment in one branch must not leak taint
        into its sibling."""
        pre_env, pre_don = dict(self.env), dict(self.donated)
        posts = []
        for b in blocks:
            self.env, self.donated = dict(pre_env), dict(pre_don)
            self.block(b)
            posts.append((self.env, self.donated))
        self.env, self.donated = {}, {}
        for env, don in posts:
            self.merge(env, don)

    def merge(self, env: Dict[str, int], don: Dict[str, int]) -> None:
        for k, v in env.items():
            self.env[k] = max(self.env.get(k, NONE), v)
        for k, v in don.items():
            self.donated.setdefault(k, v)

    def _lock_name(self, expr: ast.AST) -> Optional[str]:
        """with self._lock: / with self._cv: — mirrors racecheck's
        lexical lock model (only self-attribute context managers whose
        name smells like a lock are treated as one)."""
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and any(k in expr.attr for k in ("lock", "cv", "cond",
                                                 "mutex"))):
            return expr.attr
        return None

    def impure_store(self, target: ast.AST) -> None:
        if not self.unit.compiled:
            return
        root = _root_name(target)
        if root == "self" or (root is not None
                              and root not in self.locals):
            self.finding(IMPURE_DEVICE_FN, target,
                         "write to captured state inside compiled code "
                         "— the effect runs once at trace time, then "
                         "never again")

    def loop_scan(self, loop: ast.stmt) -> None:
        """jax.jit constructed inside a hot loop recompiles per
        iteration (each construction is a fresh cache)."""
        if self.unit.compiled or not self.unit.hot:
            return
        for n in ast.walk(loop):
            if _is_jit_call(n):
                self.finding(RETRACE, n,
                             "jax.jit constructed inside a loop — each "
                             "construction is a fresh compile cache; "
                             "hoist it and reuse the jitted callable")

    # -- truthiness contexts ------------------------------------------
    def test(self, e: ast.expr) -> None:
        if isinstance(e, ast.BoolOp):
            for v in e.values:
                self.test(v)
            return
        if isinstance(e, ast.UnaryOp) and isinstance(e.op, ast.Not):
            self.test(e.operand)
            return
        t = self.expr(e)
        if t == ARRAY:
            if self.unit.compiled:
                self.finding(RETRACE, e,
                             "data-dependent Python control flow on a "
                             "traced value — traces per value or fails "
                             "at trace time; use lax.cond/jnp.where")
            else:
                self.sync(e, "implicit bool() of a device array blocks "
                             "on the device — compare on host "
                             "metadata or materialize via .host()")
        if self.unit.compiled:
            self._shape_branch(e)

    def _shape_branch(self, e: ast.expr) -> None:
        for n in ast.walk(e):
            hit = None
            if (isinstance(n, ast.Attribute) and n.attr == "shape"
                    and self.expr_quiet(n.value) == ARRAY):
                hit = n
            elif (isinstance(n, ast.Call) and _trailing(n.func) == "len"
                    and n.args and self.expr_quiet(n.args[0]) >= SEQ):
                hit = n
            if hit is not None:
                self.finding(RETRACE, hit,
                             "shape-dependent Python control flow "
                             "inside compiled code — every distinct "
                             "shape compiles its own program")
                return

    # -- expressions --------------------------------------------------
    def expr_quiet(self, e: ast.expr) -> int:
        """taint of ``e`` without re-emitting findings (used by
        secondary scans over subtrees the main walk already visited)."""
        save_r, save_s = self.report.findings, self.report.suppressed
        self.report.findings, self.report.suppressed = [], []
        save_d = dict(self.donated)
        try:
            return self.expr(e)
        finally:
            self.report.findings, self.report.suppressed = save_r, save_s
            self.donated = save_d

    def expr(self, e: ast.expr) -> int:        # noqa: C901
        if e is None:
            return NONE
        if isinstance(e, ast.Name):
            if isinstance(e.ctx, ast.Load) and e.id in self.donated:
                dline = self.donated.pop(e.id)
                self.finding(DONATION_MISUSE, e,
                             f"'{e.id}' read after being donated to the "
                             f"device at line {dline} — donated buffers "
                             "are deallocated by XLA; copy or rebind "
                             "before dispatch")
            return self.env.get(e.id, NONE)
        if isinstance(e, ast.Attribute):
            if e.attr == "raw":
                return ARRAY                    # Chunk.raw: maybe-device
            base = self.expr(e.value)
            if e.attr in META_ATTRS:
                return NONE
            if base == ARRAY:
                return ARRAY
            return NONE
        if isinstance(e, ast.Subscript):
            base = self.expr(e.value)
            self.expr(e.slice) if isinstance(e.slice, ast.expr) else None
            if base == SEQ:
                return SEQ if isinstance(e.slice, ast.Slice) else ARRAY
            return ARRAY if base == ARRAY else NONE
        if isinstance(e, ast.Call):
            return self.call(e)
        if isinstance(e, ast.BinOp):
            return max(self.expr(e.left), self.expr(e.right))
        if isinstance(e, ast.UnaryOp):
            if isinstance(e.op, ast.Not):
                self.test(e.operand)
                return NONE
            return self.expr(e.operand)
        if isinstance(e, ast.BoolOp):
            self.test(e)
            return NONE
        if isinstance(e, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                   for op in e.ops):
                self.expr(e.left)
                for c in e.comparators:
                    self.expr(c)
                return NONE
            t = max([self.expr(e.left)]
                    + [self.expr(c) for c in e.comparators])
            return ARRAY if t == ARRAY else NONE
        if isinstance(e, ast.IfExp):
            self.test(e.test)
            return max(self.expr(e.body), self.expr(e.orelse))
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            t = max([self.expr(x) for x in e.elts], default=NONE)
            return SEQ if t else NONE
        if isinstance(e, ast.Dict):
            t = max([self.expr(v) for v in e.values if v is not None],
                    default=NONE)
            for k in e.keys:
                if k is not None:
                    self.expr(k)
            return SEQ if t else NONE
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            for gen in e.generators:
                it = self.expr(gen.iter)
                self.bind(gen.target, ARRAY if it else NONE)
                for cond in gen.ifs:
                    self.test(cond)
            t = self.expr(e.elt)
            return SEQ if t else NONE
        if isinstance(e, ast.DictComp):
            for gen in e.generators:
                it = self.expr(gen.iter)
                self.bind(gen.target, ARRAY if it else NONE)
                for cond in gen.ifs:
                    self.test(cond)
            self.expr(e.key)
            t = self.expr(e.value)
            return SEQ if t else NONE
        if isinstance(e, ast.Starred):
            return self.expr(e.value)
        if isinstance(e, ast.Await):
            return self.expr(e.value)
        if isinstance(e, ast.Lambda):
            return NONE                          # opaque; not inlined
        if isinstance(e, ast.JoinedStr):
            for v in e.values:
                if isinstance(v, ast.FormattedValue):
                    self.expr(v.value)
            return NONE
        if isinstance(e, ast.NamedExpr):
            t = self.expr(e.value)
            self.bind(e.target, t)
            return t
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self.expr(child)
        return NONE

    # -- calls --------------------------------------------------------
    def call(self, e: ast.Call) -> int:         # noqa: C901
        trail = _trailing(e.func)
        root = _root_name(e.func)

        # jax.jit(f)(x): construct-and-call retraces every call
        if isinstance(e.func, ast.Call) and _is_jit_call(e.func):
            if self.unit.hot and not self.unit.compiled:
                self.finding(RETRACE, e,
                             "jax.jit constructed and called in one "
                             "expression — the compile cache dies with "
                             "the expression; bind the jitted callable "
                             "once")
            self.expr(e.func)
            for a in e.args:
                self.expr(a)
            return ARRAY

        recv = (self.expr(e.func.value)
                if isinstance(e.func, ast.Attribute) else NONE)
        arg_taints = [self.expr(a.value if isinstance(a, ast.Starred)
                                else a) for a in e.args]
        kw_taints = [self.expr(kw.value) for kw in e.keywords]
        any_taint = max(arg_taints + kw_taints + [NONE])

        if _is_jit_call(e):
            return NONE                          # construction site only

        # sanctioned materialization: .host(), jax.device_get(...)
        if trail in SANITIZERS:
            return NONE

        # -- host-sync family --
        if (isinstance(e.func, ast.Name) and trail in SCALAR_CASTS
                and any(t == ARRAY for t in arg_taints)):
            self.sync(e, f"{trail}() on a device array forces a "
                         "blocking D2H sync on the hot path — use "
                         ".host() (or jax.device_get) at the sanctioned "
                         "boundary")
            return NONE
        if trail in ("item", "tolist") and recv == ARRAY:
            self.sync(e, f".{trail}() on a device array forces a "
                         "blocking D2H sync on the hot path")
            return NONE
        if root in NP_ROOTS and any_taint:
            self.sync(e, f"np.{trail}() on a device value triggers an "
                         "implicit __array__ D2H copy per array — batch "
                         "it through jax.device_get at the boundary")
            return NONE
        if trail == "block_until_ready":
            held = bool(self.locks)
            if self.unit.compiled:
                self.finding(IMPURE_DEVICE_FN, e,
                             "block_until_ready inside compiled code")
            elif held:
                self.finding(HOST_SYNC, e,
                             "block_until_ready while holding "
                             f"'{self.locks[-1]}' — the device wait "
                             "serializes every thread behind the lock")
            elif self.unit.hot and COMPLETER not in self.unit.roles:
                self.finding(HOST_SYNC, e,
                             "block_until_ready outside the completer "
                             "role — only the overlap completer may "
                             "wait on the device")
            return ARRAY if recv == ARRAY or any_taint else NONE

        # -- purity (compiled bodies) --
        if self.unit.compiled:
            self._compiled_call_purity(e, trail, root)

        # -- retrace at known jitted call sites --
        self._jitted_call_site(e, trail)

        # -- donation --
        self._donation(e, trail)

        # -- result taint --
        if root in DEVICE_NS or (root == "jax" and trail != "jit"):
            return ARRAY
        if trail in DEVICE_PRODUCERS:
            return SEQ if trail in ("invoke", "dispatch") else ARRAY
        if (isinstance(e.func, ast.Name) and trail in SEQ_BUILTINS
                and any_taint):
            return SEQ
        if recv:
            return recv                          # x.sum(), outs.copy()
        return NONE

    def _compiled_call_purity(self, e: ast.Call, trail: Optional[str],
                              root: Optional[str]) -> None:
        if root in IO_ROOTS or trail in ("print", "open"):
            self.finding(IMPURE_DEVICE_FN, e,
                         "I/O inside compiled code runs once at trace "
                         "time, then never again")
            return
        if root in HOST_ENTROPY_ROOTS:
            self.finding(IMPURE_DEVICE_FN, e,
                         f"host {root}.* inside compiled code is baked "
                         "in as a trace-time constant — use jax.random "
                         "keys / pass clocks as arguments")
            return
        if (root in NP_ROOTS and isinstance(e.func, ast.Attribute)
                and isinstance(e.func.value, ast.Attribute)
                and e.func.value.attr == "random"):
            self.finding(IMPURE_DEVICE_FN, e,
                         "np.random inside compiled code is a "
                         "trace-time constant — use jax.random keys")
            return
        if trail in MUTATORS and isinstance(e.func, ast.Attribute):
            rroot = _root_name(e.func.value)
            if rroot == "self" or (rroot is not None
                                   and rroot not in self.locals):
                self.finding(IMPURE_DEVICE_FN, e,
                             f".{trail}() on captured state inside "
                             "compiled code — Counters/containers "
                             "mutate once at trace time, then never "
                             "again")

    def _jitted_call_site(self, e: ast.Call, trail: Optional[str]) -> None:
        binding = None
        if isinstance(e.func, ast.Name):
            if self.unit.cls:
                binding = self.model.binding(
                    self.unit.file, f"{self.unit.cls}.{e.func.id}")
            binding = binding or self.model.binding(self.unit.file,
                                                    e.func.id)
        elif (isinstance(e.func, ast.Attribute)
              and isinstance(e.func.value, ast.Name)
              and e.func.value.id == "self" and self.unit.cls):
            binding = self.model.binding(
                self.unit.file, f"{self.unit.cls}.self.{e.func.attr}")
        if binding is None:
            return
        for a in e.args:
            if (isinstance(a, ast.Starred)
                    and (isinstance(a.value, (ast.Set, ast.SetComp))
                         or (isinstance(a.value, ast.Call)
                             and _trailing(a.value.func) == "set"))):
                self.finding(RETRACE, a,
                             "set iteration feeds a jitted call "
                             "signature — set order varies per process, "
                             "so the same logical call produces "
                             "different signatures")
        for idx, a in enumerate(e.args):
            if idx in binding.static_argnums:
                self._static_arg(a, binding)
        for kw in e.keywords:
            if kw.arg in binding.static_argnames:
                self._static_arg(kw.value, binding)
        if binding.donate_argnums:
            for idx in binding.donate_argnums:
                if idx < len(e.args) and isinstance(e.args[idx], ast.Name):
                    self.donated[e.args[idx].id] = e.lineno

    def _static_arg(self, a: ast.expr, binding) -> None:
        if isinstance(a, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
            self.finding(RETRACE, a,
                         "non-hashable literal at a static position of "
                         f"'{binding.name}' — static args must hash "
                         "stably; use a tuple")
        elif isinstance(a, ast.Call):
            self.finding(RETRACE, a,
                         "per-call-computed value at a static position "
                         f"of '{binding.name}' — every distinct value "
                         "compiles a fresh executable")

    def _donation(self, e: ast.Call, trail: Optional[str]) -> None:
        if trail != "dispatch":
            return
        donating = False
        for kw in e.keywords:
            if kw.arg == "donate":
                donating = not (isinstance(kw.value, ast.Constant)
                                and kw.value.value in (False, None))
        if donating:
            for a in e.args:
                if isinstance(a, ast.Name):
                    self.donated[a.id] = e.lineno


# -- pass driver ------------------------------------------------------------

def run_passes(model: JitModel, min_hot_sites: int = 0) -> JitReport:
    report = JitReport(num_files=model.num_files)
    for unit in model.units:
        if unit.compiled:
            report.compiled_bodies += 1
        elif unit.hot:
            report.hot_sites += 1
        else:
            continue
        _BodyWalker(model, report, unit).run()
    report.jit_sites = len(model.jit_sites)
    for site in model.jit_sites:
        report.jit_site_kinds[site.kind] = (
            report.jit_site_kinds.get(site.kind, 0) + 1)
    if min_hot_sites and report.hot_sites < min_hot_sites:
        _emit(report, model, JitFinding(
            rule=VACUOUS_COVERAGE, file="<scan>", line=0,
            message=f"only {report.hot_sites} hot-path site(s) analyzed "
                    f"(< {min_hot_sites}) — the scan is not seeing the "
                    "runtime; a gate that sees nothing proves nothing"))
    return report


def analyze_paths(paths: Sequence[str],
                  min_hot_sites: int = 0) -> JitReport:
    return run_passes(scan_paths(paths), min_hot_sites=min_hot_sites)
