"""AST scan + hot-path model for jitcheck.

jitcheck rides on racecheck's thread-role machinery: the same class
model and seed-propagated role map decide WHICH bodies are hot (chain,
source-loop, dispatcher, completer, worker, uploader — the threads a
frame crosses between source and sink), and jitcheck then walks those
bodies with its own device-taint tracker. Separately it collects every
``jax.jit`` construction in the tree (the *static* compile-site map the
runtime gate checks observed CompileCache kinds against) plus the
bodies those constructions compile (``device_fn`` inner programs,
decorated ops, fused-segment programs), which get the purity and
retrace passes instead of the host-sync pass.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..concurrency.model import (CHAIN, COMPLETER, DISPATCHER, SOURCE,
                                 UPLOADER, WORKER, Model, live_roles,
                                 roles_of)
from ..concurrency.model import scan_paths as _scan_roles

PRAGMA_RE = re.compile(r"#\s*jitcheck:\s*ok\(([^)]*)\)")

# roles whose bodies sit on the frame path — a hidden sync here stalls
# the pipeline, not just one caller.
HOT_ROLES = frozenset({CHAIN, SOURCE, WORKER, DISPATCHER, COMPLETER,
                       UPLOADER})

# jitcheck-specific role entry points grafted onto racecheck's seeds:
# cross-object calls (element -> framework, decoder registry -> plugin,
# batcher -> scheduler) that intra-class propagation cannot reach.
EXTRA_SEEDS: List[Tuple[str, str, str]] = [
    ("FilterFramework", "invoke", CHAIN),
    ("FilterFramework", "dispatch", DISPATCHER),
    ("FilterFramework", "complete", COMPLETER),
    ("DecoderPlugin", "decode", CHAIN),
    ("ServeScheduler", "complete", WORKER),
    ("OverlapExecutor", "submit", DISPATCHER),
]

# (ancestor, method) -> parameter names that carry device arrays when
# the method runs (the taint seeds a signature implies).
DEVICE_PARAMS: Dict[Tuple[str, str], Tuple[str, ...]] = {
    ("ServeScheduler", "complete"): ("outputs",),
    # only the jax backend's dispatch handle holds device arrays — the
    # interop/simulated backends hand host objects around.
    ("JaxFilter", "complete"): ("handle",),
}

# methods whose inner ``def`` bodies the fusion planner / backends hand
# to jax.jit — those inner bodies are device programs.
COMPILED_WRAPPERS = frozenset({"device_fn", "_compile", "traceable_fn"})

# attribute reads that return host metadata, never a device value
META_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "nbytes",
                        "sharding", "is_device", "done", "dev", "name"})

# trailing call names whose RESULT is a device array (taint sources)
DEVICE_PRODUCERS = frozenset({"invoke", "dispatch", "device_put",
                              "place_batch", "with_sharding_constraint",
                              "tile_error"})

# .host() / jax.device_get() are the sanctioned materialization points
SANITIZERS = frozenset({"host", "device_get", "block_host"})


def site_kind(file: str) -> str:
    """Map a jit construction site to the CompileCache ``kind`` bucket
    the runtime half will observe for it."""
    p = file.replace("\\", "/")
    if "/fusion/" in p:
        return "fusion"
    if "/filters/" in p:
        return "jax"
    if "/ops/" in p:
        return "ops"
    if "/models/" in p:
        return "models"
    if "/parallel/" in p:
        return "parallel"
    if "/trainers/" in p:
        return "trainer"
    return Path(p).stem


@dataclass(frozen=True)
class JitSite:
    file: str
    line: int
    kind: str


@dataclass(frozen=True)
class JitBinding:
    """A name bound to a jitted callable (``f = jax.jit(fn, ...)`` or a
    jit decorator) — call sites of the name get the retrace checks."""
    name: str                       # "step" or "self._decode"
    file: str
    line: int
    static_argnums: Tuple[int, ...] = ()
    static_argnames: Tuple[str, ...] = ()
    donate_argnums: Tuple[int, ...] = ()


@dataclass
class FuncUnit:
    """One analyzable body: a method, module function, or inner def."""
    file: str
    cls: Optional[str]
    name: str
    node: ast.AST                   # FunctionDef / AsyncFunctionDef
    roles: Set[str] = field(default_factory=set)
    tainted_params: Set[str] = field(default_factory=set)
    compiled: bool = False          # body is traced/compiled by jax.jit

    @property
    def qual(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name

    @property
    def hot(self) -> bool:
        return bool(self.roles & HOT_ROLES)


@dataclass
class JitModel:
    roles_model: Optional[Model] = None
    units: List[FuncUnit] = field(default_factory=list)
    bindings: Dict[Tuple[str, str], JitBinding] = field(default_factory=dict)
    jit_sites: List[JitSite] = field(default_factory=list)
    pragmas: Dict[str, Dict[int, str]] = field(default_factory=dict)
    num_files: int = 0

    def pragma_reason(self, file: str, lineno: int) -> Optional[str]:
        """pragma on the line itself or the line above."""
        table = self.pragmas.get(file, {})
        return table.get(lineno) or table.get(lineno - 1)

    def binding(self, file: str, name: str) -> Optional[JitBinding]:
        return self.bindings.get((file, name))


# -- per-file collection ----------------------------------------------------

def _trailing_attr(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _root_name(node: ast.AST) -> Optional[str]:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class _FileCollector:
    """Walks one module: finds jit constructions + bindings, classifies
    function bodies into units, and marks compiled bodies."""

    def __init__(self, model: JitModel, ro_model: Model, label: str):
        self.model = model
        self.ro = ro_model
        self.label = label
        self.jax_names: Set[str] = {"jax"}
        self.jit_names: Set[str] = set()       # from jax import jit [as j]
        self.partial_names: Set[str] = {"partial", "functools"}

    # -- jit construction recognition --
    def is_jit_func(self, func: ast.AST) -> bool:
        if isinstance(func, ast.Attribute) and func.attr == "jit":
            return _root_name(func) in self.jax_names
        if isinstance(func, ast.Name):
            return func.id in self.jit_names
        return False

    def jit_call_of(self, node: ast.AST) -> Optional[ast.Call]:
        """Return the jax.jit(...) Call inside ``node`` if node is a jit
        construction: jax.jit(...), partial(jax.jit, ...), or the bare
        jax.jit / imported jit name used as a decorator."""
        if isinstance(node, ast.Call):
            if self.is_jit_func(node.func):
                return node
            if (_trailing_attr(node.func) in ("partial",)
                    and node.args and self.is_jit_func(node.args[0])):
                return node
        return None

    def is_jit_decorator(self, dec: ast.AST) -> Optional[ast.Call]:
        call = self.jit_call_of(dec)
        if call is not None:
            return call
        if self.is_jit_func(dec):
            return ast.Call(func=dec, args=[], keywords=[])  # bare @jax.jit
        return None

    @staticmethod
    def _const_ints(node: ast.AST) -> Tuple[int, ...]:
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return (node.value,)
        if isinstance(node, (ast.Tuple, ast.List)):
            out = []
            for e in node.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.append(e.value)
            return tuple(out)
        return ()

    @staticmethod
    def _const_strs(node: ast.AST) -> Tuple[str, ...]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return (node.value,)
        if isinstance(node, (ast.Tuple, ast.List)):
            return tuple(e.value for e in node.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str))
        return ()

    def binding_from(self, name: str, call: ast.Call,
                     line: int) -> JitBinding:
        statics: Tuple[int, ...] = ()
        argnames: Tuple[str, ...] = ()
        donate: Tuple[int, ...] = ()
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                statics = self._const_ints(kw.value)
            elif kw.arg == "static_argnames":
                argnames = self._const_strs(kw.value)
            elif kw.arg == "donate_argnums":
                donate = self._const_ints(kw.value)
        return JitBinding(name=name, file=self.label, line=line,
                          static_argnums=statics, static_argnames=argnames,
                          donate_argnums=donate)

    def note_site(self, node: ast.AST) -> None:
        self.model.jit_sites.append(JitSite(
            file=self.label, line=getattr(node, "lineno", 0),
            kind=site_kind(self.label)))

    # -- module walk --
    def scan(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax":
                        self.jax_names.add(a.asname or "jax")
                    elif a.name == "functools":
                        self.partial_names.add(a.asname or "functools")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "jax":
                    for a in node.names:
                        if a.name == "jit":
                            self.jit_names.add(a.asname or "jit")
                elif node.module == "functools":
                    for a in node.names:
                        if a.name == "partial":
                            self.partial_names.add(a.asname or "partial")

        # every jit construction anywhere in the tree is a site
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and self.jit_call_of(node):
                self.note_site(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if not isinstance(dec, ast.Call) and self.is_jit_func(dec):
                        self.note_site(dec)   # bare @jax.jit decorator

        for stmt in tree.body:
            if isinstance(stmt, ast.ClassDef):
                self._scan_class(stmt)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(stmt, cls=None, roles={"api"})
                for inner in ast.walk(stmt):
                    if isinstance(inner, ast.Assign):
                        self._scan_binding_assign(
                            inner, list(stmt.body), cls=None)
            elif isinstance(stmt, ast.Assign):
                self._scan_binding_assign(stmt, tree.body, cls=None)

    def _scan_binding_assign(self, stmt: ast.Assign, scope_body,
                             cls: Optional[str]) -> None:
        call = self.jit_call_of(stmt.value) if isinstance(
            stmt.value, ast.Call) else None
        if call is None:
            return
        for tgt in stmt.targets:
            name = None
            if isinstance(tgt, ast.Name):
                name = tgt.id
            elif (isinstance(tgt, ast.Attribute)
                  and isinstance(tgt.value, ast.Name)
                  and tgt.value.id == "self"):
                name = f"self.{tgt.attr}"
            if name:
                key = (self.label, f"{cls}.{name}" if cls else name)
                self.model.bindings[key] = self.binding_from(
                    name, call, stmt.lineno)
        # jax.jit(fn) over a sibling def marks fn's body compiled
        if call.args and isinstance(call.args[0], ast.Name):
            self._mark_compiled_def(call.args[0].id, scope_body)

    def _mark_compiled_def(self, fname: str, scope_body) -> None:
        for s in scope_body:
            if (isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and s.name == fname):
                for u in self.model.units:
                    if u.node is s:
                        u.compiled = True
                        return
                self._add_unit(s, cls=None, roles=set(), compiled=True)
                return

    def _add_unit(self, node, cls, roles, compiled=False,
                  tainted: Optional[Set[str]] = None) -> FuncUnit:
        unit = FuncUnit(file=self.label, cls=cls, name=node.name,
                        node=node, roles=set(roles),
                        tainted_params=set(tainted or ()),
                        compiled=compiled)
        self.model.units.append(unit)
        return unit

    def _decorated_jit(self, node) -> Optional[ast.Call]:
        for dec in node.decorator_list:
            call = self.is_jit_decorator(dec)
            if call is not None:
                return call
        return None

    def _scan_function(self, node, cls: Optional[str],
                       roles: Set[str],
                       tainted: Optional[Set[str]] = None) -> None:
        dec_call = self._decorated_jit(node)
        unit = self._add_unit(node, cls, roles, compiled=bool(dec_call),
                              tainted=tainted)
        if dec_call is not None:
            key = (self.label, f"{cls}.{node.name}" if cls else node.name)
            self.model.bindings[key] = self.binding_from(
                node.name, dec_call, node.lineno)
        self._scan_inner(node, outer_compiled=bool(dec_call),
                         wrapper=node.name in COMPILED_WRAPPERS)

    def _scan_inner(self, node, outer_compiled: bool,
                    wrapper: bool) -> None:
        """Inner defs: compiled if the enclosing scope jits them (by
        name or by being a COMPILED_WRAPPERS method), else skipped —
        they run in the enclosing body's role and the walker inlines
        nothing."""
        body = list(node.body)
        jitted_names: Set[str] = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Call):
                call = self.jit_call_of(n)
                if call is not None and call.args and isinstance(
                        call.args[0], ast.Name):
                    jitted_names.add(call.args[0].id)
        for s in body:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if (wrapper or outer_compiled or s.name in jitted_names
                        or self._decorated_jit(s) is not None
                        or s.name in COMPILED_WRAPPERS):
                    self._add_unit(s, cls=None, roles=set(), compiled=True)
                    self._scan_inner(s, outer_compiled=True, wrapper=False)

    def _scan_class(self, cnode: ast.ClassDef) -> None:
        roles_map = roles_of(self.ro, cnode.name, extra_seeds=EXTRA_SEEDS)
        ancestry = set(self.ro.ancestry(cnode.name)) | {cnode.name}
        for stmt in cnode.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                roles = live_roles(roles_map.get(stmt.name, {"api"}))
                tainted: Set[str] = set()
                for (base, meth), params in DEVICE_PARAMS.items():
                    if base in ancestry and meth == stmt.name:
                        tainted.update(params)
                self._scan_function(stmt, cls=cnode.name, roles=roles,
                                    tainted=tainted)
                for inner in ast.walk(stmt):
                    if isinstance(inner, ast.Assign):
                        self._scan_binding_assign(
                            inner, list(stmt.body), cls=cnode.name)


def scan_paths(paths: Sequence[str]) -> JitModel:
    """Parse every ``.py`` under the given files/directories into one
    JitModel (racecheck's role model rides along for the hot-path
    classification). Unparseable files are skipped — compileall's
    problem, not jitcheck's."""
    ro_model = _scan_roles(paths)
    model = JitModel(roles_model=ro_model)
    files: List[Path] = []
    for p in paths:
        path = Path(p)
        if path.is_dir():
            files.extend(sorted(path.rglob("*.py")))
        elif path.suffix == ".py":
            files.append(path)
    seen: Set[Path] = set()
    for path in files:
        rp = path.resolve()
        if rp in seen:
            continue
        seen.add(rp)
        try:
            source = path.read_text(encoding="utf-8")
            tree = ast.parse(source)
        except (OSError, SyntaxError, UnicodeDecodeError):
            continue
        label = str(path)
        model.num_files += 1
        table: Dict[int, str] = {}
        for n, line in enumerate(source.splitlines(), 1):
            m = PRAGMA_RE.search(line)
            if m:
                table[n] = m.group(1).strip() or "unspecified"
        if table:
            model.pragmas[label] = table
        _FileCollector(model, ro_model, label).scan(tree)
    return model
