"""Runtime half of jitcheck: the compile-stability monitor.

The static passes predict WHERE compilation may happen (the jit-site
map, bucketed by CompileCache ``kind``); the runtime half observes what
actually happened — per-element ``jit_hits`` / ``jit_misses`` /
``jit_prewarmed`` / ``jit_recompiles`` counters plus (where the jax
build exposes it) ``jax.monitoring`` compile events — and
``check_against_static`` closes the contract:

* steady-state recompiles == 0 — a warmed process serving the same
  traffic must never compile on the frame path again;
* observed signatures ⊆ statically predicted — every CompileCache
  ``kind`` that recorded a signature must correspond to a jit
  construction the static scan saw (a kind the scan can't see means
  the model is unhooked, the gate's version of vacuous coverage).

``tools/jit_stability.py`` (``make jit-stability``) drives the builtin
corpus through two passes and applies exactly this check.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, Set

JIT_STAT_KEYS = ("jit_recompiles", "jit_misses", "jit_hits",
                 "jit_prewarmed")


def jit_stat_snapshot(pipeline: Any) -> Dict[str, Dict[str, int]]:
    """Per-element jit counters for every element that has any (filter
    backends and fused segments), from one consistent stats() pass."""
    out: Dict[str, Dict[str, int]] = {}
    for name, snap in pipeline.stats().items():
        row = {k: int(snap[k]) for k in JIT_STAT_KEYS if k in snap}
        if row:
            out[name] = row
    return out


def steady_recompiles(snapshot: Dict[str, Dict[str, int]]) -> int:
    """Frame-path compilations in the window the snapshot covers: a
    filter's post-warmup signature compiles plus a fused segment's
    program-cache misses. Both must be zero once warm."""
    return sum(row.get("jit_recompiles", 0) + row.get("jit_misses", 0)
               for row in snapshot.values())


class CompileEventMonitor:
    """Counts jax.monitoring compile events process-wide. Best-effort:
    older jax builds without the monitoring hooks degrade to a counter
    that stays at zero (``available`` says which you got), and jax only
    offers clear-all, so ``install()`` is one-way — ``reset()`` rebases
    the count instead of unregistering."""

    def __init__(self) -> None:
        self.available = False
        self._count = 0
        self._base = 0
        self.events: Dict[str, int] = {}

    def _on_event(self, event: str, **kwargs: Any) -> None:
        if "compil" in event:
            self._count += 1
            self.events[event] = self.events.get(event, 0) + 1

    def install(self) -> "CompileEventMonitor":
        try:
            from jax import monitoring
            monitoring.register_event_listener(self._on_event)
            if hasattr(monitoring, "register_event_duration_secs_listener"):
                monitoring.register_event_duration_secs_listener(
                    lambda event, duration, **kw: self._on_event(event))
            self.available = True
        except Exception:
            self.available = False
        return self

    def reset(self) -> None:
        self._base = self._count

    @property
    def count(self) -> int:
        return self._count - self._base


@dataclass
class StabilityResult:
    steady_recompiles: int
    observed_kinds: Set[str] = field(default_factory=set)
    static_kinds: Set[str] = field(default_factory=set)
    problems: list = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.problems

    def __str__(self) -> str:
        status = "ok" if self.ok else "BROKEN"
        return (f"jit-stability {status}: steady recompiles="
                f"{self.steady_recompiles}, observed kinds="
                f"{sorted(self.observed_kinds)} ⊆ static "
                f"{sorted(self.static_kinds)}"
                + ("".join(f"\n  {p}" for p in self.problems)))


def check_against_static(static: Any,
                         observed_kinds: Iterable[str],
                         steady: int,
                         strict: bool = True) -> StabilityResult:
    """The static↔runtime contract. ``static`` is a JitReport (or any
    object with ``jit_site_kinds``) or a plain iterable of kind names;
    ``observed_kinds`` is what CompileCache recorded; ``steady`` is the
    second-pass recompile count. Raises AssertionError with the full
    breakdown when strict (the gate path), else returns the result."""
    kinds = getattr(static, "jit_site_kinds", None)
    static_kinds = set(kinds) if kinds is not None else set(static)
    observed = set(observed_kinds)
    result = StabilityResult(steady_recompiles=int(steady),
                             observed_kinds=observed,
                             static_kinds=static_kinds)
    if steady:
        result.problems.append(
            f"{steady} compilation(s) on the frame path of a warmed "
            "process — the compile cache is not holding steady state")
    extra = observed - static_kinds
    if extra:
        result.problems.append(
            f"observed compile kind(s) {sorted(extra)} have no "
            "statically predicted jit site — the static scan does not "
            "see the code that compiled them")
    if strict and result.problems:
        raise AssertionError(str(result))
    return result
