"""Finding/severity/report model for pipelint.

A ``Finding`` pins a defect to an element (and, when known, the pad
where it was detected). A ``Report`` aggregates findings and maps them
to the CLI exit-code contract: 0 clean (info only), 1 warnings,
2 errors.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from enum import IntEnum
from typing import List, Optional


class Severity(IntEnum):
    """Ordered so ``max(findings)`` is the report verdict."""

    INFO = 0
    WARNING = 1
    ERROR = 2

    def __str__(self) -> str:  # "error", not "Severity.ERROR"
        return self.name.lower()


@dataclass(frozen=True)
class Finding:
    rule: str
    severity: Severity
    message: str
    element: Optional[str] = None
    pad: Optional[str] = None

    @property
    def location(self) -> str:
        if self.element is None:
            return "<pipeline>"
        return f"{self.element}.{self.pad}" if self.pad else self.element

    def to_dict(self) -> dict:
        return {"rule": self.rule, "severity": str(self.severity),
                "element": self.element, "pad": self.pad,
                "location": self.location, "message": self.message}

    def __str__(self) -> str:
        return (f"{str(self.severity):7s} {self.rule:22s} "
                f"{self.location}: {self.message}")


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)
    num_elements: int = 0

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == Severity.ERROR]

    @property
    def warnings(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == Severity.WARNING]

    @property
    def exit_code(self) -> int:
        """0 clean / 1 warnings / 2 errors (the CLI contract)."""
        if self.errors:
            return 2
        if self.warnings:
            return 1
        return 0

    def by_rule(self, rule: str) -> List[Finding]:
        return [f for f in self.findings if f.rule == rule]

    def to_text(self) -> str:
        lines = [str(f) for f in sorted(
            self.findings, key=lambda f: (-int(f.severity), f.rule))]
        lines.append(
            f"pipelint: {len(self.errors)} error(s), "
            f"{len(self.warnings)} warning(s), "
            f"{len(self.findings) - len(self.errors) - len(self.warnings)} "
            f"info in {self.num_elements} element(s)")
        return "\n".join(lines)

    def to_json(self) -> str:
        return json.dumps({
            "findings": [f.to_dict() for f in self.findings],
            "errors": len(self.errors), "warnings": len(self.warnings),
            "elements": self.num_elements, "exit_code": self.exit_code,
        }, indent=2)


class PipelineValidationError(ValueError):
    """Raised by ``Pipeline.start()`` when validation finds errors."""

    def __init__(self, report: Report):
        self.report = report
        errs = "; ".join(f"{f.location}: {f.message}" for f in report.errors)
        super().__init__(
            f"pipeline failed validation with {len(report.errors)} "
            f"error(s): {errs} (set pipeline.validate_on_start=False to "
            f"launch anyway)")
