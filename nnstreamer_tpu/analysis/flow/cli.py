"""``python -m nnstreamer_tpu flowcheck`` — the settlement lint CLI.

    flowcheck [paths...] [--json] [-o FILE] [-q] [-v]
              [--min-acquire-sites N]

Scans the given files/directories (default: the installed
``nnstreamer_tpu`` package) and reports leak, double-settle,
missing-declared-loss, and identity-break findings. Exit codes:
0 clean, 1 findings, 2 usage error.
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List, Optional

from .passes import analyze_paths

USAGE_ERROR = 2


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="nnstreamer_tpu flowcheck",
        description="static settlement & resource-conservation "
                    "analyzer (acquire/settle leaks, double-settles, "
                    "undeclared losses, identity breaks) for the "
                    "zero-loss accounting model")
    ap.add_argument("paths", nargs="*",
                    help="files or directories to scan (default: the "
                         "nnstreamer_tpu package)")
    ap.add_argument("--json", action="store_true",
                    help="emit machine-readable findings")
    ap.add_argument("-o", "--output", metavar="FILE",
                    help="also write the report (JSON) to FILE")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="suppress output; exit code only")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="list suppressed findings too")
    ap.add_argument("--min-acquire-sites", type=int, default=0,
                    metavar="N",
                    help="fail unless at least N acquire sites are "
                         "modeled (vacuous-coverage guard; default 0)")
    try:
        opts = ap.parse_args(argv)
    except SystemExit as exc:
        # argparse exits 2 on bad flags and 0 on --help: keep both
        return int(exc.code or 0) and USAGE_ERROR

    paths = opts.paths or [str(Path(__file__).resolve().parents[2])]
    for p in paths:
        if not Path(p).exists():
            print(f"flowcheck: no such path: {p}", file=sys.stderr)
            return USAGE_ERROR

    report = analyze_paths(paths,
                           min_acquire_sites=opts.min_acquire_sites)

    if opts.output:
        out = Path(opts.output)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(report.to_json() + "\n", encoding="utf-8")
    if not opts.quiet:
        print(report.to_json() if opts.json
              else report.to_text(verbose=opts.verbose))
    return report.exit_code


if __name__ == "__main__":
    raise SystemExit(main())
