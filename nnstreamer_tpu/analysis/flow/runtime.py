"""Runtime cross-check of the declared conservation identities.

The static pass proves every identity term is *produced* somewhere;
this validator proves the arithmetic actually balances over live
``Counters`` snapshots — the serve, chaos, and router suites call
:func:`check_identities` on their merged reports so a settlement bug
that slips past the AST model still fails a fast test, not a slow
chaos run.

    from nnstreamer_tpu.analysis.flow import check_identities
    snap = dict(scheduler.report())
    snap["pending"] = scheduler.pending()
    check_identities(snap, names=["serve-settlement"])

An identity is evaluated when every one of its term names is a key of
the snapshot (terms the caller can't observe simply exclude the
identity — unless it was requested by name, which makes a missing term
an error). Violations raise ``AssertionError`` with a per-term
breakdown; ``strict=False`` returns the results for inspection
instead.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Mapping, Optional, Tuple

from .registry import DECLARED_IDENTITIES, Identity, identities_by_name


@dataclass(frozen=True)
class IdentityResult:
    name: str
    expression: str
    lhs: Tuple[str, int]
    rhs: Tuple[Tuple[str, int], ...]
    holds: bool

    def breakdown(self) -> str:
        terms = " + ".join(f"{n}={v}" for n, v in self.rhs)
        total = sum(v for _, v in self.rhs)
        status = "holds" if self.holds else "VIOLATED"
        return (f"{self.name}: {self.lhs[0]}={self.lhs[1]} vs "
                f"{terms} (= {total}) — {status}")


def check_identities(snapshot: Mapping[str, int],
                     names: Optional[Iterable[str]] = None,
                     strict: bool = True) -> List[IdentityResult]:
    """Assert the declared conservation identities over a counter
    snapshot. Returns one :class:`IdentityResult` per identity
    evaluated; raises ``AssertionError`` on any violation (or on a
    requested-by-name identity whose terms the snapshot lacks) unless
    ``strict=False``."""
    if names is None:
        selected: List[Identity] = list(DECLARED_IDENTITIES)
        required = False
    else:
        by_name = identities_by_name()
        unknown = [n for n in names if n not in by_name]
        if unknown:
            raise KeyError(f"unknown identity name(s): {unknown} "
                           f"(known: {sorted(by_name)})")
        selected = [by_name[n] for n in names]
        required = True

    results: List[IdentityResult] = []
    problems: List[str] = []
    for ident in selected:
        term_names = [t.name for t in ident.terms()]
        missing = [n for n in term_names if n not in snapshot]
        if missing:
            if required:
                problems.append(
                    f"{ident.name}: snapshot lacks term(s) {missing} "
                    f"(needs {term_names})")
            continue
        lhs_v = int(snapshot[ident.lhs.name])
        rhs = tuple((t.name, int(snapshot[t.name])) for t in ident.rhs)
        holds = lhs_v == sum(v for _, v in rhs)
        res = IdentityResult(name=ident.name,
                             expression=ident.expression,
                             lhs=(ident.lhs.name, lhs_v),
                             rhs=rhs, holds=holds)
        results.append(res)
        if not holds:
            problems.append(res.breakdown())

    if problems and strict:
        raise AssertionError(
            "conservation identity violation:\n  "
            + "\n  ".join(problems))
    return results
