"""Findings assembly for flowcheck.

The path walk (:mod:`.model`) already produced the raw leak /
double-settle / missing-declared-loss events; this module applies the
``# flowcheck: ok(reason)`` pragma, runs the module-level
identity-break pass (every statically declared conservation identity
must have each of its counter terms *produced* in its declaring file),
enforces the vacuous-coverage guard, and sorts everything into a
:class:`~.findings.FlowReport`.
"""
from __future__ import annotations

from typing import List, Sequence

from .findings import (IDENTITY_BREAK, VACUOUS_COVERAGE, FlowFinding,
                       FlowReport)
from .model import FlowModel, scan_paths
from .registry import DECLARED_IDENTITIES, Identity


def _emit(report: FlowReport, model: FlowModel,
          finding: FlowFinding) -> None:
    reason = model.pragma_reason(finding.file, finding.line)
    if reason is not None:
        report.suppressed.append(finding)
    else:
        report.findings.append(finding)


def _files_matching(model: FlowModel, suffix: str) -> List[str]:
    """Scanned files whose path ends with the registry's ``file``
    suffix (``serve/batcher.py``)."""
    return [f for f in model.files
            if f.replace("\\", "/").endswith(suffix)]


def _check_identity(report: FlowReport, model: FlowModel,
                    ident: Identity) -> bool:
    """Identity-break pass for one identity. Returns True when the
    identity was applicable to this scan (all declaring files present)
    and therefore counted as checked."""
    static_terms = [t for t in ident.terms() if t.counter and t.file]
    if not static_terms:
        return False
    per_term_files = {}
    for t in static_terms:
        matched = _files_matching(model, t.file)
        if not matched:
            return False        # declaring module outside this scan
        per_term_files[t] = matched
    for t in static_terms:
        produced = any(t.counter in model.productions.get(f, set())
                       for f in per_term_files[t])
        if not produced:
            _emit(report, model, FlowFinding(
                rule=IDENTITY_BREAK,
                file=per_term_files[t][0],
                line=ident.line,
                message=(f"identity '{ident.name}' "
                         f"({ident.expression}) declares term "
                         f"'{t.name}' but counter '{t.counter}' is "
                         f"never produced in {t.file} — the identity "
                         f"cannot balance"),
                resource=ident.name))
    return True


def run_passes(model: FlowModel,
               min_acquire_sites: int = 0) -> FlowReport:
    report = FlowReport(num_files=model.num_files,
                        num_functions=model.num_functions,
                        acquire_sites=model.acquire_sites)
    for finding in model.raw:
        _emit(report, model, finding)

    checked: List[str] = []
    for ident in DECLARED_IDENTITIES:
        if _check_identity(report, model, ident):
            checked.append(ident.name)
    for ident in model.module_identities:
        if _check_identity(report, model, ident):
            checked.append(ident.name)
    report.identities_checked = tuple(checked)

    if min_acquire_sites and model.acquire_sites < min_acquire_sites:
        scope = model.files[0] if model.files else "(empty scan)"
        report.findings.append(FlowFinding(
            rule=VACUOUS_COVERAGE, file=scope, line=0,
            message=(f"only {model.acquire_sites} acquire site(s) "
                     f"modeled (< {min_acquire_sites}): the scan "
                     f"proves nothing — receiver regexes or "
                     f"decorations have rotted")))

    report.findings.sort(key=lambda f: (f.rule, f.file, f.line))
    report.suppressed.sort(key=lambda f: (f.file, f.line))
    return report


def analyze_paths(paths: Sequence[str],
                  min_acquire_sites: int = 0) -> FlowReport:
    return run_passes(scan_paths(paths), min_acquire_sites)
