"""flowcheck — static settlement & resource-conservation analysis.

pipelint validates pipeline GRAPHS, racecheck validates the lock
discipline of the CODE; flowcheck proves the third property family:
*conservation along every code path*. Every acquired resource token
(window slot, KV block, accepted socket, admitted request) must settle
exactly once — or its ownership must provably escape — on every path,
including exception edges; every lossy settle must declare its loss in
a counter; and every module's declared conservation identity must be
both statically producible and arithmetically true at runtime.

    from nnstreamer_tpu.analysis.flow import analyze_paths
    report = analyze_paths(["nnstreamer_tpu/"])
    assert report.exit_code == 0, report.to_text()

See Documentation/accounting.md for the conservation model, the
declared identities, ``@flow.acquires/@flow.settles`` annotation, the
``# flow: owns(resource)`` handoff marker, and the
``# flowcheck: ok(reason)`` suppression pragma.
"""
from .findings import (DOUBLE_SETTLE, IDENTITY_BREAK, LEAK,
                       MISSING_DECLARED_LOSS, VACUOUS_COVERAGE,
                       FlowFinding, FlowReport)
from .model import FlowModel, scan_paths
from .passes import analyze_paths, run_passes
from .registry import (DECLARED_IDENTITIES, Identity, IdentityTerm,
                       ResourceSpec, SPECS)
from .runtime import IdentityResult, check_identities

__all__ = [
    "analyze_paths", "run_passes", "scan_paths", "FlowModel",
    "FlowFinding", "FlowReport", "LEAK", "DOUBLE_SETTLE",
    "MISSING_DECLARED_LOSS", "IDENTITY_BREAK", "VACUOUS_COVERAGE",
    "ResourceSpec", "SPECS", "Identity", "IdentityTerm",
    "DECLARED_IDENTITIES", "check_identities", "IdentityResult",
]
