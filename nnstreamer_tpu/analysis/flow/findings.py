"""Finding/report model for flowcheck.

Same shape as racecheck's (``file:line``-pinned findings, 0/1/2 exit
contract, suppressions listed separately) with one extra axis: a
finding names the *resource* whose conservation it violates, and the
report carries the coverage counters (acquire sites modeled, identities
checked) the vacuous-coverage guard and the docs generator read.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

# finding classes (the ``rule`` field)
LEAK = "leak"
DOUBLE_SETTLE = "double-settle"
MISSING_DECLARED_LOSS = "missing-declared-loss"
IDENTITY_BREAK = "identity-break"
VACUOUS_COVERAGE = "vacuous-coverage"


@dataclass(frozen=True)
class FlowFinding:
    rule: str
    file: str
    line: int
    message: str
    resource: Optional[str] = None  # resource or identity name involved
    func: Optional[str] = None      # qualified function, e.g. "Cls.meth"

    @property
    def location(self) -> str:
        return f"{self.file}:{self.line}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "location": self.location, "resource": self.resource,
                "func": self.func, "message": self.message}

    def __str__(self) -> str:
        return f"{self.rule:22s} {self.location}: {self.message}"


@dataclass
class FlowReport:
    findings: List[FlowFinding] = field(default_factory=list)
    suppressed: List[FlowFinding] = field(default_factory=list)
    num_files: int = 0
    num_functions: int = 0
    # coverage: matched acquire call sites (+ `# flow: owns()` markers)
    # and declared identities whose terms were statically checked — the
    # vacuous-coverage guard fails the gate when acquire_sites falls
    # under the CLI's --min-acquire-sites floor.
    acquire_sites: int = 0
    identities_checked: Tuple[str, ...] = ()

    def by_rule(self, rule: str) -> List[FlowFinding]:
        return [f for f in self.findings if f.rule == rule]

    @property
    def exit_code(self) -> int:
        """0 clean / 1 findings (suppressions don't count) — the CLI
        maps usage errors to 2 before analysis ever runs."""
        return 1 if self.findings else 0

    def to_text(self, verbose: bool = False) -> str:
        lines = [str(f) for f in sorted(
            self.findings, key=lambda f: (f.rule, f.file, f.line))]
        if verbose:
            lines += [f"suppressed {f}" for f in sorted(
                self.suppressed, key=lambda f: (f.file, f.line))]
        lines.append(
            f"flowcheck: {len(self.findings)} finding(s) "
            f"({len(self.suppressed)} suppressed) across "
            f"{self.num_files} file(s) / {self.num_functions} "
            f"function(s); {self.acquire_sites} acquire site(s) "
            f"modeled, {len(self.identities_checked)} identity(ies) "
            f"checked")
        return "\n".join(lines)

    def to_json(self) -> str:
        return json.dumps({
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "files": self.num_files,
            "functions": self.num_functions,
            "acquire_sites": self.acquire_sites,
            "identities_checked": list(self.identities_checked),
            "exit_code": self.exit_code,
        }, indent=2)
