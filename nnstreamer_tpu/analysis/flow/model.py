"""Path-sensitive acquire/settle extraction for flowcheck.

The scan runs in two phases over the parsed sources — no code is ever
executed:

**Phase 1 (registration)** collects, per file: ``# flowcheck: ok(...)``
pragma lines, ``# flow: owns(resource)`` ownership markers,
``@flow.acquires/@flow.settles`` decorations (which union the decorated
method NAMES into the matching :class:`~.registry.ResourceSpec`, or
mint a new any-receiver spec for a resource name the registry doesn't
know — how the fixture corpus declares toy resources), module-level
``FLOW_IDENTITY = "lhs == a + b"`` declarations, and every statically
visible ``Counters`` *production* site (``.inc("x")``, ``.add(x=...)``,
``c["x"] = ...`` — ``update()``/constructor seeding is initialisation,
not production).

**Phase 2 (path walk)** symbolically executes every function: an
acquire call (or owns marker) mints a *token*; the walker then forks
the state at branches, exception edges (every non-whitelisted call may
raise), loop bodies (0-or-1 iteration), and ``try``/``except``/
``finally`` (every handler is assumed to catch everything; ``finally``
applies to all outcome classes) and demands that on every path each
token is settled exactly once or its ownership provably *escapes*
(stored to an attribute/container, returned/yielded, passed to a
non-borrowing call, captured by a closure). Violations surface as
``leak`` / ``double-settle`` findings; a lossy settle whose path never
bumps a declared loss counter surfaces as ``missing-declared-loss``.

The model is deliberately optimistic where the repo's idiom is sound
(ownership transfers on argument passing even when the callee raises;
``if tok is None`` kills the token in the failure branch) and
pessimistic where leaks actually ship (any unlisted call can raise
between acquire and settle).
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field, replace
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .findings import (DOUBLE_SETTLE, LEAK, MISSING_DECLARED_LOSS,
                       FlowFinding)
from .registry import (Identity, ResourceSpec, SPECS, parse_identity_expr)

PRAGMA_RE = re.compile(r"#\s*flowcheck:\s*ok\(([^)]*)\)")
OWNS_RE = re.compile(r"#\s*flow:\s*owns\(([^)]*)\)")

# cap on simultaneously tracked states per function: path explosion is
# truncated, never an error (coverage degrades gracefully)
MAX_STATES = 400

# calls trusted not to raise AND not to take ownership of arguments
# (builtins, logging, container/sync primitives, clocks, Counters)
TRUSTED_CALLS = {
    "len", "int", "float", "str", "bool", "list", "dict", "tuple", "set",
    "frozenset", "min", "max", "sum", "sorted", "reversed", "isinstance",
    "issubclass", "getattr", "hasattr", "setattr", "enumerate", "range",
    "zip", "map", "filter", "repr", "print", "abs", "id", "round", "any",
    "all", "iter", "next", "format", "divmod",
    "append", "appendleft", "extend", "insert", "remove", "discard",
    "notify", "notify_all", "clear", "popleft", "pop", "get", "put",
    "setdefault", "index", "count",
    "inc", "add", "update", "snapshot", "items", "keys", "values",
    "copy", "deepcopy",
    "acquire", "release", "wait", "join", "close", "start", "is_alive",
    "locked", "set", "is_set",
    "info", "debug", "warning", "error", "exception", "log",
    "time", "time_ns", "monotonic", "monotonic_ns", "perf_counter",
    "perf_counter_ns", "sleep",
}

# default loss counters granted to fixture-declared (decorator-minted)
# resources so `settles("res", "loss")` is testable without a registry
# entry
DEFAULT_LOSS_COUNTERS = frozenset(
    {"declared_lost", "dropped", "shed", "lost", "evicted"})


def _receiver_of(func: ast.AST) -> Optional[str]:
    """Dotted receiver of a call target: ``self.mgr.alloc`` -> "self.mgr",
    ``pool.alloc`` -> "pool", bare ``alloc`` -> "", non-name chains
    (e.g. calls) -> None."""
    if isinstance(func, ast.Name):
        return ""
    if not isinstance(func, ast.Attribute):
        return None
    parts: List[str] = []
    node = func.value
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _root_name(node: ast.AST) -> Optional[str]:
    """``entry.t_dispatch_ns`` -> "entry", ``x`` -> "x", else None."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


@dataclass
class _Token:
    spec_name: str
    line: int                       # acquire/owns line
    names: Set[str] = field(default_factory=set)
    settled: bool = False
    escaped: bool = False

    def copy(self) -> "_Token":
        return _Token(self.spec_name, self.line, set(self.names),
                      self.settled, self.escaped)


class _State:
    """One symbolic path: live/settled tokens, pending declared losses,
    loss counters already bumped."""

    __slots__ = ("tokens", "pending_loss", "bumped")

    def __init__(self) -> None:
        self.tokens: List[_Token] = []
        self.pending_loss: List[Tuple[str, int]] = []  # (spec, line)
        self.bumped: Set[str] = set()

    def clone(self) -> "_State":
        st = _State()
        st.tokens = [t.copy() for t in self.tokens]
        st.pending_loss = list(self.pending_loss)
        st.bumped = set(self.bumped)
        return st


# outcome kinds
_FALL, _RETURN, _RAISE, _BREAK, _CONTINUE = (
    "fall", "return", "raise", "break", "continue")


@dataclass
class FlowModel:
    """Everything the passes need: raw (pre-pragma) findings from the
    path walk, pragma/production tables, declared fixture identities,
    and coverage counters."""
    raw: List[FlowFinding] = field(default_factory=list)
    pragmas: Dict[str, Dict[int, str]] = field(default_factory=dict)
    productions: Dict[str, Set[str]] = field(default_factory=dict)
    module_identities: List[Identity] = field(default_factory=list)
    files: List[str] = field(default_factory=list)
    acquire_sites: int = 0
    num_files: int = 0
    num_functions: int = 0
    specs: Tuple[ResourceSpec, ...] = SPECS

    def pragma_reason(self, file: str, lineno: int) -> Optional[str]:
        """``# flowcheck: ok(reason)`` on the line or the line above."""
        table = self.pragmas.get(file, {})
        for ln in (lineno, lineno - 1):
            if ln in table:
                return table[ln]
        return None


class _FunctionAnalyzer:
    """Walks one function body over all paths, emitting raw findings
    into the shared model."""

    def __init__(self, model: FlowModel, file: str, qualname: str,
                 specs: Sequence[ResourceSpec],
                 owns: Dict[int, str]) -> None:
        self.model = model
        self.file = file
        self.func = qualname
        self.specs = specs
        self.spec_by_name = {s.name: s for s in specs}
        self.owns = owns
        self._seen: Set[Tuple[str, int, str]] = set()

    # -- finding emission --------------------------------------------------
    def _event(self, rule: str, line: int, resource: str,
               message: str) -> None:
        key = (rule, line, resource)
        if key in self._seen:
            return
        self._seen.add(key)
        self.model.raw.append(FlowFinding(
            rule=rule, file=self.file, line=line, message=message,
            resource=resource, func=self.func))

    # -- entry -------------------------------------------------------------
    def run(self, fnode: ast.AST) -> None:
        self.model.num_functions += 1
        outcomes = self._walk(list(fnode.body), _State())
        for kind, st, line in outcomes:
            for tok in st.tokens:
                if tok.settled or tok.escaped:
                    continue
                spec = tok.spec_name
                if kind == _RAISE:
                    self._event(
                        LEAK, line, spec,
                        f"{spec} acquired at line {tok.line} in "
                        f"{self.func} leaks when the call here raises "
                        f"(no settle/escape on the exception path)")
                else:
                    self._event(
                        LEAK, tok.line, spec,
                        f"{spec} acquired here is neither settled nor "
                        f"handed off on some path through {self.func}")
            for spec_name, line_ in st.pending_loss:
                spec = self.spec_by_name.get(spec_name)
                counters = sorted(spec.loss_counters) if spec else []
                self._event(
                    MISSING_DECLARED_LOSS, line_, spec_name,
                    f"lossy settle of {spec_name} in {self.func} but no "
                    f"loss counter ({', '.join(counters)}) is bumped on "
                    f"this path — the loss is silent, not declared")

    # -- statement walking -------------------------------------------------
    def _walk(self, stmts: List[ast.stmt],
              state: _State) -> List[Tuple[str, _State, int]]:
        cur: List[_State] = [state]
        done: List[Tuple[str, _State, int]] = []
        last_line = stmts[-1].lineno if stmts else 0
        for stmt in stmts:
            nxt: List[_State] = []
            for st in cur:
                for kind, s2, line in self._stmt(stmt, st):
                    if kind == _FALL:
                        nxt.append(s2)
                    else:
                        done.append((kind, s2, line))
            cur = nxt[:MAX_STATES]
            done = done[:MAX_STATES]
            if not cur:
                break
        done.extend((_FALL, s, last_line) for s in cur)
        return done[:MAX_STATES]

    def _stmt(self, stmt: ast.stmt,
              st: _State) -> List[Tuple[str, _State, int]]:
        if isinstance(stmt, ast.Expr):
            raise_line = self._may_raise_line(stmt)
            pre = st.clone() if raise_line is not None else None
            self._apply_owns(stmt, st, None)
            minted, refs = self._expr(stmt.value, st)
            for tok in minted:   # unbound acquire: anonymous live token
                st.tokens.append(tok)
            return self._forked(stmt, st, pre, raise_line)

        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            return self._assign(stmt, st)

        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                minted, refs = self._expr(stmt.value, st)
                for tok in minted:
                    tok.escaped = True
                    st.tokens.append(tok)
                self._escape_names(st, refs)
            return [(_RETURN, st, stmt.lineno)]

        if isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                minted, refs = self._expr(stmt.exc, st)
                for tok in minted:
                    tok.escaped = True
                    st.tokens.append(tok)
                self._escape_names(st, refs)
            return [(_RAISE, st, stmt.lineno)]

        if isinstance(stmt, ast.Break):
            return [(_BREAK, st, stmt.lineno)]
        if isinstance(stmt, ast.Continue):
            return [(_CONTINUE, st, stmt.lineno)]

        if isinstance(stmt, ast.If):
            return self._if(stmt, st)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, st)
        if isinstance(stmt, (ast.While,)):
            return self._while(stmt, st)
        if isinstance(stmt, ast.For):
            return self._for(stmt, st)
        if isinstance(stmt, ast.With):
            return self._with(stmt, st)

        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # nested def: any outer token named inside escapes (closure
            # takes ownership — e.g. completion callbacks)
            names = {n.id for n in ast.walk(stmt)
                     if isinstance(n, ast.Name)}
            self._escape_names(st, names)
            return [(_FALL, st, stmt.lineno)]

        if isinstance(stmt, (ast.Assert, ast.Delete, ast.Pass,
                             ast.Global, ast.Nonlocal, ast.Import,
                             ast.ImportFrom, ast.ClassDef)):
            return [(_FALL, st, stmt.lineno)]

        # anything else: process expressions conservatively
        for node in ast.iter_child_nodes(stmt):
            if isinstance(node, ast.expr):
                self._expr(node, st)
        return [(_FALL, st, stmt.lineno)]

    # -- assignments -------------------------------------------------------
    def _assign(self, stmt: ast.stmt,
                st: _State) -> List[Tuple[str, _State, int]]:
        raise_line = self._may_raise_line(stmt)
        pre = st.clone() if raise_line is not None else None
        value = getattr(stmt, "value", None)
        minted: List[_Token] = []
        refs: Set[str] = set()
        if value is not None:
            minted, refs = self._expr(value, st)
        self._apply_owns(stmt, st, stmt)
        if pre is not None:
            # the owns marker binds on the exception path too: the
            # obligation exists the moment the statement starts
            self._apply_owns(stmt, pre, stmt)

        targets = getattr(stmt, "targets", None) or \
            ([stmt.target] if getattr(stmt, "target", None) is not None
             else [])
        for tgt in targets:
            name = None
            if isinstance(tgt, ast.Name):
                name = tgt.id
            elif isinstance(tgt, (ast.Tuple, ast.List)) and tgt.elts and \
                    isinstance(tgt.elts[0], ast.Name):
                # conn, addr = srv.accept(): the token is the first elt
                name = tgt.elts[0].id
            if name is not None:
                for tok in minted:
                    tok.names.add(name)
                for tok in st.tokens:
                    if not tok.settled and tok.names & refs:
                        tok.names.add(name)
            elif isinstance(tgt, (ast.Attribute, ast.Subscript)):
                # storing into an attribute/container publishes the
                # value: ownership escapes to the object
                if isinstance(tgt, ast.Subscript):
                    self._note_setitem(tgt, st)
                for tok in minted:
                    tok.escaped = True
                self._escape_names(st, refs)
        for tok in minted:
            st.tokens.append(tok)
        return self._forked(stmt, st, pre, raise_line)

    def _note_setitem(self, tgt: ast.Subscript, st: _State) -> None:
        """``counters["x"] = v`` counts as producing/bumping x."""
        sl = tgt.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
            self._bump(st, sl.value)

    # -- control flow ------------------------------------------------------
    def _if(self, stmt: ast.If,
            st: _State) -> List[Tuple[str, _State, int]]:
        self._expr(stmt.test, st)
        name, none_branch = self._none_test(stmt.test)
        body_st, else_st = st.clone(), st
        if name is not None:
            killed = body_st if none_branch == "body" else else_st
            killed.tokens = [t for t in killed.tokens
                             if name not in t.names or t.settled]
        out = self._walk(list(stmt.body), body_st)
        out += self._walk(list(stmt.orelse), else_st)
        return out[:MAX_STATES]

    @staticmethod
    def _none_test(test: ast.expr) -> Tuple[Optional[str], str]:
        """Detect acquire-failure tests. Returns (token name, branch in
        which the token is absent) — ("t","body") for ``if t is None``,
        ("t","orelse") for ``if t:`` — or (None, "")."""
        if isinstance(test, ast.Compare) and len(test.ops) == 1 and \
                isinstance(test.comparators[0], ast.Constant) and \
                test.comparators[0].value is None:
            name = _root_name(test.left)
            if name:
                if isinstance(test.ops[0], ast.Is):
                    return name, "body"
                if isinstance(test.ops[0], ast.IsNot):
                    return name, "orelse"
        if isinstance(test, ast.UnaryOp) and \
                isinstance(test.op, ast.Not):
            name = _root_name(test.operand)
            if name:
                return name, "body"
        if isinstance(test, ast.Name):
            return test.id, "orelse"
        return None, ""

    def _try(self, stmt: ast.Try,
             st: _State) -> List[Tuple[str, _State, int]]:
        body_out = self._walk(list(stmt.body), st)
        pre_finally: List[Tuple[str, _State, int]] = []
        for kind, s, line in body_out:
            if kind == _RAISE and stmt.handlers:
                # every handler is assumed able to catch this exception
                for h in stmt.handlers:
                    pre_finally += self._walk(list(h.body), s.clone())
            elif kind == _FALL and stmt.orelse:
                pre_finally += self._walk(list(stmt.orelse), s)
            else:
                pre_finally.append((kind, s, line))
        pre_finally = pre_finally[:MAX_STATES]
        if not stmt.finalbody:
            return pre_finally
        out: List[Tuple[str, _State, int]] = []
        for kind, s, line in pre_finally:
            for fk, fs, fl in self._walk(list(stmt.finalbody), s):
                # a finally that falls through preserves the pending
                # outcome; one that returns/raises overrides it
                out.append((kind, fs, line) if fk == _FALL
                           else (fk, fs, fl))
        return out[:MAX_STATES]

    def _while(self, stmt: ast.While,
               st: _State) -> List[Tuple[str, _State, int]]:
        self._expr(stmt.test, st)
        infinite = (isinstance(stmt.test, ast.Constant)
                    and stmt.test.value is True)
        body_out = self._walk(list(stmt.body), st.clone())
        out: List[Tuple[str, _State, int]] = []
        after: List[_State] = []
        for kind, s, line in body_out:
            if kind in (_FALL, _CONTINUE):
                if not infinite:
                    after.append(s)   # loop condition turns false next
            elif kind == _BREAK:
                after.append(s)
            else:
                out.append((kind, s, line))
        if not infinite:
            after.append(st)          # zero-iteration path
        out += [(_FALL, s, stmt.lineno) for s in after]
        return out[:MAX_STATES]

    def _for(self, stmt: ast.For,
             st: _State) -> List[Tuple[str, _State, int]]:
        minted, refs = self._expr(stmt.iter, st)
        for tok in minted:
            st.tokens.append(tok)
        body_st = st.clone()
        if isinstance(stmt.target, ast.Name):
            # for b in cov: b is a view into the token's payload
            for tok in body_st.tokens:
                if not tok.settled and tok.names & refs:
                    tok.names.add(stmt.target.id)
        body_out = self._walk(list(stmt.body), body_st)
        out: List[Tuple[str, _State, int]] = []
        after: List[_State] = [st]    # zero-iteration path
        for kind, s, line in body_out:
            if kind in (_FALL, _CONTINUE, _BREAK):
                after.append(s)
            else:
                out.append((kind, s, line))
        out += [(_FALL, s, stmt.lineno) for s in after]
        return out[:MAX_STATES]

    def _with(self, stmt: ast.With,
              st: _State) -> List[Tuple[str, _State, int]]:
        for item in stmt.items:
            minted, refs = self._expr(item.context_expr, st)
            if item.optional_vars is not None and \
                    isinstance(item.optional_vars, ast.Name):
                for tok in minted:
                    tok.names.add(item.optional_vars.id)
            for tok in minted:
                st.tokens.append(tok)
        return self._walk(list(stmt.body), st)

    # -- expression effects ------------------------------------------------
    def _expr(self, node: ast.expr,
              st: _State) -> Tuple[List[_Token], Set[str]]:
        """Apply acquire/settle/escape effects of one expression.
        Returns (tokens minted at top level, surviving referenced
        names usable as alias sources)."""
        minted: List[_Token] = []
        refs: Set[str] = set()
        self._expr_into(node, st, minted, refs)
        return minted, refs

    def _expr_into(self, node: ast.expr, st: _State,
                   minted: List[_Token], refs: Set[str]) -> None:
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                refs.add(node.id)
            return
        if isinstance(node, (ast.Lambda,)):
            names = {n.id for n in ast.walk(node)
                     if isinstance(n, ast.Name)}
            self._escape_names(st, names)
            return
        if isinstance(node, (ast.Yield, ast.YieldFrom)):
            inner = getattr(node, "value", None)
            if inner is not None:
                m2, r2 = self._expr(inner, st)
                for tok in m2:
                    tok.escaped = True
                    st.tokens.append(tok)
                self._escape_names(st, r2)
            return
        if isinstance(node, ast.Call):
            self._call(node, st, minted, refs)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr_into(child, st, minted, refs)

    def _call(self, node: ast.Call, st: _State,
              minted: List[_Token], refs: Set[str]) -> None:
        name = node.func.attr if isinstance(node.func, ast.Attribute) \
            else (node.func.id if isinstance(node.func, ast.Name) else "")
        receiver = _receiver_of(node.func)
        if receiver is None:
            receiver = ""
        else:
            # visit the receiver chain root as a plain reference
            root = _root_name(node.func)
            if root and root not in ("self",):
                refs.add(root)

        spec, role = self._classify(name, receiver)

        arg_minted: List[_Token] = []
        arg_refs: Set[str] = set()
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            self._expr_into(arg, st, arg_minted, arg_refs)
        for tok in arg_minted:          # token used as an argument:
            tok.escaped = True          # ownership moves to the callee
            st.tokens.append(tok)

        # Counters production: .inc("x", ...) / .add(x=...)
        if name == "inc" and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            self._bump(st, node.args[0].value)
        elif name == "add":
            for kw in node.keywords:
                if kw.arg:
                    self._bump(st, kw.arg)

        if role == "acquire":
            self.model.acquire_sites += 1
            minted.append(_Token(spec.name, node.lineno))
            refs |= arg_refs            # acquire borrows its args
            return
        if role in ("settle", "loss"):
            self._settle(st, spec, arg_refs, node.lineno,
                         is_loss=(role == "loss"))
            return

        # settle invoked ON the token itself (``conn.close()``): the
        # receiver is the token, so the spec's receiver regex (which
        # names the POOL) can't match — match by token name instead
        root = _root_name(node.func) \
            if isinstance(node.func, ast.Attribute) else None
        if root:
            for tok in st.tokens:
                if root not in tok.names:
                    continue
                tspec = self.spec_by_name.get(tok.spec_name)
                if tspec is None:
                    continue
                if name in tspec.loss_settle_attrs:
                    self._settle(st, tspec, {root}, node.lineno,
                                 is_loss=True)
                    return
                if name in tspec.settle_attrs:
                    self._settle(st, tspec, {root}, node.lineno,
                                 is_loss=False)
                    return

        if name in TRUSTED_CALLS or receiver.split(".")[-1] in (
                "logger", "log"):
            refs |= arg_refs            # borrowing call
        else:
            self._escape_names(st, arg_refs)

    def _classify(self, name: str, receiver: str):
        """(spec, "acquire"|"settle"|"loss") for a matching call site,
        else (None, "")."""
        for spec in self.specs:
            if not spec.matches_receiver(receiver):
                continue
            if name in spec.acquire_attrs:
                return spec, "acquire"
            if name in spec.loss_settle_attrs:
                return spec, "loss"
            if name in spec.settle_attrs:
                return spec, "settle"
        return None, ""

    # -- settle / bump / escape / owns -------------------------------------
    def _settle(self, st: _State, spec: ResourceSpec,
                arg_names: Set[str], line: int, is_loss: bool) -> None:
        if is_loss and not (spec.loss_counters & st.bumped):
            # a lossy settle needs a declared-loss bump on this path
            # whether or not the token itself is tracked here (ring
            # evictions settle retention acquired elsewhere)
            st.pending_loss.append((spec.name, line))
        mine = [t for t in st.tokens if t.spec_name == spec.name]
        live = [t for t in mine if not t.settled]
        # a settle arg can alias several tokens at once (``allb = cov +
        # fresh; release(allb)``): one call settles them all
        matched = [t for t in live if t.names & arg_names]
        if matched:
            for t in matched:
                t.settled = True
            return
        for t in mine:
            if t.settled and t.names & arg_names:
                self._event(
                    DOUBLE_SETTLE, line, spec.name,
                    f"{spec.name} already settled on this path is "
                    f"settled again in {self.func} — one terminal "
                    f"event per token")
                return
        if arg_names and mine:
            # named settle of something we never tracked: a helper
            # settling a parameter it doesn't own — not ours to judge
            return
        anon = [t for t in live if not t.escaped] or live
        if anon:
            anon[0].settled = True      # unnamed settle: oldest live
        elif [t for t in mine if t.settled]:
            self._event(
                DOUBLE_SETTLE, line, spec.name,
                f"every {spec.name} token on this path is already "
                f"settled; this second settle in {self.func} "
                f"double-counts a terminal event")

    def _bump(self, st: _State, counter: str) -> None:
        st.bumped.add(counter)
        keep = []
        for spec_name, line in st.pending_loss:
            spec = self.spec_by_name.get(spec_name)
            if spec is not None and counter in spec.loss_counters:
                continue
            keep.append((spec_name, line))
        st.pending_loss = keep

    @staticmethod
    def _escape_names(st: _State, names: Set[str]) -> None:
        if not names:
            return
        for tok in st.tokens:
            if not tok.settled and tok.names & names:
                tok.escaped = True

    def _apply_owns(self, stmt: ast.stmt, st: _State,
                    assign: Optional[ast.stmt]) -> None:
        """``# flow: owns(resource)`` on a statement line mints an
        ownership obligation there (cross-function handoff, e.g. a
        completer thread popping an entry whose slot it must release)."""
        resource = self.owns.get(stmt.lineno)
        if resource is None or resource not in self.spec_by_name:
            return
        tok = _Token(resource, stmt.lineno)
        targets = getattr(assign, "targets", None) if assign else None
        if targets and isinstance(targets[0], ast.Name):
            tok.names.add(targets[0].id)
        st.tokens.append(tok)
        self.model.acquire_sites += 1

    # -- exception edges ---------------------------------------------------
    @staticmethod
    def _forked(stmt: ast.stmt, st: _State, pre: Optional[_State],
                raise_line: Optional[int]
                ) -> List[Tuple[str, _State, int]]:
        """Fall-through with the statement's effects applied, plus an
        exception edge carrying the PRE-statement state: ownership only
        transfers to a callee that actually completed, so a raising
        call leaves every token where it was."""
        out: List[Tuple[str, _State, int]] = [(_FALL, st, stmt.lineno)]
        if raise_line is not None and pre is not None:
            out.append((_RAISE, pre, raise_line))
        return out

    def _may_raise_line(self, stmt: ast.stmt) -> Optional[int]:
        """First call in the statement that isn't whitelisted as
        non-raising (registered acquires/settles, builtins, logging,
        sync primitives)."""
        for node in ast.walk(stmt):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue
            if not isinstance(node, ast.Call):
                continue
            name = node.func.attr if isinstance(node.func, ast.Attribute) \
                else (node.func.id
                      if isinstance(node.func, ast.Name) else "")
            receiver = _receiver_of(node.func) or ""
            spec, role = self._classify(name, receiver)
            if role:
                continue
            if name in TRUSTED_CALLS:
                continue
            if receiver.split(".")[-1] in ("logger", "log"):
                continue
            return node.lineno
        return None


# -- phase 1: registration -------------------------------------------------

@dataclass
class _FileFacts:
    label: str
    tree: ast.Module
    owns: Dict[int, str] = field(default_factory=dict)


def _collect_decorations(tree: ast.Module) -> List[Tuple[str, str, str]]:
    """(resource, method name, "acquire"|"settle"|"loss") for every
    ``@flow.acquires/@flow.settles`` decoration in the module."""
    regs: List[Tuple[str, str, str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            dname = dec.func.attr if isinstance(dec.func, ast.Attribute) \
                else (dec.func.id
                      if isinstance(dec.func, ast.Name) else "")
            if dname not in ("acquires", "settles"):
                continue
            if not (dec.args and isinstance(dec.args[0], ast.Constant)
                    and isinstance(dec.args[0].value, str)):
                continue
            resource = dec.args[0].value
            if dname == "acquires":
                regs.append((resource, node.name, "acquire"))
            else:
                kind = "ok"
                if len(dec.args) > 1 and \
                        isinstance(dec.args[1], ast.Constant):
                    kind = str(dec.args[1].value)
                for kw in dec.keywords:
                    if kw.arg == "kind" and \
                            isinstance(kw.value, ast.Constant):
                        kind = str(kw.value.value)
                regs.append((resource, node.name,
                             "loss" if kind == "loss" else "settle"))
    return regs


def _collect_productions(tree: ast.Module) -> Set[str]:
    """Counter names this module *produces*: ``.inc("x")``,
    ``.add(x=...)``, ``c["x"] = v``. ``update({...})`` and constructor
    kwargs are initialisation, not production."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = node.func.attr \
                if isinstance(node.func, ast.Attribute) else ""
            if name == "inc" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str):
                out.add(node.args[0].value)
            elif name == "add":
                for kw in node.keywords:
                    if kw.arg:
                        out.add(kw.arg)
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) and \
                        isinstance(tgt.slice, ast.Constant) and \
                        isinstance(tgt.slice.value, str):
                    out.add(tgt.slice.value)
    return out


def _collect_identities(tree: ast.Module, label: str) -> List[Identity]:
    out: List[Identity] = []
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "FLOW_IDENTITY" and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            ident = parse_identity_expr(node.value.value, label,
                                        node.lineno)
            if ident is not None:
                out.append(ident)
    return out


def _effective_specs(regs: List[Tuple[str, str, str]]
                     ) -> Tuple[ResourceSpec, ...]:
    """Union decorator-registered method names into the seeded specs;
    resource names the registry doesn't know become new any-receiver
    specs (the fixture-corpus mechanism)."""
    by_name = {s.name: s for s in SPECS}
    extra: Dict[str, Dict[str, Set[str]]] = {}
    for resource, meth, role in regs:
        slot = extra.setdefault(resource, {"acquire": set(),
                                           "settle": set(),
                                           "loss": set()})
        slot[role].add(meth)
    out: List[ResourceSpec] = []
    for spec in SPECS:
        e = extra.pop(spec.name, None)
        if e:
            spec = replace(
                spec,
                acquire_attrs=spec.acquire_attrs | frozenset(e["acquire"]),
                settle_attrs=spec.settle_attrs | frozenset(e["settle"]),
                loss_settle_attrs=(spec.loss_settle_attrs
                                   | frozenset(e["loss"])))
        out.append(spec)
    for resource, e in sorted(extra.items()):
        out.append(ResourceSpec(
            name=resource,
            acquire_attrs=frozenset(e["acquire"]),
            settle_attrs=frozenset(e["settle"]),
            loss_settle_attrs=frozenset(e["loss"]),
            loss_counters=DEFAULT_LOSS_COUNTERS,
            receiver_re=r".*",
            doc="declared via @flow.acquires/@flow.settles"))
    return tuple(out)


# -- phase 2 driver --------------------------------------------------------

def _scan_functions(model: FlowModel, facts: _FileFacts,
                    specs: Sequence[ResourceSpec]) -> None:
    def run(fn: ast.AST, qual: str) -> None:
        _FunctionAnalyzer(model, facts.label, qual, specs,
                          facts.owns).run(fn)

    for node in facts.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            run(node, node.name)
        elif isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    run(item, f"{node.name}.{item.name}")


def scan_paths(paths: Sequence[str]) -> FlowModel:
    """Parse every ``.py`` under the given files/directories and run
    both phases. Unparseable files are skipped."""
    model = FlowModel()
    files: List[Path] = []
    for p in paths:
        path = Path(p)
        if path.is_dir():
            files.extend(sorted(path.rglob("*.py")))
        elif path.suffix == ".py":
            files.append(path)

    parsed: List[_FileFacts] = []
    regs: List[Tuple[str, str, str]] = []
    seen: Set[Path] = set()
    for path in files:
        rp = path.resolve()
        if rp in seen:
            continue
        seen.add(rp)
        try:
            source = path.read_text(encoding="utf-8")
            tree = ast.parse(source)
        except (OSError, SyntaxError, UnicodeDecodeError):
            continue
        label = str(path)
        model.num_files += 1
        model.files.append(label)
        facts = _FileFacts(label=label, tree=tree)
        pragma_table: Dict[int, str] = {}
        for n, line in enumerate(source.splitlines(), 1):
            m = PRAGMA_RE.search(line)
            if m:
                pragma_table[n] = m.group(1).strip() or "unspecified"
            m = OWNS_RE.search(line)
            if m:
                facts.owns[n] = m.group(1).strip()
        if pragma_table:
            model.pragmas[label] = pragma_table
        regs += _collect_decorations(tree)
        model.productions[label] = _collect_productions(tree)
        model.module_identities += _collect_identities(tree, label)
        parsed.append(facts)

    model.specs = _effective_specs(regs)
    for facts in parsed:
        _scan_functions(model, facts, model.specs)
    return model
