"""The acquire/settle registry: what flowcheck knows to conserve.

Two kinds of declarations live here:

* :class:`ResourceSpec` — a paired acquire/settle protocol (window
  slots, KV blocks, sockets...). Call sites are matched by method name
  *and* a receiver regex (``self.window.acquire`` is a slot acquire;
  ``self._lock.acquire`` is not). ``@flow.acquires/@flow.settles``
  decorations found during the scan union extra method names into the
  matching spec, so new code self-registers without editing this file.

* :class:`Identity` — a module's declared conservation identity over
  its ``Counters`` (e.g. the serve identity
  ``requests == completed + shed_deadline + cancelled + shed_failed +
  pending``). The static pass proves every non-derived term is actually
  *produced* (``inc``/``add``) in its declaring file; the runtime
  validator (:mod:`.runtime`) asserts the arithmetic over live
  snapshots in the serve/chaos/router tests.

Fixture modules can declare their own identity with a module-level
string constant ``FLOW_IDENTITY = "lhs == a + b"`` — every name is then
required to be produced in that same module.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple


@dataclass(frozen=True)
class ResourceSpec:
    name: str
    # method names whose calls mint a token (one per call)
    acquire_attrs: frozenset
    # method names whose calls settle a token
    settle_attrs: frozenset
    # settle names that DISCARD the payload: the calling path must also
    # increment one of loss_counters, else missing-declared-loss
    loss_settle_attrs: frozenset = frozenset()
    loss_counters: frozenset = frozenset()
    # regex over the dotted receiver ("self.window", "pool") gating
    # which call sites belong to this spec
    receiver_re: str = r".*"
    doc: str = ""

    def matches_receiver(self, receiver: str) -> bool:
        return re.search(self.receiver_re, receiver) is not None


SPECS: Tuple[ResourceSpec, ...] = (
    ResourceSpec(
        name="window-slot",
        acquire_attrs=frozenset({"acquire"}),
        settle_attrs=frozenset({"release"}),
        receiver_re=r"(^|\.)_?window$",
        doc="InFlightWindow slot: acquire() at dispatch must reach "
            "release() on every completion path (including completer "
            "exceptions), or the window permanently loses depth."),
    ResourceSpec(
        name="kv-block",
        acquire_attrs=frozenset({"alloc", "lookup", "cow"}),
        settle_attrs=frozenset({"release", "free"}),
        receiver_re=r"(^|\.)_?(mgr|pool_mgr|kvpool|blockpool)$",
        doc="KVBlockPool blocks: alloc/lookup/cow take a reference "
            "that must be released, seated into a lane (escape), or "
            "given back on the admission error path."),
    ResourceSpec(
        name="socket",
        acquire_attrs=frozenset({"accept"}),
        settle_attrs=frozenset({"close", "sever_socket"}),
        receiver_re=r"(^|\.)_?(srv|server|sock|listener)$",
        doc="Accepted connections: every accept() must reach close()/"
            "sever_socket() or be handed to an owning reader thread."),
    ResourceSpec(
        name="ring-slot",
        acquire_attrs=frozenset(),
        settle_attrs=frozenset({"release"}),
        loss_settle_attrs=frozenset({"evict", "drop_frames"}),
        loss_counters=frozenset({"declared_lost", "session_declared_lost",
                                 "dropped", "shed", "frames_dropped"}),
        receiver_re=r"(^|\.)_?ring$",
        doc="ReplayRing retention: an eviction that discards frames is "
            "a DECLARED loss — the evicting path must increment a loss "
            "counter so `sent == delivered + declared_lost` can hold."),
)


@dataclass(frozen=True)
class IdentityTerm:
    name: str                      # key in a runtime snapshot
    counter: Optional[str] = None  # Counters key produced statically
    file: Optional[str] = None     # file suffix that must produce it
    # derived terms (counter None) are computed at snapshot time
    # (e.g. pending = batcher depth) and skipped by the static pass


@dataclass(frozen=True)
class Identity:
    name: str
    lhs: IdentityTerm
    rhs: Tuple[IdentityTerm, ...]
    doc: str = ""
    line: int = 1                  # pin for module-declared identities

    @property
    def expression(self) -> str:
        return (f"{self.lhs.name} == "
                + " + ".join(t.name for t in self.rhs))

    def terms(self) -> Tuple[IdentityTerm, ...]:
        return (self.lhs,) + tuple(self.rhs)


def _t(name: str, file: Optional[str] = None,
       counter: Optional[str] = None) -> IdentityTerm:
    return IdentityTerm(name=name, counter=(counter or name) if file
                        else None, file=file)


DECLARED_IDENTITIES: Tuple[Identity, ...] = (
    Identity(
        name="serve-settlement",
        lhs=_t("requests", "serve/batcher.py", "submitted"),
        rhs=(_t("completed", "serve/scheduler.py"),
             _t("shed_deadline", "serve/batcher.py"),
             _t("cancelled", "serve/batcher.py"),
             _t("shed_failed", "serve/scheduler.py"),
             _t("pending")),
        doc="Every admitted request settles exactly once: demuxed "
            "result, deadline shed, cancellation, invoke-failure shed, "
            "or still pending in the batcher."),
    Identity(
        name="roi-settlement",
        lhs=_t("serve_roi_requests", "serve/elements.py"),
        rhs=(_t("serve_roi_results", "serve/elements.py"),
             _t("serve_roi_shed", "serve/elements.py"),
             _t("serve_roi_pending")),
        doc="One RESULT xor one SHED answers every ROI-gated frame; "
            "a shed frame's sibling crops are cancelled, never "
            "half-stitched."),
    Identity(
        name="session-delivery",
        lhs=_t("session_sent", "elements/edge.py"),
        rhs=(_t("session_delivered", "elements/edge.py"),
             _t("session_declared_lost", "elements/edge.py")),
        doc="Zero-loss session accounting: every sent frame is either "
            "delivered (post-dedup) or explicitly declared lost at "
            "RESUME — never silently dropped."),
    Identity(
        name="router-settlement",
        lhs=_t("router_requests", "serve/router.py"),
        rhs=(_t("router_delivered", "serve/router.py"),
             _t("router_shed", "serve/router.py"),
             _t("router_orphaned", "serve/router.py")),
        doc="Fleet router conservation: every accepted request is "
            "delivered, shed with retry-after, or declared orphaned "
            "after replica death."),
    Identity(
        name="fleet-replica-lifecycle",
        lhs=_t("replicas_spawned", "fleet/autoscaler.py"),
        rhs=(_t("replicas_serving", "fleet/autoscaler.py"),
             _t("replicas_draining", "fleet/autoscaler.py"),
             _t("replicas_retired", "fleet/autoscaler.py"),
             _t("replicas_resurrecting", "fleet/autoscaler.py")),
        doc="Autoscaler conservation: every spawned replica is serving, "
            "draining toward preemption, retired (exited), or "
            "resurrecting from its snapshot — scale-down and chaos "
            "kills book through the same transitions."),
)


def identities_by_name() -> Dict[str, Identity]:
    return {i.name: i for i in DECLARED_IDENTITIES}


_IDENT_RE = re.compile(
    r"^\s*(\w+)\s*==\s*(\w+(?:\s*\+\s*\w+)*)\s*$")


def parse_identity_expr(expr: str, file: str,
                        line: int) -> Optional[Identity]:
    """Parse a fixture-declared ``FLOW_IDENTITY = "lhs == a + b"``
    string into an Identity whose every term must be produced in
    ``file``. Returns None when the string does not parse."""
    m = _IDENT_RE.match(expr)
    if not m:
        return None
    lhs = IdentityTerm(name=m.group(1), counter=m.group(1), file=file)
    rhs = tuple(IdentityTerm(name=t.strip(), counter=t.strip(), file=file)
                for t in m.group(2).split("+"))
    return Identity(name=f"{file}:{m.group(1)}", lhs=lhs, rhs=rhs,
                    doc="module-declared identity", line=line)
