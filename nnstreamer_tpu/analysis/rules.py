"""pipelint graph rules.

Each :class:`Rule` inspects the parsed-but-unstarted pipeline plus the
caps inference result and yields findings with element/pad locations.
Rules never execute elements and never raise past :func:`analyze` — a
broken rule must not block a launch.
"""
from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set

from ..pipeline.element import Element, SinkElement, SrcElement
from ..tensors.types import TensorFormat
from ..utils.log import logger
from .findings import Finding, Report, Severity
from .infer import InferenceResult, config_of, infer_caps


def kind_of(elem: Element) -> str:
    return getattr(type(elem), "ELEMENT_NAME", type(elem).__name__.lower())


@dataclass
class LintContext:
    pipeline: object
    inference: InferenceResult

    @property
    def elements(self) -> List[Element]:
        return list(self.pipeline.elements.values())

    def of_kind(self, *kinds: str) -> List[Element]:
        return [e for e in self.elements if kind_of(e) in kinds]

    def downstream(self, elem: Element) -> Iterable[Element]:
        for pad in elem.src_pads.values():
            if pad.peer is not None:
                yield pad.peer.element

    def upstream(self, elem: Element) -> Iterable[Element]:
        for pad in elem.sink_pads.values():
            if pad.peer is not None:
                yield pad.peer.element

    def sources_feeding(self, elem: Element) -> List[Element]:
        """Transitive upstream closure, returning the true sources."""
        seen: Set[str] = set()
        stack, out = [elem], []
        while stack:
            e = stack.pop()
            if e.name in seen:
                continue
            seen.add(e.name)
            ups = list(self.upstream(e))
            if not ups and e is not elem and not e.sink_pads:
                out.append(e)
            stack.extend(ups)
        return out


class Rule:
    """Base lint rule. ``id`` names the rule in findings; ``severity``
    is the default used by :meth:`finding`."""

    id = "rule"
    severity = Severity.WARNING

    def check(self, ctx: LintContext) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, message: str, element: Optional[str] = None,
                pad: Optional[str] = None,
                severity: Optional[Severity] = None) -> Finding:
        return Finding(self.id,
                       self.severity if severity is None else severity,
                       message, element, pad)


class DanglingPadRule(Rule):
    """Static sink pads that were never linked: the element will wait
    forever for data (crop's ``info`` pad, a combiner leg, ...).
    Completely isolated elements are flagged too."""

    id = "dangling-pad"
    severity = Severity.WARNING

    def check(self, ctx: LintContext):
        for e in ctx.elements:
            pads = list(e.sink_pads.values()) + list(e.src_pads.values())
            linked = [p for p in pads if p.is_linked]
            if pads and not linked:
                yield self.finding(
                    "element is not linked to anything", e.name)
                continue
            for pname, pad in e.sink_pads.items():
                if not pad.is_linked:
                    yield self.finding(
                        f"sink pad {pname!r} is never linked; the element "
                        f"waits on it forever", e.name, pname)


class CycleRule(Rule):
    """Cycles in the dataflow graph: buffers would chase their own tail
    and caps can never settle."""

    id = "cycle"
    severity = Severity.ERROR

    def check(self, ctx: LintContext):
        cyc = ctx.inference.cyclic
        if not cyc:
            return
        # restrict the blame to elements actually ON a cycle (Kahn also
        # strands everything downstream of one)
        by_name = {e.name: e for e in ctx.elements}
        on_cycle = sorted(n for n in cyc if self._reaches_self(
            by_name[n], by_name, cyc))
        for name in on_cycle:
            yield self.finding(
                f"element is part of a dataflow cycle "
                f"({' -> '.join(on_cycle)})", name)

    @staticmethod
    def _reaches_self(elem, by_name, cyc) -> bool:
        seen: Set[str] = set()
        stack = [p.peer.element for p in elem.src_pads.values()
                 if p.peer is not None]
        while stack:
            e = stack.pop()
            if e.name == elem.name:
                return True
            if e.name in seen or e.name not in cyc:
                continue
            seen.add(e.name)
            stack.extend(p.peer.element for p in e.src_pads.values()
                         if p.peer is not None)
        return False


class TeeNoQueueRule(Rule):
    """A tee branch that reaches a sink without a queue runs serialized
    with its sibling branches in one streaming thread — one slow/blocked
    branch stalls them all (deadlock-prone with combiners downstream)."""

    id = "tee-no-queue"
    severity = Severity.WARNING

    def check(self, ctx: LintContext):
        from ..pipeline.basic import Queue
        for tee in ctx.of_kind("tee"):
            branches = [(n, p) for n, p in tee.src_pads.items()
                        if p.peer is not None]
            if len(branches) < 2:
                continue
            for pname, pad in branches:
                if self._lacks_queue(pad.peer.element, Queue):
                    yield self.finding(
                        f"branch {pname!r} reaches a sink without a "
                        f"queue; branches share one streaming thread",
                        tee.name, pname)

    @staticmethod
    def _lacks_queue(start: Element, queue_cls) -> bool:
        seen: Set[str] = set()
        stack = [start]
        while stack:
            e = stack.pop()
            if e.name in seen or isinstance(e, queue_cls):
                continue
            seen.add(e.name)
            if isinstance(e, SinkElement):
                return True
            stack.extend(p.peer.element for p in e.src_pads.values()
                         if p.peer is not None)
        return False


class JitSignatureRule(Rule):
    """A tensor_filter fed by a dynamic-shape (flexible) upstream gets
    one XLA compile per distinct shape. Bucketed sources bound the
    signature count to len(buckets); anything else is unbounded."""

    id = "jit-signatures"
    severity = Severity.WARNING
    bucket_budget = 8

    def check(self, ctx: LintContext):
        for filt in ctx.of_kind("tensor_filter"):
            pad = filt.sink_pads.get("sink")
            if pad is None or pad.peer is None:
                continue
            caps = ctx.inference.pad_caps.get(pad.peer)
            cfg = config_of(caps)
            if cfg is None or cfg.format != TensorFormat.FLEXIBLE:
                continue  # static/unknown stream: nothing provable
            srcs = ctx.sources_feeding(filt)
            bounded = False
            for src in srcs:
                skind = kind_of(src)
                if skind == "tensor_serve_src":
                    buckets = [b for b in str(src.buckets).split(",") if b]
                    bounded = True
                    if len(buckets) > self.bucket_budget:
                        yield self.finding(
                            f"{len(buckets)} batch buckets exceed the "
                            f"jit-signature budget of {self.bucket_budget} "
                            f"(one compile each)", filt.name, "sink")
                elif skind == "tensor_query_serversrc" \
                        and int(getattr(src, "batch", 0)) > 0:
                    bounded = True  # padded micro-batches: fixed signature
            if not bounded:
                origin = ", ".join(sorted(kind_of(s) for s in srcs)) \
                    or "upstream"
                yield self.finding(
                    f"flexible-shape stream from {origin}: one jit "
                    f"compile per distinct shape (unbounded signature "
                    f"cardinality); bucket via tensor_serve_src or pin "
                    f"dims with a capsfilter", filt.name, "sink")


class ShardingRule(Rule):
    """tensor_filter custom=mesh:DxSxT shards the batch over D data-
    parallel devices; a batch not divisible by D fails at device_put."""

    id = "sharding-divisibility"
    severity = Severity.WARNING
    _MESH = re.compile(r"(?:^|,)mesh:(\d+)x(\d+)x(\d+)")

    def check(self, ctx: LintContext):
        from ..tensors.info import TensorsInfo
        for filt in ctx.of_kind("tensor_filter"):
            m = self._MESH.search(str(filt.custom))
            if not m:
                continue
            dp = int(m.group(1))
            if dp <= 1:
                continue
            pad = filt.sink_pads.get("sink")
            if pad is None or pad.peer is None:
                continue
            cfg = config_of(ctx.inference.pad_caps.get(pad.peer))
            if cfg is None or cfg.format != TensorFormat.STATIC \
                    or not len(cfg.info):
                continue
            stream = cfg.info[0]
            if filt.input and filt.inputtype:
                # declared model dims make the batch axis provable
                try:
                    model = TensorsInfo.make(filt.inputtype, filt.input)[0]
                except ValueError:
                    continue
                if len(stream.shape) != len(model.shape) + 1:
                    continue  # unbatched (or mismatched: caps rule's job)
                batch = int(stream.shape[0])
                if batch % dp:
                    yield self.finding(
                        f"batch {batch} is not divisible by the mesh's "
                        f"data-parallel axis {dp} (custom="
                        f"{filt.custom!r})", filt.name, "sink",
                        severity=Severity.ERROR)
            elif stream.shape and int(stream.shape[0]) % dp:
                yield self.finding(
                    f"leading dim {int(stream.shape[0])} is not divisible "
                    f"by the mesh's data-parallel axis {dp}; if it is the "
                    f"batch axis, device_put will fail", filt.name, "sink")


class ServeMeshRule(Rule):
    """Serve topology of the sharding rule: a bucketed
    ``tensor_serve_src`` stacks batches at its configured bucket sizes,
    so when the stream feeds a ``mesh:DxSxT`` filter every bucket must
    divide the data-parallel axis — one indivisible bucket means every
    batch that lands in it runs replicated (all rows on every chip)
    instead of sharded. The src's own ``mesh=`` property snaps buckets
    to dp multiples at start; the ERROR fires on the buckets as they
    would actually stack."""

    id = "serve-mesh-divisibility"
    severity = Severity.ERROR
    _MESH = re.compile(r"(?:^|,)mesh:(\d+)x(\d+)x(\d+)")

    @staticmethod
    def _effective_buckets(src) -> List[int]:
        try:
            buckets = [int(b) for b in str(src.buckets).split(",")
                       if b.strip()]
        except ValueError:
            return []
        spec = str(getattr(src, "mesh", "") or "")
        if spec:
            from ..parallel.mesh import spec_dims
            dims = spec_dims(spec)
            if dims is not None and dims[0] > 1:
                snap = dims[0]
                buckets = sorted({-(-b // snap) * snap
                                  for b in buckets if b > 0})
        return buckets

    def check(self, ctx: LintContext):
        for filt in ctx.of_kind("tensor_filter"):
            m = self._MESH.search(str(filt.custom))
            if not m:
                continue
            dp = int(m.group(1))
            if dp <= 1:
                continue
            for src in ctx.sources_feeding(filt):
                if kind_of(src) != "tensor_serve_src":
                    continue
                bad = [b for b in self._effective_buckets(src) if b % dp]
                if bad:
                    yield self.finding(
                        f"serve buckets {bad} do not divide the mesh's "
                        f"data-parallel axis {dp} (custom="
                        f"{filt.custom!r}); those batches run replicated "
                        f"on every chip — declare mesh= on {src.name!r} "
                        f"to snap buckets, or fix the bucket list",
                        filt.name, "sink")


class MeshColocationRule(Rule):
    """Train/serve colocation shares ONE device pool: a
    ``tensor_trainer mesh=X`` next to a serving path declaring
    ``mesh:Y`` (filter custom or serve src property) with X != Y builds
    two different Mesh objects over the same chips — params cannot stay
    mesh-resident across both, so each side's device_put evicts the
    other's layout. Declaring one spec makes them share the memoized
    mesh (parallel.mesh.shared_mesh)."""

    id = "mesh-colocation"
    severity = Severity.WARNING
    _MESH = re.compile(r"(?:^|,)mesh:([^,]+)")

    def check(self, ctx: LintContext):
        serve_specs = {}
        for filt in ctx.of_kind("tensor_filter"):
            m = self._MESH.search(str(filt.custom))
            if m and m.group(1).strip():
                serve_specs.setdefault(m.group(1).strip(), filt.name)
        for src in ctx.of_kind("tensor_serve_src"):
            spec = str(getattr(src, "mesh", "") or "").strip()
            if spec:
                serve_specs.setdefault(spec, src.name)
        if not serve_specs:
            return
        for tr in ctx.of_kind("tensor_trainer"):
            spec = str(getattr(tr, "mesh", "") or "").strip()
            if not spec:
                continue
            for other, where in sorted(serve_specs.items()):
                if other != spec:
                    yield self.finding(
                        f"trainer mesh={spec!r} but {where!r} declares "
                        f"mesh {other!r} on the same device pool: the "
                        f"two sides rebuild different meshes and evict "
                        f"each other's params; declare one spec so they "
                        f"share the mesh", tr.name)


class SinklessBranchRule(Rule):
    """Data flowing into an element whose src pads go nowhere is
    silently dropped; a pipeline with no sink at all never reaches
    EOS."""

    id = "sinkless-branch"
    severity = Severity.WARNING

    def check(self, ctx: LintContext):
        elems = ctx.elements
        if elems and not any(isinstance(e, SinkElement) for e in elems):
            yield self.finding(
                "pipeline has no sink element; wait_eos() would hang")
        for e in elems:
            if isinstance(e, SinkElement) or not e.src_pads:
                continue
            if any(p.is_linked for p in e.sink_pads.values()) \
                    and not any(p.is_linked for p in e.src_pads.values()):
                yield self.finding(
                    "branch dead-ends here: no src pad is linked, "
                    "buffers are dropped", e.name)


class CombinerDtypeRule(Rule):
    """tensor_merge np.concatenate's its legs — mismatched dtypes would
    silently upcast (or fail) at runtime; join forwards the first leg's
    caps, so a differing leg violates them mid-stream."""

    id = "combiner-dtype"
    severity = Severity.ERROR

    def check(self, ctx: LintContext):
        from ..elements.combiner import pad_sort_key
        for comb in ctx.of_kind("tensor_merge", "join"):
            kind = kind_of(comb)
            legs = []
            for pname in sorted(comb.sink_pads, key=pad_sort_key):
                pad = comb.sink_pads[pname]
                if pad.peer is None:
                    continue
                cfg = config_of(ctx.inference.pad_caps.get(pad.peer))
                if cfg is not None and len(cfg.info):
                    legs.append((pname, cfg))
            if len(legs) < 2:
                continue
            ref_name, ref = legs[0]
            for pname, cfg in legs[1:]:
                dtypes = [i.type for i in cfg.info]
                ref_dtypes = [i.type for i in ref.info]
                if dtypes != ref_dtypes:
                    yield self.finding(
                        f"dtype {[str(t) for t in dtypes]} differs from "
                        f"{ref_name!r}'s {[str(t) for t in ref_dtypes]}; "
                        f"{kind} would silently widen or corrupt",
                        comb.name, pname)
                elif kind == "join" and not cfg.info.is_equal(ref.info):
                    yield self.finding(
                        f"shape differs from {ref_name!r} "
                        f"({cfg.info!r} vs {ref.info!r}); join forwards "
                        f"one caps for all legs", comb.name, pname)


class UnboundedAdmissionRule(Rule):
    """Serving entry points must bound admission: an unbounded queue
    turns an overloaded server into a memory leak with unbounded tail
    latency instead of shedding load."""

    id = "unbounded-admission"
    severity = Severity.WARNING

    def check(self, ctx: LintContext):
        for e in ctx.of_kind("tensor_serve_src"):
            if int(e.max_queue) <= 0:
                yield self.finding(
                    f"max-queue={int(e.max_queue)} disables admission "
                    f"control (clamped to 1 silently); set a real bound",
                    e.name)
            if float(e.deadline_ms) < 0:
                yield self.finding(
                    "negative deadline-ms sheds every request", e.name)
        for e in ctx.of_kind("tensor_query_serversrc"):
            yield self.finding(
                "per-request path has no admission control or shedding; "
                "production traffic belongs on tensor_serve_src",
                e.name, severity=Severity.INFO)


class ShedNoRetryAfterRule(Rule):
    """A SHED reply without a positive retry-after hint gives clients
    nothing to pace themselves by: they hot-loop resubmitting into the
    very overload that shed them, or back off blind. Every element that
    mints SHEDs must carry a usable hint — backpressure is part of the
    settlement contract (RESULT xor SHED-with-retry-after)."""

    id = "shed-no-retry-after"
    severity = Severity.WARNING

    def check(self, ctx: LintContext):
        for e in ctx.of_kind("tensor_serve_src", "tensor_serve_router"):
            if float(getattr(e, "retry_after_ms", 0.0)) <= 0:
                yield self.finding(
                    f"retry-after-ms={float(e.retry_after_ms):g} on a "
                    "shedding entry point: SHED replies carry no "
                    "backpressure hint, so shed clients resubmit "
                    "immediately into the same overload", e.name)
        for e in ctx.of_kind("tensor_filter"):
            if int(getattr(e, "breaker_threshold", 0)) > 0 and \
                    float(getattr(e, "breaker_retry_after_ms", 0.0)) <= 0:
                yield self.finding(
                    "breaker-retry-after-ms<=0 with the circuit breaker "
                    "armed: breaker-open sheds pace nothing upstream",
                    e.name)


class LinkResilienceRule(Rule):
    """Network-edge elements with no timeout or with reconnection
    disabled turn a transient peer outage into a permanent hang or a
    silent EOS."""

    id = "link-resilience"
    severity = Severity.WARNING

    def check(self, ctx: LintContext):
        for e in ctx.of_kind("tensor_query_client", "edgesrc", "mqttsrc"):
            if float(getattr(e, "timeout", 0.0)) <= 0:
                yield self.finding(
                    "timeout<=0 on a network element: a dead peer hangs "
                    "the stream forever", e.name)
            if kind_of(e) in ("edgesrc", "mqttsrc") \
                    and not bool(getattr(e, "reconnect", True)):
                yield self.finding(
                    "reconnect=false: a dropped link ends the stream as "
                    "EOS instead of re-dialing with backoff", e.name,
                    severity=Severity.INFO)


class ErrorPolicyRule(Rule):
    """on-error specs are parsed lazily at the first fault — a typo'd
    spec or an impossible policy (restart of a stateful element) must
    surface at lint time, not mid-incident."""

    id = "error-policy"
    severity = Severity.WARNING

    def check(self, ctx: LintContext):
        from ..fault.policy import ErrorPolicy
        for e in ctx.elements:
            spec = str(getattr(e, "on_error", "fail"))
            try:
                policy = ErrorPolicy.parse(spec)
            except ValueError as exc:
                yield self.finding(
                    f"unparseable on-error spec {spec!r}: {exc}",
                    e.name, severity=Severity.ERROR)
                continue
            if policy.action == "retry" and isinstance(e, SinkElement):
                yield self.finding(
                    "on-error=retry on a sink re-runs side effects "
                    "(duplicate renders/publishes); prefer skip or fail",
                    e.name)
            elif policy.action == "restart" \
                    and not getattr(type(e), "RESTART_SAFE", False):
                yield self.finding(
                    f"on-error=restart on {kind_of(e)}: element is not "
                    f"restart-safe (a restart discards internal state)",
                    e.name, severity=Severity.ERROR)


class WireConfigRule(Rule):
    """Wire-v2 link properties are negotiated strings: a typo'd codec
    silently degrades to raw (the peer clamps it), so it must surface at
    lint time; and a lossy on-wire downcast feeding a trainer corrupts
    gradients silently — the operator must opt in knowingly."""

    id = "wire-config"
    severity = Severity.ERROR

    def check(self, ctx: LintContext):
        from ..edge.wire import CODECS, PRECISIONS
        for e in ctx.of_kind("tensor_query_client", "edgesink"):
            codec = str(getattr(e, "wire_codec", "raw"))
            if codec not in CODECS:
                yield self.finding(
                    f"invalid wire-codec {codec!r}; valid: "
                    f"{', '.join(CODECS)}", e.name)
            precision = str(getattr(e, "wire_precision", "none"))
            if precision not in PRECISIONS:
                yield self.finding(
                    f"invalid wire-precision {precision!r}; valid: "
                    f"{', '.join(PRECISIONS)}", e.name)
            elif precision != "none" and kind_of(e) == "tensor_query_client":
                # lossy downcast + a trainer consuming the results =
                # silently degraded gradients; warn loudly
                seen: Set[str] = set()
                stack = list(ctx.downstream(e))
                while stack:
                    d = stack.pop()
                    if d.name in seen:
                        continue
                    seen.add(d.name)
                    if kind_of(d) == "tensor_trainer":
                        yield self.finding(
                            f"wire-precision={precision} is lossy and the "
                            f"results feed trainer '{d.name}': gradients "
                            f"see fp32-rounded activations",
                            e.name, severity=Severity.WARNING)
                        break
                    stack.extend(ctx.downstream(d))
        for e in ctx.of_kind("edgesink"):
            frames = int(getattr(e, "coalesce_frames", 1))
            if frames < 1:
                yield self.finding(
                    f"coalesce-frames={frames} is not a batch size; "
                    f"use 1 to disable coalescing", e.name)
            elif frames > 1 and float(getattr(e, "coalesce_ms", 0.0)) <= 0:
                yield self.finding(
                    "coalesce-frames>1 with coalesce-ms<=0: a partial "
                    "batch below the size threshold stalls until more "
                    "frames arrive (no age flush)", e.name,
                    severity=Severity.WARNING)


class FusionBreakRule(Rule):
    """A single non-fusible element sandwiched between two device-fusible
    neighbors splits what would otherwise be one FusedSegment into two
    (or none) — every split re-crosses the host/device boundary, which on
    a remote-attached TPU costs a full RTT per frame."""

    id = "fusion-break"
    severity = Severity.WARNING

    def check(self, ctx: LintContext):
        from ..fusion.planner import static_veto
        for e in ctx.elements:
            if isinstance(e, (SrcElement, SinkElement)):
                continue  # runs necessarily end at the graph edge
            reason = static_veto(e, ctx.inference)
            if reason is None:
                continue
            ups = [p.peer.element for p in e.sink_pads.values()
                   if p.peer is not None]
            downs = [p.peer.element for p in e.src_pads.values()
                     if p.peer is not None]
            if len(ups) != 1 or len(downs) != 1:
                continue
            up, down = ups[0], downs[0]
            if static_veto(up, ctx.inference) is not None \
                    or static_veto(down, ctx.inference) is not None:
                continue
            yield self.finding(
                f"breaks a device-fusible run between '{up.name}' and "
                f"'{down.name}' ({reason}); move it outside the run, or "
                f"accept per-element dispatch with fuse=false", e.name)


class FusionTransferRule(Rule):
    """An element that declares a device_fn promises the fusion planner
    that its *static* caps transfer matches what the chain path
    negotiates at runtime (``transform_caps``). If they disagree, a
    fused segment advertises caps the unfused pipeline never produces —
    a guaranteed parity break, so this is an error."""

    id = "fusion-transfer"
    severity = Severity.ERROR

    def check(self, ctx: LintContext):
        from .infer import element_transfer
        for e in ctx.elements:
            if type(e).device_fn is Element.device_fn:
                continue  # no device_fn declared: nothing promised
            if type(e).transform_caps is Element.transform_caps:
                continue  # runtime path negotiates elsewhere; not comparable
            in_caps = ctx.inference.in_caps(e)
            known = {p: c for p, c in in_caps.items()
                     if c is not None and c.is_fixed()}
            if len(known) != 1:
                continue  # gradual typing: only fire on fully-known caps
            incaps = next(iter(known.values()))
            try:
                runtime = e.transform_caps(incaps)
            except Exception:  # noqa: BLE001 -- transfer rule, not crash rule
                continue
            declared = element_transfer(e, in_caps)
            for pname, dcaps in declared.items():
                if dcaps is None or runtime is None:
                    continue
                if dcaps != runtime:
                    yield self.finding(
                        f"device_fn is declared but static transfer "
                        f"({dcaps}) disagrees with the chain path's "
                        f"transform_caps ({runtime}); a fused segment "
                        f"would break byte parity", e.name, pname)


class SessionReplayBudgetRule(Rule):
    """An edgesink replay ring smaller than ONE coalesced batch cannot
    replay even the minimal unit of loss: the very first reconnect gap
    is guaranteed to contain declared-lost frames. That configuration
    can never deliver the zero-loss promise session=true makes, so it
    is an error, not a tuning warning."""

    id = "session-replay-budget"
    severity = Severity.ERROR

    def check(self, ctx: LintContext):
        import numpy as np
        for e in ctx.of_kind("edgesink"):
            if not bool(getattr(e, "session", False)):
                continue
            ring_bytes = int(getattr(e, "session_ring_kb", 0)) * 1024
            frames = max(1, int(getattr(e, "coalesce_frames", 1)))
            pad = e.sink_pads.get("sink")
            if pad is None or pad.peer is None:
                continue
            cfg = config_of(ctx.inference.pad_caps.get(pad.peer))
            if cfg is None or cfg.format != TensorFormat.STATIC \
                    or not len(cfg.info):
                continue  # gradual typing: only fire on provable frames
            try:
                frame_bytes = sum(
                    int(np.prod(i.shape)) * np.dtype(i.type.np_dtype).itemsize
                    for i in cfg.info)
            except (TypeError, ValueError):
                continue
            batch_bytes = frames * frame_bytes
            if frame_bytes > 0 and ring_bytes < batch_bytes:
                yield self.finding(
                    f"session replay ring ({ring_bytes} B) is smaller than "
                    f"one coalesced batch ({frames} frame(s) x "
                    f"{frame_bytes} B = {batch_bytes} B): the first "
                    f"reconnect gap is GUARANTEED to declare lost frames; "
                    f"raise session-ring-kb or lower coalesce-frames",
                    e.name, "sink")


class SessionNoReconnectRule(Rule):
    """session=true buys replay-on-RESUME — but RESUME only happens on a
    re-dial. With reconnect=false a dropped link just ends the stream as
    EOS and the session's replay ring never gets asked, so the operator
    is paying for acks with no delivery guarantee in return."""

    id = "session-no-reconnect"
    severity = Severity.WARNING

    def check(self, ctx: LintContext):
        for e in ctx.of_kind("edgesrc"):
            if bool(getattr(e, "session", False)) \
                    and not bool(getattr(e, "reconnect", True)):
                yield self.finding(
                    "session=true with reconnect=false: a dropped link "
                    "ends the stream before any RESUME can replay the "
                    "gap — the session guarantees nothing; enable "
                    "reconnect or drop the session overhead", e.name)


class RouterNoReplicasRule(Rule):
    """A fleet router with neither a static replica list nor a broker
    topic can never route anything: every request it accepts sheds.
    That is a dead configuration, not a tuning choice — an error before
    launch."""

    id = "router-no-replicas"
    severity = Severity.ERROR

    def check(self, ctx: LintContext):
        for e in ctx.of_kind("tensor_serve_router"):
            replicas = str(getattr(e, "replicas", "") or "").strip()
            topic = str(getattr(e, "topic", "") or "").strip()
            if not replicas and not topic:
                yield self.finding(
                    "router has zero replica endpoints and no broker "
                    "topic: every request will be shed; set replicas= "
                    "(host:port,...) or topic= + dest-port= for broker "
                    "discovery", e.name)


class RouterAffinitySessionlessRule(Rule):
    """affinity=true keys dispatch on per-client session identity — but
    session=false disables minting those keys, so every frame silently
    degrades to least-loaded placement and the operator's affinity
    expectation (stream order, warm per-replica state) is not actually
    being honored."""

    id = "router-affinity-sessionless"
    severity = Severity.WARNING

    def check(self, ctx: LintContext):
        for e in ctx.of_kind("tensor_serve_router"):
            if bool(getattr(e, "affinity", True)) \
                    and not bool(getattr(e, "session", True)):
                yield self.finding(
                    "affinity=true with session=false: no session keys "
                    "are minted, so dispatch silently degrades to "
                    "least-loaded and sessions do NOT stick to a "
                    "replica; enable session or set affinity=false",
                    e.name)


class AsyncWindowRule(Rule):
    """In-flight window sanity for tensor_filter's overlapped executor.

    ERROR on ``in-flight < 1`` (a zero/negative window can never admit
    a frame: the dispatcher blocks forever on the first buffer) and on
    a window wider than the serve batcher's jit-signature budget when
    fed by a bucketed tensor_serve_src — up to K distinct bucket
    signatures can then be in flight at once, each holding a compiled
    executable, which blows the same budget JitSignatureRule enforces
    for compiles. WARN when ``in-flight > 1`` feeds an order-sensitive
    element (aggregator stacking windows, trainer consuming a sample
    stream, rate pacing on PTS) with the reorder buffer disabled —
    completions may then overtake each other on error gaps and the
    downstream element silently mis-groups frames.
    """

    id = "async-window"
    severity = Severity.ERROR
    _ORDER_SENSITIVE = ("tensor_aggregator", "tensor_trainer",
                        "tensor_rate")

    def check(self, ctx: LintContext):
        budget = JitSignatureRule.bucket_budget
        for filt in ctx.of_kind("tensor_filter"):
            try:
                k = int(getattr(filt, "in_flight", 1))
            except (TypeError, ValueError):
                yield self.finding(
                    f"in-flight={getattr(filt, 'in_flight', None)!r} is "
                    f"not an integer", filt.name)
                continue
            if k < 1:
                yield self.finding(
                    f"in-flight={k}: the window can never admit a frame "
                    f"(dispatch blocks forever); use 1 for synchronous "
                    f"operation", filt.name)
                continue
            if k > budget and any(
                    kind_of(s) == "tensor_serve_src"
                    and len([b for b in str(s.buckets).split(",") if b]) > 1
                    for s in ctx.sources_feeding(filt)):
                yield self.finding(
                    f"in-flight={k} behind a bucketed tensor_serve_src: "
                    f"up to {k} distinct bucket signatures can be in "
                    f"flight at once, exceeding the jit-signature budget "
                    f"of {budget} live executables; shrink the window or "
                    f"the bucket list", filt.name)
            if k > 1 and not bool(getattr(filt, "reorder", True)):
                hit = self._order_sensitive_downstream(ctx, filt)
                if hit is not None:
                    yield self.finding(
                        f"in-flight={k} with reorder=false feeds "
                        f"order-sensitive {kind_of(hit)} '{hit.name}': "
                        f"completions can arrive out of PTS order; "
                        f"enable reorder or set in-flight=1",
                        filt.name, severity=Severity.WARNING)

    def _order_sensitive_downstream(self, ctx: LintContext, elem):
        seen, stack = set(), list(ctx.downstream(elem))
        while stack:
            e = stack.pop()
            if e.name in seen:
                continue
            seen.add(e.name)
            if kind_of(e) in self._ORDER_SENSITIVE:
                return e
            stack.extend(ctx.downstream(e))
        return None


class StatefulNoCheckpointRule(Rule):
    """An element that declares itself NOT restart-safe carries state a
    plain stop/start loses — exactly the state a preemption
    (``Pipeline.preempt``/SIGTERM) needs to snapshot. If it also does
    not implement ``snapshot_state``, a preempted pipeline silently
    discards that state on restore: frames, windows, or training
    progress vanish without a declaration. WARN, not ERROR — the
    pipeline still runs, it just cannot survive preemption whole."""

    id = "stateful-no-checkpoint"
    severity = Severity.WARNING

    def check(self, ctx: LintContext):
        from ..pipeline.element import Element as _Base
        for e in ctx.elements:
            cls = type(e)
            # only elements that EXPLICITLY declare RESTART_SAFE=False
            # on their own class (inherited defaults are the base
            # contract, not a statement about this element's state)
            if "RESTART_SAFE" not in cls.__dict__ \
                    or cls.RESTART_SAFE is not False:
                continue
            if cls.snapshot_state is _Base.snapshot_state:
                yield self.finding(
                    f"{kind_of(e)} declares RESTART_SAFE=False but "
                    f"implements no snapshot_state(): its state is "
                    f"silently lost across preempt/restore; implement "
                    f"the Checkpointable hooks or declare why the state "
                    f"is disposable", e.name)


class TraceExportRule(Rule):
    """A source with ``trace-export=true`` promises frame-level trace
    continuity — but the trace context rides in buffer extras, and an
    element that mints fresh output buffers (``STRIPS_META``) drops it.
    Downstream spans then fall back to same-thread inheritance (fine
    inside one streaming thread) and the WIRE loses the context
    entirely: the remote half of the span tree detaches. WARN naming
    the first stripping element on each path."""

    id = "trace-export-stripped"
    severity = Severity.WARNING

    def check(self, ctx: LintContext):
        for src in ctx.elements:
            if not isinstance(src, SrcElement) \
                    or not bool(getattr(src, "trace_export", False)):
                continue
            seen: Set[str] = set()
            stack = list(ctx.downstream(src))
            while stack:
                e = stack.pop()
                if e.name in seen:
                    continue
                seen.add(e.name)
                if getattr(type(e), "STRIPS_META", False):
                    yield self.finding(
                        f"source '{src.name}' exports trace context but "
                        f"{kind_of(e)} '{e.name}' mints fresh buffers "
                        f"(STRIPS_META): frame spans past it lose their "
                        f"trace ids on wire hops; move the element "
                        f"upstream of the source stamp or accept "
                        f"same-thread-only spans", e.name)
                    continue  # report the FIRST stripper per path
                stack.extend(ctx.downstream(e))


class LlmDecodeNoKvBudgetRule(Rule):
    """A decode-role (or explicitly paged) llm filter without an
    explicit ``pool_blocks`` budget sizes its KV pool from
    n_parallel x max_len — the contiguous worst case. That defeats the
    point of paging on a decode replica: admission is supposed to be
    token-budgeted against a deliberately smaller arena (plus prefix
    cache headroom), and the implicit default silently reserves lane
    memory as if paging were off."""

    id = "llm-decode-no-kv-budget"
    severity = Severity.ERROR

    def check(self, ctx: LintContext):
        from ..filters.base import parse_custom_properties
        for filt in ctx.of_kind("tensor_filter"):
            opts = parse_custom_properties(str(filt.custom or ""))
            paged = (opts.get("role") == "decode"
                     or opts.get("paged", "").lower()
                     in ("1", "true", "yes", "on"))
            if not paged or "pool_blocks" in opts:
                continue
            # a decode-role serve replica makes the omission fatal in
            # practice (every stream of the fleet lands here); flag the
            # filter either way
            yield self.finding(
                "paged llm decode without custom=pool_blocks:N — the "
                "KV pool silently defaults to the contiguous worst "
                "case (n_parallel x max_len tokens), so decode "
                "occupancy is not actually token-budgeted; size the "
                "pool explicitly", filt.name, "sink")


class LlmPrefixCacheLossyLinkRule(Rule):
    """fp16 KV handoff feeding a content-addressed prefix cache: the
    chain digest says 'same tokens, same KV' but the shipped blocks
    were rounded through float16 (bf16 KV loses mantissa width, the
    f32 logits lose range), so cached blocks differ bitwise from what
    a local prefill would compute — hits stop being exact."""

    id = "llm-prefix-cache-lossy-link"
    severity = Severity.WARNING

    def check(self, ctx: LintContext):
        from ..filters.base import parse_custom_properties
        for filt in ctx.of_kind("tensor_filter"):
            opts = parse_custom_properties(str(filt.custom or ""))
            if opts.get("kv_precision") != "fp16":
                continue
            ships = "handoff" in opts or opts.get("role") in ("prefill",
                                                              "decode")
            caches = opts.get("prefix_cache", "true").lower() \
                not in ("0", "false", "no")
            if ships and caches:
                yield self.finding(
                    "kv_precision:fp16 on a prefix-caching llm link: "
                    "shipped KV blocks are float16-rounded, so the "
                    "content-addressed cache serves blocks that no "
                    "longer match a local prefill bit-for-bit; use "
                    "kv_precision:bf16 (byte-exact for bf16 KV) or "
                    "disable prefix_cache on this replica",
                    filt.name, "sink")


class DeltaNoKeyframeIntervalRule(Rule):
    """Delta wire codec with no finite keyframe interval: the link's
    only scheduled resynchronization points are gone. A subscriber that
    joins late, or whose reference drifts for any unforeseen reason,
    then has no bounded-time path back to a self-contained frame — the
    stream degrades into diffs against state only the sender has."""

    id = "delta-no-keyframe-interval"
    severity = Severity.ERROR

    def check(self, ctx: LintContext):
        from ..edge.wire import CODEC_DELTA
        for e in ctx.of_kind("edgesink"):
            if str(getattr(e, "wire_codec", "raw")) != CODEC_DELTA:
                continue
            k = int(getattr(e, "wire_delta_k", 0))
            if k <= 0:
                yield self.finding(
                    f"wire-codec=delta with wire-delta-k={k}: no finite "
                    "keyframe interval — only connect/layout-change/"
                    "promotion keyframes remain, so a reference that "
                    "drifts has no bounded-time resync; set "
                    "wire-delta-k to a positive frame count", e.name)


class DeltaLossyGateFeedsTrainerRule(Rule):
    """tensor_delta's gate/roi modes drop unchanged frames and tiles —
    exactly right for inference, silently wrong for training: the
    dropped samples are the (heavily static) majority class, so a
    trainer downstream learns from a motion-biased subsample without
    anyone opting in."""

    id = "delta-lossy-gate-feeds-trainer"
    severity = Severity.WARNING

    def check(self, ctx: LintContext):
        for e in ctx.of_kind("tensor_delta"):
            mode = str(getattr(e, "mode", "gate"))
            if mode not in ("gate", "roi"):
                continue  # mask mode annotates only; nothing is dropped
            seen: Set[str] = set()
            stack = list(ctx.downstream(e))
            while stack:
                d = stack.pop()
                if d.name in seen:
                    continue
                seen.add(d.name)
                if kind_of(d) == "tensor_trainer":
                    yield self.finding(
                        f"tensor_delta mode={mode} drops unchanged "
                        f"frames/tiles and the survivors feed trainer "
                        f"'{d.name}': the training distribution is "
                        "motion-biased; train from a mask-mode tap or "
                        "the ungated stream", e.name)
                    break
                stack.extend(ctx.downstream(d))


class AutoscalerConfigRule(Rule):
    """Autoscaler control-law sanity. ERROR on a bound inversion
    (``min-replicas > max-replicas``: the floor-repair and scale-up
    paths fight forever) and on a non-positive drain deadline (every
    scale-down then skips the drain wait and preempts replicas with
    requests still in flight — scale-down stops being zero-loss). WARN
    when the autoscaler has neither a router element nor a metrics URL:
    ``observe()`` always reads 0, so it can only ever hold the floor
    and the elastic behavior the element exists for is silently off."""

    id = "autoscaler-config"
    severity = Severity.ERROR

    def check(self, ctx: LintContext):
        for e in ctx.of_kind("tensor_autoscaler"):
            lo = int(getattr(e, "min_replicas", 1))
            hi = int(getattr(e, "max_replicas", 4))
            if lo > hi:
                yield self.finding(
                    f"min-replicas={lo} > max-replicas={hi}: the floor "
                    "repair wants more replicas than scale-up may ever "
                    "grant — the fleet thrashes at the cap and never "
                    "reaches the declared minimum", e.name)
            dd = float(getattr(e, "drain_deadline_ms", 2000.0))
            if dd <= 0:
                yield self.finding(
                    f"drain-deadline-ms={dd:g}: scale-down preempts "
                    "without waiting for in-flight requests to settle, "
                    "so every scale-down orphans live work; set a "
                    "positive drain deadline", e.name)
            if not str(getattr(e, "metrics_url", "") or "").strip() \
                    and not str(getattr(e, "router", "") or "").strip():
                yield self.finding(
                    "no metrics source: neither router= nor "
                    "metrics-url= is set, so observed queue delay is "
                    "always 0 and the autoscaler only ever holds "
                    "min-replicas", e.name, severity=Severity.WARNING)


ALL_RULES: List[Rule] = [
    DanglingPadRule(), CycleRule(), TeeNoQueueRule(), JitSignatureRule(),
    ShardingRule(), ServeMeshRule(), MeshColocationRule(),
    SinklessBranchRule(), CombinerDtypeRule(),
    UnboundedAdmissionRule(), ShedNoRetryAfterRule(),
    LinkResilienceRule(), ErrorPolicyRule(),
    WireConfigRule(), FusionBreakRule(), FusionTransferRule(),
    SessionReplayBudgetRule(), SessionNoReconnectRule(),
    RouterNoReplicasRule(), RouterAffinitySessionlessRule(),
    AsyncWindowRule(), StatefulNoCheckpointRule(), TraceExportRule(),
    LlmDecodeNoKvBudgetRule(), LlmPrefixCacheLossyLinkRule(),
    DeltaNoKeyframeIntervalRule(), DeltaLossyGateFeedsTrainerRule(),
    AutoscalerConfigRule(),
]


def analyze(pipeline, rules: Optional[List[Rule]] = None) -> Report:
    """Run caps inference + every rule over ``pipeline``; returns the
    aggregated :class:`Report`. Never starts an element."""
    inference = infer_caps(pipeline)
    report = Report(findings=list(inference.findings),
                    num_elements=len(pipeline.elements))
    ctx = LintContext(pipeline, inference)
    for rule in (ALL_RULES if rules is None else rules):
        try:
            report.findings.extend(rule.check(ctx))
        except Exception:  # noqa: BLE001 -- a broken rule must not block launch
            logger.warning("pipelint: rule %s crashed; skipped",
                           rule.id, exc_info=True)
    return report
