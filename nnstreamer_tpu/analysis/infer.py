"""Static caps/shape/dtype inference over a parsed, unstarted pipeline.

Walks the dataflow graph in topological order and propagates ``Caps``
through each element's declared :meth:`Element.static_transfer`. Typing
is gradual: an unknown (None) flows silently through downstream
elements, so only *provable* contradictions become findings — exactly
the failures runtime negotiation would hit mid-stream, reported here
with the element and pad before anything starts.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from ..pipeline.element import Element, TransferError
from ..pipeline.pad import Pad
from ..tensors.caps import Caps
from ..tensors.info import TensorsConfig
from ..utils.log import logger
from .findings import Finding, Severity

RULE_CAPS = "caps-inference"


def config_of(caps: Optional[Caps]) -> Optional[TensorsConfig]:
    """Tensor config of known, fixed other/tensors caps; else None."""
    if caps is None or caps.any or not caps.structures:
        return None
    try:
        return caps.to_config() if caps.is_fixed() else None
    except ValueError:
        return None


@dataclass
class InferenceResult:
    pad_caps: Dict[Pad, Optional[Caps]] = field(default_factory=dict)
    findings: List[Finding] = field(default_factory=list)
    cyclic: Set[str] = field(default_factory=set)  # element names in cycles
    order: List[Element] = field(default_factory=list)

    def in_caps(self, elem: Element) -> Dict[str, Optional[Caps]]:
        """Per-sink-pad caps seen by *elem* (peer's inferred output)."""
        out: Dict[str, Optional[Caps]] = {}
        for pname, pad in elem.sink_pads.items():
            out[pname] = (self.pad_caps.get(pad.peer)
                          if pad.peer is not None else None)
        return out

    def out_caps(self, elem: Element) -> Dict[str, Optional[Caps]]:
        return {pname: self.pad_caps.get(pad)
                for pname, pad in elem.src_pads.items()}

    def in_config(self, elem: Element) -> Optional[TensorsConfig]:
        """Tensor config on a single-sink element's input, else None.
        Convenience shared by lint rules and the fusion planner."""
        caps = self.in_caps(elem)
        if len(caps) != 1:
            return None
        return config_of(next(iter(caps.values())))

    def out_config(self, elem: Element) -> Optional[TensorsConfig]:
        caps = self.out_caps(elem)
        if len(caps) != 1:
            return None
        return config_of(next(iter(caps.values())))


def element_transfer(
        elem: Element, in_caps: Dict[str, Optional[Caps]],
        findings: Optional[List[Finding]] = None,
) -> Dict[str, Optional[Caps]]:
    """Invoke *elem*'s declared :meth:`Element.static_transfer` under the
    shared error discipline. This is the single call site contract —
    pipelint propagation, the fusion rules, and the fusion planner all
    go through here, so each element declares its transfer exactly once
    and every consumer maps its failures the same way: TransferError /
    ValueError become findings (when a sink is passed), anything else is
    a lint bug and degrades to unknown."""
    try:
        return elem.static_transfer(in_caps) or {}
    except TransferError as exc:
        if findings is not None:
            findings.append(Finding(
                RULE_CAPS, Severity.ERROR, str(exc), elem.name, exc.pad))
        return {}
    except ValueError as exc:
        # the same error runtime negotiation would raise mid-stream
        if findings is not None:
            pad = (next(iter(elem.sink_pads))
                   if len(elem.sink_pads) == 1 else None)
            findings.append(Finding(
                RULE_CAPS, Severity.ERROR, str(exc), elem.name, pad))
        return {}
    except Exception:  # noqa: BLE001 -- never block launch on a lint bug
        logger.debug("pipelint: %s.static_transfer failed; treating "
                     "outputs as unknown", elem.name, exc_info=True)
        return {}


def _topo_order(elements: List[Element]):
    """Kahn's algorithm over pad links. Returns (order, cyclic_names):
    elements never reaching indegree 0 sit on (or downstream of) a
    cycle and are excluded from propagation."""
    indeg = {e.name: 0 for e in elements}
    for e in elements:
        for pad in e.sink_pads.values():
            if pad.peer is not None:
                indeg[e.name] += 1
    ready = [e for e in elements if indeg[e.name] == 0]
    order: List[Element] = []
    while ready:
        e = ready.pop(0)
        order.append(e)
        for pad in e.src_pads.values():
            if pad.peer is not None:
                down = pad.peer.element
                indeg[down.name] -= 1
                if indeg[down.name] == 0:
                    ready.append(down)
    done = {e.name for e in order}
    cyclic = {e.name for e in elements if e.name not in done}
    return order, cyclic


def infer_caps(pipeline) -> InferenceResult:
    """Run declared-transfer propagation over ``pipeline``'s graph."""
    elements = list(pipeline.elements.values())
    order, cyclic = _topo_order(elements)
    res = InferenceResult(cyclic=cyclic, order=order)
    for elem in order:
        out = element_transfer(elem, res.in_caps(elem), res.findings)
        for pname, pad in elem.src_pads.items():
            res.pad_caps[pad] = out.get(pname)
    return res
