"""Finding/report model for racecheck.

Where pipelint findings pin to an element/pad of one pipeline,
racecheck findings pin to ``file:line`` of the codebase itself. The
exit-code contract also differs: concurrency findings have no benign
tier, so ANY live finding fails the gate (0 clean / 1 findings /
2 usage error).
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import List, Optional, Set, Tuple

# finding classes (the ``rule`` field)
UNGUARDED_WRITE = "unguarded-shared-write"
LOCK_ORDER_CYCLE = "lock-order-cycle"
BLOCKING_UNDER_LOCK = "blocking-under-lock"
SLEEP_UNDER_LOCK = "sleep-under-lock"


@dataclass(frozen=True)
class RaceFinding:
    rule: str
    file: str
    line: int
    message: str
    cls: Optional[str] = None       # owning class, e.g. "Element"
    attr: Optional[str] = None      # attribute or lock name involved
    roles: Tuple[str, ...] = ()     # thread roles that collide

    @property
    def location(self) -> str:
        return f"{self.file}:{self.line}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "location": self.location, "class": self.cls,
                "attr": self.attr, "roles": list(self.roles),
                "message": self.message}

    def __str__(self) -> str:
        return f"{self.rule:22s} {self.location}: {self.message}"


@dataclass
class RaceReport:
    findings: List[RaceFinding] = field(default_factory=list)
    suppressed: List[RaceFinding] = field(default_factory=list)
    num_classes: int = 0
    num_files: int = 0
    # the static lock-order graph, for the runtime validator cross-check
    lock_edges: Set[Tuple[str, str]] = field(default_factory=set)

    def by_rule(self, rule: str) -> List[RaceFinding]:
        return [f for f in self.findings if f.rule == rule]

    @property
    def exit_code(self) -> int:
        """0 clean / 1 findings (suppressions don't count) — the CLI
        maps usage errors to 2 before analysis ever runs."""
        return 1 if self.findings else 0

    def to_text(self, verbose: bool = False) -> str:
        lines = [str(f) for f in sorted(
            self.findings, key=lambda f: (f.rule, f.file, f.line))]
        if verbose:
            lines += [f"suppressed {f}" for f in sorted(
                self.suppressed, key=lambda f: (f.file, f.line))]
        lines.append(
            f"racecheck: {len(self.findings)} finding(s) "
            f"({len(self.suppressed)} suppressed) in {self.num_classes} "
            f"class(es) across {self.num_files} file(s); "
            f"lock-order graph has {len(self.lock_edges)} edge(s)")
        return "\n".join(lines)

    def to_json(self) -> str:
        return json.dumps({
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [f.to_dict() for f in self.suppressed],
            "classes": self.num_classes, "files": self.num_files,
            "lock_order_edges": sorted(list(e) for e in self.lock_edges),
            "exit_code": self.exit_code,
        }, indent=2)
