"""The racecheck analysis passes: lockset, lock-order, blocking.

Lockset (Eraser, Savage et al. SOSP '97, adapted)
-------------------------------------------------
For each class attribute with post-init accesses: if accesses span >=2
live thread roles and at least one is a write, every WRITE must hold a
common lock. Reads are exempt (CPython attribute loads are GIL-atomic
reference reads; a reader sees the old or the new object, never a torn
one), and so is single-writer publication: plain ``self.x = value``
stores all coming from ONE role (the classic publish-then-read flag
pattern). Read-modify-writes (``+=``, ``d[k] = d[k] + 1``, container
mutators) never qualify for the exemption — lost updates are exactly
what this pass exists to catch.

Lock-order
----------
``with self._a:`` nested (lexically or through intra-class calls and
typed-attribute calls) inside ``with self._b:`` adds the edge
``Cls._b -> Cls._a``. A cycle in the resulting graph is a potential
deadlock: two threads can interleave the two orders.

Blocking-under-lock
-------------------
Intra-procedural: a call that can block indefinitely (``time.sleep``,
socket ``recv``/``accept``/``connect``, zero-arg ``queue.get()``,
zero-arg ``Thread.join()``, untimed ``Event.wait()``, model
``invoke``) issued while a ``with self._lock`` is lexically held.
``cond.wait()`` on the held condition itself is exempt — waiting
releases it. Interprocedural holds (a helper that blocks, called with
a lock held) are NOT tracked; keep blocking helpers out of critical
sections or suppress with an explicit pragma.
"""
from __future__ import annotations

from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .findings import (BLOCKING_UNDER_LOCK, LOCK_ORDER_CYCLE,
                       SLEEP_UNDER_LOCK, UNGUARDED_WRITE, RaceFinding,
                       RaceReport)
from .model import API, Access, Model, live_roles, roles_of


def _emit(report: RaceReport, model: Model, finding: RaceFinding) -> None:
    reason = model.pragma_reason(finding.file, finding.line)
    if reason is not None:
        report.suppressed.append(finding)
    else:
        report.findings.append(finding)


# -- lockset pass ----------------------------------------------------------

def _entry_locks(model: Model, cls_name: str) -> Dict[str, FrozenSet[str]]:
    """Locks provably held at ENTRY of each method: the intersection
    over every intra-class call site of (locks lexically held there +
    the caller's own entry locks). This is what keeps a helper like
    ``_try_endpoint`` — only ever called inside ``with
    self._connect_mutex`` — from looking unguarded. Methods that are
    also callable from outside the class (anything public, plus
    recursion cycles) conservatively get the empty set."""
    eff = model.effective_methods(cls_name)
    sites: Dict[str, List[Tuple[str, FrozenSet[str]]]] = \
        {name: [] for name in eff}
    for m in eff.values():
        for call in m.calls:
            if call.attr is None and call.callee in sites:
                sites[call.callee].append((m.name, call.locks))
    entry: Dict[str, Optional[FrozenSet[str]]] = {}
    for name in eff:
        # public methods are external entry points regardless of
        # internal call sites; purely-internal helpers start unknown
        if not sites[name] or not name.startswith("_"):
            entry[name] = frozenset()
        else:
            entry[name] = None
    changed = True
    while changed:
        changed = False
        for name in eff:
            if entry[name] is not None and not sites[name]:
                continue
            if entry[name] == frozenset() and not name.startswith("_"):
                continue
            acc: Optional[FrozenSet[str]] = None
            unknown = False
            for caller, locks in sites[name]:
                ce = entry.get(caller)
                if ce is None:
                    unknown = True
                    break
                held = locks | ce
                acc = held if acc is None else (acc & held)
            if unknown or acc is None:
                continue
            if acc != entry[name]:
                entry[name] = acc
                changed = True
    return {n: (e if e is not None else frozenset())
            for n, e in entry.items()}


def lockset_pass(model: Model, report: RaceReport) -> None:
    # public attrs written post-init anywhere: targets for foreign reads
    foreign_by_attr: Dict[str, List] = {}
    for fa in model.foreign:
        if fa.kind == "read":
            foreign_by_attr.setdefault(fa.attr, []).append(fa)

    # role table per accessing class, for foreign-access role lookup
    role_cache: Dict[str, Dict[str, Set[str]]] = {}

    def roles_for(cls_name: Optional[str], method: str) -> Set[str]:
        if cls_name is None or cls_name not in model.classes:
            return {API}
        if cls_name not in role_cache:
            role_cache[cls_name] = roles_of(model, cls_name)
        return role_cache[cls_name].get(method, {API})

    for cls_name, cls in model.classes.items():
        if cls_name not in role_cache:
            role_cache[cls_name] = roles_of(model, cls_name)
        roles = role_cache[cls_name]
        safe = {a for a, t in model.effective_attr_types(cls_name).items()
                if _is_safe_type(t)}
        entry = _entry_locks(model, cls_name)
        # own accesses grouped by attribute, lifecycle methods excluded
        by_attr: Dict[str, List] = {}
        for m in cls.methods.values():
            mroles = live_roles(roles.get(m.name, {API}))
            if not mroles:          # init-only method: quiescent
                continue
            held_at_entry = entry.get(m.name, frozenset())
            for acc in m.accesses:
                if acc.attr in safe:
                    continue
                if held_at_entry:
                    acc = Access(attr=acc.attr, kind=acc.kind,
                                 lineno=acc.lineno,
                                 locks=acc.locks | held_at_entry,
                                 method=acc.method)
                by_attr.setdefault(acc.attr, []).append((acc, mroles))

        for attr, accs in sorted(by_attr.items()):
            writes = [(a, r) for a, r in accs if a.is_write]
            if not writes:
                continue
            all_roles: Set[str] = set()
            for _, r in accs:
                all_roles |= r
            if not attr.startswith("_"):
                for fa in foreign_by_attr.get(attr, ()):
                    if fa.cls == cls_name:
                        continue    # same-class helper, already counted
                    all_roles |= live_roles(roles_for(fa.cls, fa.method))
            if len(all_roles) < 2:
                continue
            common: Optional[FrozenSet[str]] = None
            for a, _ in writes:
                common = a.locks if common is None else common & a.locks
            if common:
                continue            # every write shares a guard
            write_roles: Set[str] = set()
            for _, r in writes:
                write_roles |= r
            if all(a.kind == "store" for a, _ in writes) \
                    and len(write_roles) <= 1:
                continue            # single-writer publication
            worst = next((a for a, _ in writes if not a.locks), writes[0][0])
            _emit(report, model, RaceFinding(
                rule=UNGUARDED_WRITE, file=cls.file, line=worst.lineno,
                cls=cls_name, attr=attr,
                roles=tuple(sorted(all_roles)),
                message=(f"{cls_name}.{attr} written in "
                         f"{cls_name}.{worst.method}() without a "
                         f"consistent lock, but accessed from roles "
                         f"{{{', '.join(sorted(all_roles))}}}")))


def _is_safe_type(type_name: str) -> bool:
    from .model import SAFE_TYPES
    return type_name in SAFE_TYPES


# -- lock-order pass -------------------------------------------------------

def _locks_acquired(model: Model) -> Dict[Tuple[str, str], Set[str]]:
    """(class, method) -> qualified lock names the call may acquire,
    transitively through self-calls and typed-attribute calls."""
    acq: Dict[Tuple[str, str], Set[str]] = {}
    for cls_name, cls in model.classes.items():
        types = model.effective_attr_types(cls_name)
        for m in cls.methods.values():
            own = {f"{cls_name}.{a.lock}" for a in m.acquisitions}
            acq[(cls_name, m.name)] = own
    changed = True
    while changed:
        changed = False
        for cls_name, cls in model.classes.items():
            types = model.effective_attr_types(cls_name)
            eff = model.effective_methods(cls_name)
            for m in cls.methods.values():
                mine = acq[(cls_name, m.name)]
                before = len(mine)
                for call in m.calls:
                    target: Optional[Tuple[str, str]] = None
                    if call.attr is None:
                        callee = eff.get(call.callee)
                        if callee is not None:
                            target = (callee.cls_name, call.callee)
                    else:
                        tname = types.get(call.attr.split(".")[0])
                        if tname in model.classes and \
                                call.callee in model.classes[tname].methods:
                            target = (tname, call.callee)
                    if target and target in acq:
                        mine |= acq[target]
                if len(mine) != before:
                    changed = True
    return acq


def lock_order_pass(model: Model, report: RaceReport) -> None:
    acq = _locks_acquired(model)
    # edge -> example (file, line) where it is created
    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    for cls_name, cls in model.classes.items():
        types = model.effective_attr_types(cls_name)
        eff = model.effective_methods(cls_name)
        for m in cls.methods.values():
            for a in m.acquisitions:
                inner = f"{cls_name}.{a.lock}"
                for held in a.held:
                    outer = f"{cls_name}.{held}"
                    if outer != inner:
                        edges.setdefault((outer, inner),
                                         (cls.file, a.lineno))
            for call in m.calls:
                if not call.locks:
                    continue
                target: Optional[Tuple[str, str]] = None
                if call.attr is None:
                    callee = eff.get(call.callee)
                    if callee is not None:
                        target = (callee.cls_name, call.callee)
                else:
                    tname = types.get(call.attr.split(".")[0])
                    if tname in model.classes and \
                            call.callee in model.classes[tname].methods:
                        target = (tname, call.callee)
                if not target:
                    continue
                for inner in acq.get(target, ()):
                    for held in call.locks:
                        outer = f"{cls_name}.{held}"
                        if outer != inner:
                            edges.setdefault((outer, inner),
                                             (cls.file, call.lineno))

    report.lock_edges = set(edges)

    for cycle in find_cycles(set(edges)):
        first = min(cycle)
        idx = cycle.index(first)
        ordered = cycle[idx:] + cycle[:idx]
        file, line = edges[(ordered[0], ordered[1 % len(ordered)])]
        chain = " -> ".join(ordered + (ordered[0],))
        _emit(report, model, RaceFinding(
            rule=LOCK_ORDER_CYCLE, file=file, line=line,
            cls=ordered[0].split(".")[0], attr=ordered[0],
            message=(f"lock-order cycle {chain}: two threads taking "
                     f"these locks in different orders can deadlock")))


def find_cycles(edges: Set[Tuple[str, str]]) -> List[Tuple[str, ...]]:
    """Elementary cycles in a small digraph (DFS back-edge walk; each
    cycle reported once, rotation-normalized)."""
    graph: Dict[str, List[str]] = {}
    for src, dst in edges:
        graph.setdefault(src, []).append(dst)
    seen_cycles: Set[Tuple[str, ...]] = set()
    out: List[Tuple[str, ...]] = []

    def dfs(node: str, path: List[str], on_path: Set[str],
            visited: Set[str]) -> None:
        for nxt in graph.get(node, ()):
            if nxt in on_path:
                cyc = tuple(path[path.index(nxt):])
                idx = cyc.index(min(cyc))
                norm = cyc[idx:] + cyc[:idx]
                if norm not in seen_cycles:
                    seen_cycles.add(norm)
                    out.append(norm)
            elif nxt not in visited:
                path.append(nxt)
                on_path.add(nxt)
                dfs(nxt, path, on_path, visited)
                on_path.discard(nxt)
                path.pop()
        visited.add(node)

    visited: Set[str] = set()
    for start in sorted(graph):
        if start not in visited:
            dfs(start, [start], {start}, visited)
    return out


# -- blocking pass ---------------------------------------------------------

def blocking_pass(model: Model, report: RaceReport) -> None:
    units: List[Tuple[Optional[str], object, str]] = []
    for cls_name, cls in model.classes.items():
        for m in cls.methods.values():
            units.append((cls_name, m, cls.file))
    for fn in model.functions:
        units.append((None, fn, fn.file))

    for cls_name, m, file in units:
        for b in m.blocking:
            held = ", ".join(
                f"{cls_name}.{l}" if cls_name else l
                for l in sorted(b.locks))
            where = f"{cls_name}.{m.name}" if cls_name else m.name
            rule = SLEEP_UNDER_LOCK if b.rule == "sleep-under-lock" \
                else BLOCKING_UNDER_LOCK
            _emit(report, model, RaceFinding(
                rule=rule, file=file, line=b.lineno, cls=cls_name,
                attr=next(iter(sorted(b.locks)), None),
                message=(f"{where}() calls {b.what} while holding "
                         f"{held}: blocks every thread contending for "
                         f"the lock")))


def run_passes(model: Model) -> RaceReport:
    report = RaceReport(num_classes=len(model.classes),
                        num_files=model.num_files)
    lockset_pass(model, report)
    lock_order_pass(model, report)
    blocking_pass(model, report)
    report.findings.sort(key=lambda f: (f.rule, f.file, f.line))
    return report


def analyze_paths(paths: Sequence[str]) -> RaceReport:
    from .model import scan_paths
    return run_passes(scan_paths(paths))
