"""Opt-in runtime lock instrumentation: the racecheck witness.

The static lock-order graph is an over-approximation; this module
records what actually happens. Wrap an object's locks with
``instrument_object(obj, monitor)`` and run the test suite: the
monitor records every acquisition edge (lock A held while taking
lock B, per thread) and, optionally via the Counters hook, the lock
names held at each counter mutation. Afterwards
``monitor.check_against_static(static_edges)`` asserts

* the RECORDED graph is acyclic (no run ever witnessed a deadlockable
  order), and
* every recorded edge is present in the static graph (the static pass
  did not miss an ordering the runtime exercised).

Wrappers keep lock semantics exact: ``TracedLock`` delegates to a real
``threading.Lock``; ``TracedCondition`` wraps a real Condition —
``wait()`` needs no stack surgery because a blocked thread performs no
acquisitions, so its held-stack stays truthful for the edges IT
creates.
"""
from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Optional, Set, Tuple


class LockMonitor:
    """Collects acquisition-order edges from traced locks."""

    def __init__(self):
        self._tls = threading.local()
        self._mu = threading.Lock()
        self.edges: Dict[Tuple[str, str], int] = {}
        self.acquisitions: Dict[str, int] = {}

    # -- called by the wrappers -------------------------------------------
    def _stack(self) -> List[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def note_acquired(self, name: str) -> None:
        stack = self._stack()
        with self._mu:
            self.acquisitions[name] = self.acquisitions.get(name, 0) + 1
            for held in stack:
                if held != name:
                    key = (held, name)
                    self.edges[key] = self.edges.get(key, 0) + 1
        stack.append(name)

    def note_released(self, name: str) -> None:
        stack = self._stack()
        # out-of-order release is legal for plain locks: remove the
        # newest matching entry rather than assuming LIFO
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    # -- verdicts ----------------------------------------------------------
    def edge_set(self) -> Set[Tuple[str, str]]:
        return set(self.edges)

    def find_cycles(self) -> List[Tuple[str, ...]]:
        from .passes import find_cycles
        return find_cycles(self.edge_set())

    def check_against_static(
            self, static_edges: Iterable[Tuple[str, str]]
    ) -> Tuple[List[Tuple[str, ...]], Set[Tuple[str, str]]]:
        """(cycles, edges the static graph missed) — both empty on a
        clean run."""
        cycles = self.find_cycles()
        missed = self.edge_set() - set(static_edges)
        return cycles, missed


class TracedLock:
    """A ``threading.Lock`` that reports acquisitions to a monitor."""

    def __init__(self, name: str, monitor: LockMonitor,
                 inner: Optional[object] = None):
        self.name = name
        self.monitor = monitor
        self._inner = inner if inner is not None else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self.monitor.note_acquired(self.name)
        return got

    def release(self) -> None:
        self.monitor.note_released(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "TracedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class TracedCondition:
    """A ``threading.Condition`` reporting its underlying-lock
    acquisitions. ``wait()`` keeps the name on the thread's stack: the
    blocked thread acquires nothing while waiting, and on wakeup it
    holds the lock again — exactly what the stack says."""

    def __init__(self, name: str, monitor: LockMonitor,
                 inner: Optional[threading.Condition] = None):
        self.name = name
        self.monitor = monitor
        self._inner = inner if inner is not None else threading.Condition()

    def acquire(self, *args) -> bool:
        got = self._inner.acquire(*args)
        if got:
            self.monitor.note_acquired(self.name)
        return got

    def release(self) -> None:
        self.monitor.note_released(self.name)
        self._inner.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._inner.wait(timeout)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        return self._inner.wait_for(predicate, timeout)

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()

    def __enter__(self) -> "TracedCondition":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


_LOCK_TYPE = type(threading.Lock())
_RLOCK_TYPE = type(threading.RLock())


def instrument_object(obj, monitor: LockMonitor,
                      cls_name: Optional[str] = None) -> List[str]:
    """Replace every Lock/RLock/Condition attribute of ``obj`` with a
    traced wrapper named ``ClassName.attr`` — matching the static
    graph's node names, so recorded edges are directly comparable.
    Returns the wrapped names."""
    cls_name = cls_name or type(obj).__name__
    wrapped: List[str] = []
    for attr in list(vars(obj)):
        value = getattr(obj, attr)
        name = f"{cls_name}.{attr}"
        if isinstance(value, (TracedLock, TracedCondition)):
            continue
        if isinstance(value, threading.Condition):
            setattr(obj, attr, TracedCondition(name, monitor, value))
            wrapped.append(name)
        elif isinstance(value, (_LOCK_TYPE, _RLOCK_TYPE)):
            setattr(obj, attr, TracedLock(name, monitor, value))
            wrapped.append(name)
    return wrapped


def instrument_counters(counters, monitor: LockMonitor) -> str:
    """Trace a :class:`~...utils.atomic.Counters` leaf lock under the
    canonical ``Counters._lock`` node name."""
    name = "Counters._lock"
    inner = object.__getattribute__(counters, "_lock")
    if not isinstance(inner, TracedLock):
        object.__setattr__(counters, "_lock",
                           TracedLock(name, monitor, inner))
    return name
