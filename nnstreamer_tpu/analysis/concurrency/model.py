"""AST scan + thread-role model for racecheck.

This module turns Python sources into the facts the passes consume —
no code is ever executed:

* per class: methods, base classes, attribute type assignments
  (``self.x = ClassName(...)``), safe-typed attributes (locks, queues,
  events, Counters — objects that synchronize internally);
* per method: ``self`` attribute accesses (read / plain store /
  read-modify-write) each annotated with the locks lexically held,
  nested ``with self._lock`` acquisitions, ``self.*()`` calls,
  ``threading.Thread/Timer`` spawn targets, and potentially blocking
  calls with the locks held at the call site;
* foreign accesses: ``x.attr`` reads of PUBLIC attributes of other
  objects (how ``Pipeline.stats()`` reading every element's counters
  contributes the user-thread role to each element's lockset).

Thread roles
------------
Each method of each class is classified by the thread(s) that execute
it. Roles are seeded at known entry points (``Element.chain``,
``SrcElement._loop``, the fault supervisor, watchdog/timer callbacks,
scheduler flush workers, network reader loops — plus any method passed
as ``threading.Thread(target=self.m)``) and propagated to callees
through intra-class ``self.*()`` calls to a fixpoint. A method with no
role after propagation defaults to ``api`` (the user thread). Lifecycle
methods (``__init__``/``start``/``stop``/...) carry the quiescent
``init`` pseudo-role: ``Pipeline.start()`` orders them strictly
before/after the streaming threads, so their accesses cannot race and
the role is dropped when locksets are evaluated.
"""
from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

PRAGMA_RE = re.compile(r"#\s*racecheck:\s*ok\(([^)]*)\)")

# -- thread roles ----------------------------------------------------------
API = "api"                  # the user thread (default)
CHAIN = "chain"              # buffer chain path (possibly fan-in)
SOURCE = "source-loop"       # supervised src streaming thread
TIMER = "timer"              # watchdog / breaker half-open timers
NET = "net-reader"           # accept loops + per-client reader threads
WORKER = "worker"            # scheduler/batcher flush threads
DISPATCHER = "dispatcher"    # overlap window: chain-side frame dispatch
COMPLETER = "completer"      # overlap window: per-element completer
UPLOADER = "uploader"        # coalescing H2D upload service thread
SCRAPER = "scraper"          # obs metrics endpoint serve/handle threads
INIT = "init"                # quiescent lifecycle (dropped in locksets)

# (ancestor class, method name) -> role: known entry points. Applied to
# every class that inherits the method.
DEFAULT_SEEDS: List[Tuple[str, str, str]] = [
    ("Element", "chain", CHAIN),
    ("Element", "handle_event", CHAIN),
    ("Element", "handle_upstream_event", CHAIN),
    ("SrcElement", "_loop", SOURCE),
    ("Supervisor", "run", SOURCE),
    ("Supervisor", "handle", SOURCE),
    ("Supervisor", "ok", SOURCE),
    ("Watchdog", "_loop", TIMER),
    ("TensorFilter", "_on_idle", TIMER),
    # async overlapped executor (elements/overlap.py): the chain thread
    # dispatches into the window, a dedicated thread completes frames
    ("OverlapExecutor", "submit", DISPATCHER),
    ("OverlapExecutor", "_complete_loop", COMPLETER),
    ("TensorFilter", "_complete_frame", COMPLETER),
    ("TensorFilter", "_complete_error", COMPLETER),
    ("FusedSegment", "_complete_frame", COMPLETER),
    ("FusedSegment", "_complete_error", COMPLETER),
    # bidirectional transfer service (tensors/transfer.py)
    ("_Uploader", "_run", UPLOADER),
    # obs telemetry plane (obs/server.py): the pull endpoint's accept
    # loop + per-request handlers run off the pipeline threads entirely
    ("MetricsServer", "_serve_loop", SCRAPER),
    ("MetricsServer", "_handle", SCRAPER),
]

# methods whose accesses are ordered by the pipeline lifecycle
# (Pipeline.start()/stop() run them strictly before/after streaming;
# "create" is the framework-subplugin open hook — the SrcElement
# per-buffer create() keeps its source-loop role through propagation)
LIFECYCLE = {"__init__", "start", "stop", "close", "destroy", "open",
             "shutdown", "create", "__del__"}

# attribute types that synchronize internally — accesses are skipped
SAFE_TYPES = {"Lock", "RLock", "Condition", "Event", "Semaphore",
              "BoundedSemaphore", "Barrier", "Queue", "SimpleQueue",
              "LifoQueue", "PriorityQueue", "local", "Counters"}

# method names that mutate their receiver (list/dict/set/deque/Counters)
MUTATORS = {"append", "appendleft", "extend", "insert", "remove", "pop",
            "popleft", "clear", "add", "discard", "update", "setdefault",
            "inc"}


def _dotted_self_attr(node: ast.AST) -> Optional[str]:
    """``self.a`` -> "a", ``self.a.b`` -> "a.b", else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self":
        return ".".join(reversed(parts)) or None
    return None


def _call_name(func: ast.AST) -> str:
    """Trailing name of a call target: ``time.sleep`` -> "sleep"."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


@dataclass
class Access:
    attr: str
    kind: str                      # "read" | "store" | "rmw"
    lineno: int
    locks: FrozenSet[str]          # self locks lexically held
    method: str

    @property
    def is_write(self) -> bool:
        return self.kind != "read"


@dataclass
class Acquire:
    lock: str                      # "a" or "a.b" (self-attr chain)
    lineno: int
    held: Tuple[str, ...]          # self locks already held at this site


@dataclass
class BlockingCall:
    what: str                      # e.g. "time.sleep", ".recv()"
    rule: str                      # sleep-under-lock | blocking-under-lock
    lineno: int
    locks: FrozenSet[str]


@dataclass
class CallSite:
    callee: str                    # method name for self.m(...)
    attr: Optional[str]            # attr name for self.attr.m(...)
    lineno: int
    locks: FrozenSet[str]


@dataclass
class ForeignAccess:
    attr: str
    kind: str                      # "read" | "store"
    lineno: int
    file: str
    cls: Optional[str]             # class of the accessing method
    method: str


@dataclass
class MethodInfo:
    name: str
    lineno: int
    cls_name: str
    file: str
    accesses: List[Access] = field(default_factory=list)
    acquisitions: List[Acquire] = field(default_factory=list)
    blocking: List[BlockingCall] = field(default_factory=list)
    calls: List[CallSite] = field(default_factory=list)
    spawn_targets: Set[str] = field(default_factory=set)
    timer_targets: Set[str] = field(default_factory=set)


@dataclass
class ClassInfo:
    name: str
    file: str
    lineno: int
    bases: List[str]
    methods: Dict[str, MethodInfo] = field(default_factory=dict)
    attr_types: Dict[str, str] = field(default_factory=dict)


@dataclass
class Model:
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    foreign: List[ForeignAccess] = field(default_factory=list)
    # module-level functions get blocking analysis too
    functions: List[MethodInfo] = field(default_factory=list)
    pragmas: Dict[str, Dict[int, str]] = field(default_factory=dict)
    num_files: int = 0

    # -- hierarchy helpers -------------------------------------------------
    def ancestry(self, cls_name: str) -> List[str]:
        """cls_name + transitive base names resolvable in the model."""
        out, todo, seen = [], [cls_name], set()
        while todo:
            name = todo.pop(0)
            if name in seen:
                continue
            seen.add(name)
            out.append(name)
            info = self.classes.get(name)
            if info:
                todo.extend(info.bases)
        return out

    def effective_methods(self, cls_name: str) -> Dict[str, MethodInfo]:
        """name -> nearest definition walking the (name-resolved) MRO."""
        eff: Dict[str, MethodInfo] = {}
        for name in self.ancestry(cls_name):
            info = self.classes.get(name)
            if not info:
                continue
            for mname, m in info.methods.items():
                eff.setdefault(mname, m)
        return eff

    def effective_attr_types(self, cls_name: str) -> Dict[str, str]:
        types: Dict[str, str] = {}
        for name in self.ancestry(cls_name):
            info = self.classes.get(name)
            if not info:
                continue
            for attr, t in info.attr_types.items():
                types.setdefault(attr, t)
        return types

    def pragma_reason(self, file: str, lineno: int) -> Optional[str]:
        """``# racecheck: ok(reason)`` on the line or the line above."""
        table = self.pragmas.get(file, {})
        for ln in (lineno, lineno - 1):
            if ln in table:
                return table[ln]
        return None


class _MethodVisitor(ast.NodeVisitor):
    """Collects one method's facts, tracking the lexical with-lock stack.

    Only ``with self.<attr-chain>:`` items count as lock acquisitions —
    a with on a local variable can't be named in the class-level lock
    graph and is ignored (documented limitation)."""

    def __init__(self, info: MethodInfo):
        self.info = info
        self.stack: List[str] = []

    # -- helpers -----------------------------------------------------------
    def _locks(self) -> FrozenSet[str]:
        return frozenset(self.stack)

    def _record_access(self, attr: str, kind: str, lineno: int) -> None:
        self.info.accesses.append(Access(
            attr=attr, kind=kind, lineno=lineno, locks=self._locks(),
            method=self.info.name))

    def _record_foreign(self, model_sink: List[ForeignAccess],
                        attr: str, kind: str, lineno: int) -> None:
        pass  # foreign accesses are collected by the module visitor

    # -- with: lock acquisition --------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        acquired: List[str] = []
        for item in node.items:
            lock = _dotted_self_attr(item.context_expr)
            if lock is not None:
                self.info.acquisitions.append(Acquire(
                    lock=lock, lineno=item.context_expr.lineno,
                    held=tuple(self.stack)))
                self.stack.append(lock)
                acquired.append(lock)
            else:
                self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self.stack.pop()

    # -- assignments -------------------------------------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            self._visit_store_target(tgt)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._visit_store_target(node.target)
        if node.value is not None:
            self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        tgt = node.target
        attr = _dotted_self_attr(tgt) if isinstance(tgt, ast.Attribute) \
            else None
        if attr is not None:
            kind = "rmw" if "." not in attr else "read"
            self._record_access(attr.split(".")[0], kind, tgt.lineno)
        elif isinstance(tgt, ast.Subscript):
            inner = _dotted_self_attr(tgt.value)
            if inner is not None:
                # self.d[k] += 1: read-modify-write of the container;
                # self.a.b[k] += 1 mutates the FOREIGN object b, which
                # is only a read of our own attribute a
                kind = "rmw" if "." not in inner else "read"
                self._record_access(inner.split(".")[0], kind,
                                    tgt.lineno)
            else:
                self.visit(tgt.value)
            self.visit(tgt.slice)
        else:
            self.visit(tgt)
        self.visit(node.value)

    def _visit_store_target(self, tgt: ast.AST) -> None:
        if isinstance(tgt, ast.Attribute):
            attr = _dotted_self_attr(tgt)
            if attr is not None:
                kind = "store" if "." not in attr else "read"
                self._record_access(attr.split(".")[0], kind,
                                    tgt.lineno)
                return
        if isinstance(tgt, ast.Subscript):
            inner = _dotted_self_attr(tgt.value)
            if inner is not None:
                # self.d[k] = v mutates the container in place; on a
                # deeper chain the mutated object belongs elsewhere
                kind = "rmw" if "." not in inner else "read"
                self._record_access(inner.split(".")[0], kind,
                                    tgt.lineno)
                self.visit(tgt.slice)
                return
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                self._visit_store_target(elt)
            return
        self.visit(tgt)

    # -- calls -------------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        handled_receiver = False
        if isinstance(func, ast.Attribute):
            recv = _dotted_self_attr(func.value)
            if isinstance(func.value, ast.Name) and func.value.id == "self":
                # self.m(...): intra-class call
                self.info.calls.append(CallSite(
                    callee=func.attr, attr=None, lineno=node.lineno,
                    locks=self._locks()))
                handled_receiver = True
            elif recv is not None:
                # self.attr.m(...): cross-object call; a mutator method
                # is a write of the container attribute (but mutating
                # self.a.b mutates the foreign object b, which only
                # READS our own attribute a)
                base = recv.split(".")[0]
                kind = "rmw" if (func.attr in MUTATORS
                                 and "." not in recv) else "read"
                self._record_access(base, kind, node.lineno)
                self.info.calls.append(CallSite(
                    callee=func.attr, attr=recv, lineno=node.lineno,
                    locks=self._locks()))
                handled_receiver = True
        self._check_spawn(node)
        self._check_blocking(node)
        if not handled_receiver:
            self.visit(func)
        for arg in node.args:
            self.visit(arg)
        for kw in node.keywords:
            self.visit(kw.value)

    def _check_spawn(self, node: ast.Call) -> None:
        name = _call_name(node.func)
        if name == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    tgt = _dotted_self_attr(kw.value)
                    if tgt:
                        self.info.spawn_targets.add(tgt.split(".")[0])
        elif name == "Timer":
            for arg in list(node.args) + [kw.value for kw in node.keywords
                                          if kw.arg == "function"]:
                tgt = _dotted_self_attr(arg)
                if tgt:
                    self.info.timer_targets.add(tgt.split(".")[0])

    def _check_blocking(self, node: ast.Call) -> None:
        if not self.stack:
            return
        func = node.func
        name = _call_name(func)
        kwargs = {kw.arg for kw in node.keywords}
        lineno = node.lineno
        locks = self._locks()

        def hit(what: str, rule: str) -> None:
            self.info.blocking.append(BlockingCall(
                what=what, rule=rule, lineno=lineno, locks=locks))

        if name == "sleep":
            hit("sleep()", "sleep-under-lock")
        elif name in ("recv", "recv_msg", "accept", "connect",
                      "create_connection"):
            hit(f"{name}()", "blocking-under-lock")
        elif name == "get" and not node.args and "timeout" not in kwargs:
            # zero-arg .get(): queue.Queue.get() blocks forever;
            # dict.get(k) always has a positional arg and never matches
            hit(".get() without timeout", "blocking-under-lock")
        elif name == "join" and not node.args:
            # zero-arg .join(): Thread.join() blocks; str.join(seq)
            # always has an argument and never matches
            hit(".join()", "blocking-under-lock")
        elif name == "invoke":
            hit("model invoke()", "blocking-under-lock")
        elif name == "wait" and "timeout" not in kwargs and not node.args:
            # cond.wait() RELEASES the condition it is called on — only
            # flag when some OTHER lock stays held while blocked
            recv = _dotted_self_attr(func.value) \
                if isinstance(func, ast.Attribute) else None
            others = [l for l in self.stack if l != recv]
            if others:
                hit(".wait() without timeout", "blocking-under-lock")


class _ModuleVisitor:
    """Walks one module: classes, their methods, module functions, and
    foreign public-attribute accesses anywhere in the file."""

    def __init__(self, model: Model, file: str):
        self.model = model
        self.file = file

    def scan(self, tree: ast.Module) -> None:
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                self._scan_class(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(node, cls=None)
        self._scan_foreign(tree)

    def _scan_class(self, node: ast.ClassDef) -> None:
        bases = []
        for b in node.bases:
            if isinstance(b, ast.Name):
                bases.append(b.id)
            elif isinstance(b, ast.Attribute):
                bases.append(b.attr)
        info = ClassInfo(name=node.name, file=self.file,
                         lineno=node.lineno, bases=bases)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                minfo = MethodInfo(name=item.name, lineno=item.lineno,
                                   cls_name=node.name, file=self.file)
                visitor = _MethodVisitor(minfo)
                for stmt in item.body:
                    visitor.visit(stmt)
                info.methods[item.name] = minfo
                self._collect_attr_types(item, info)
        # first definition wins on a (rare) cross-module name collision
        self.model.classes.setdefault(node.name, info)

    def _scan_function(self, node: ast.FunctionDef,
                       cls: Optional[str]) -> None:
        minfo = MethodInfo(name=node.name, lineno=node.lineno,
                           cls_name=cls or "<module>", file=self.file)
        visitor = _MethodVisitor(minfo)
        for stmt in node.body:
            visitor.visit(stmt)
        self.model.functions.append(minfo)

    def _collect_attr_types(self, fn: ast.FunctionDef,
                            info: ClassInfo) -> None:
        """``self.x = ClassName(...)`` anywhere in the method body."""
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            tname = _call_name(node.value.func)
            if not tname:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute):
                    attr = _dotted_self_attr(tgt)
                    if attr and "." not in attr:
                        info.attr_types.setdefault(attr, tname)

    def _scan_foreign(self, tree: ast.Module) -> None:
        """Reads of PUBLIC attributes on non-self receivers, with the
        class+method context they occur in. Private attributes are
        skipped (cross-object private access is its own smell, but it
        cannot be bound to an owner by name alone), and so are
        receivers that name an import (``np.stack`` is a module
        function, not somebody's ``stack`` attribute)."""
        imported: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    imported.add(alias.asname or alias.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    imported.add(alias.asname or alias.name)

        def walk(node: ast.AST, cls: Optional[str], meth: str) -> None:
            if isinstance(node, ast.ClassDef):
                for child in node.body:
                    walk(child, node.name, meth)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for child in node.body:
                    walk(child, cls, node.name)
                return
            if isinstance(node, ast.Attribute):
                base_is_self = (isinstance(node.value, ast.Name)
                                and node.value.id == "self")
                if (not base_is_self and not node.attr.startswith("_")
                        and isinstance(node.value, ast.Name)
                        and node.value.id not in imported):
                    kind = "store" if isinstance(node.ctx, ast.Store) \
                        else "read"
                    self.model.foreign.append(ForeignAccess(
                        attr=node.attr, kind=kind, lineno=node.lineno,
                        file=self.file, cls=cls, method=meth))
            for child in ast.iter_child_nodes(node):
                walk(child, cls, meth)

        for node in tree.body:
            walk(node, None, "<module>")


def scan_paths(paths: Sequence[str]) -> Model:
    """Parse every ``.py`` under the given files/directories into one
    Model. Unparseable files are skipped (they are compileall's problem,
    not racecheck's)."""
    model = Model()
    files: List[Path] = []
    for p in paths:
        path = Path(p)
        if path.is_dir():
            files.extend(sorted(path.rglob("*.py")))
        elif path.suffix == ".py":
            files.append(path)
    seen: Set[Path] = set()
    for path in files:
        rp = path.resolve()
        if rp in seen:
            continue
        seen.add(rp)
        try:
            source = path.read_text(encoding="utf-8")
            tree = ast.parse(source)
        except (OSError, SyntaxError, UnicodeDecodeError):
            continue
        label = str(path)
        model.num_files += 1
        table: Dict[int, str] = {}
        for n, line in enumerate(source.splitlines(), 1):
            m = PRAGMA_RE.search(line)
            if m:
                table[n] = m.group(1).strip() or "unspecified"
        if table:
            model.pragmas[label] = table
        _ModuleVisitor(model, label).scan(tree)
    return model


# -- thread-role computation ----------------------------------------------

def _spawn_role(target: str, model: Model, cls_name: str) -> str:
    n = target.lower()
    if any(k in n for k in ("accept", "client", "recv", "listen",
                            "reader", "sub")):
        return NET
    if any(k in n for k in ("watch", "timer", "idle")):
        return TIMER
    # before the generic loop/stream bucket: _complete_loop is the
    # overlap completer, _run on an uploader is the H2D service
    if "complete" in n:
        return COMPLETER
    if "upload" in n:
        return UPLOADER
    if "loop" in n or "stream" in n:
        if "SrcElement" in model.ancestry(cls_name):
            return SOURCE
        return WORKER
    return WORKER


def roles_of(
    model: Model,
    cls_name: str,
    extra_seeds: Optional[List[Tuple[str, str, str]]] = None,
) -> Dict[str, Set[str]]:
    """method name -> roles, for the class viewed as concrete (its own
    + inherited methods resolved nearest-definition-first).

    ``extra_seeds`` lets sibling analyzers (jitcheck) graft additional
    (ancestor, method, role) entry points onto the same propagation
    without disturbing racecheck's defaults."""
    eff = model.effective_methods(cls_name)
    roles: Dict[str, Set[str]] = {name: set() for name in eff}
    ancestry = set(model.ancestry(cls_name))

    seeds = DEFAULT_SEEDS if not extra_seeds else DEFAULT_SEEDS + extra_seeds
    for base, meth, role in seeds:
        if base in ancestry and meth in roles:
            roles[meth].add(role)
    for name in roles:
        if name in LIFECYCLE:
            roles[name].add(INIT)
    for m in eff.values():
        for tgt in m.spawn_targets:
            if tgt in roles:
                roles[tgt].add(_spawn_role(tgt, model, cls_name))
        for tgt in m.timer_targets:
            if tgt in roles:
                roles[tgt].add(TIMER)

    changed = True
    while changed:
        changed = False
        for name, m in eff.items():
            mine = roles[name]
            if not mine:
                continue
            for call in m.calls:
                if call.attr is None and call.callee in roles:
                    before = len(roles[call.callee])
                    roles[call.callee] |= mine
                    if len(roles[call.callee]) != before:
                        changed = True

    for name in roles:
        if not roles[name]:
            roles[name] = {API}
    return roles


def live_roles(roles: Set[str]) -> Set[str]:
    """Roles that can actually race: the quiescent INIT role is dropped
    (lifecycle ordering, not locking, serializes those accesses)."""
    return {r for r in roles if r != INIT}
