"""racecheck — static concurrency analysis for the runtime itself.

pipelint (the sibling package) validates pipeline GRAPHS; racecheck
validates the CODE that executes them: an Eraser-style lockset pass
over a thread-role model, a lock-order graph with deadlock-cycle
detection, and a blocking-under-lock pass — plus an opt-in runtime
lock monitor that cross-checks the static graph against acquisitions
recorded while the test suite runs.

    from nnstreamer_tpu.analysis.concurrency import analyze_paths
    report = analyze_paths(["nnstreamer_tpu/"])
    assert report.exit_code == 0, report.to_text()

See Documentation/concurrency.md for the role model, the lock
hierarchy, and the ``# racecheck: ok(reason)`` suppression pragma.
"""
from .findings import (BLOCKING_UNDER_LOCK, LOCK_ORDER_CYCLE,
                       SLEEP_UNDER_LOCK, UNGUARDED_WRITE, RaceFinding,
                       RaceReport)
from .model import Model, roles_of, scan_paths
from .passes import analyze_paths, find_cycles, run_passes
from .runtime import (LockMonitor, TracedCondition, TracedLock,
                      instrument_counters, instrument_object)

__all__ = [
    "analyze_paths", "run_passes", "scan_paths", "roles_of",
    "find_cycles", "Model", "RaceFinding", "RaceReport",
    "UNGUARDED_WRITE", "LOCK_ORDER_CYCLE", "BLOCKING_UNDER_LOCK",
    "SLEEP_UNDER_LOCK", "LockMonitor", "TracedLock", "TracedCondition",
    "instrument_object", "instrument_counters",
]
