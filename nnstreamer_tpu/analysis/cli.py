"""``python -m nnstreamer_tpu lint "<description>"`` — the pipelint CLI.

Exit codes: 0 clean (info only), 1 warnings, 2 errors (parse failures
included). ``--json`` switches the report to machine-readable output.
"""
from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from .findings import Finding, Report, Severity


def lint_description(desc: str) -> Report:
    """Parse + analyze one launch description without starting it."""
    from .. import parse_launch  # full package: registers every element
    from .rules import analyze
    try:
        pipe = parse_launch(desc)
    except ValueError as exc:
        return Report(findings=[Finding(
            "parse", Severity.ERROR, str(exc))])
    return analyze(pipe)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m nnstreamer_tpu lint",
        description="Statically analyze a pipeline description: caps/"
                    "shape/dtype inference plus graph lint rules. "
                    "Nothing is executed.")
    ap.add_argument("description", help="gst-launch-style pipeline string")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress output; exit code only")
    args = ap.parse_args(argv)
    report = lint_description(args.description)
    if not args.quiet:
        print(report.to_json() if args.json else report.to_text())
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
