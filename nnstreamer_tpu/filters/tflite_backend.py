"""tensorflow-lite interop backend: .tflite models on the XLA path.

≙ ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc (the
reference's benchmark-baseline backend, 1825 LoC around the TFLite
interpreter + XNNPACK/GPU/NNAPI delegates). Here the model is imported
once (interop/tflite.py) into a jittable function, so "delegate" is
simply XLA on the chosen device — the same engine as the jax backend,
which is the point: interop formats converge on the MXU path.

Framework names: ``tensorflow-lite`` (canonical), aliases
``tensorflow2-lite`` / ``tflite`` match the reference's property values.
"""
from __future__ import annotations

from .interop_base import ImportedModelFilter
from .registry import register_alias, register_filter


def _load(path: str):
    from ..interop import tflite
    return tflite.load(path)


@register_filter
class TFLiteFilter(ImportedModelFilter):
    NAME = "tensorflow-lite"
    EXTENSIONS = (".tflite",)
    _load = staticmethod(_load)


register_alias("tensorflow2-lite", "tensorflow-lite")
register_alias("tflite", "tensorflow-lite")
